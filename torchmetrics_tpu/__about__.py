__version__ = "0.1.0"
__author__ = "TorchMetrics-TPU contributors"
__license__ = "Apache-2.0"
__docs__ = "TPU-native (JAX/XLA/Pallas) machine-learning metrics framework"
