# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Durable, atomic, self-verifying checkpoint store.

PR 2 made metric checkpoints *self-validating dicts*; this module makes them
*survive the process*. A :class:`CheckpointStore` owns one directory of
snapshots (see :mod:`~torchmetrics_tpu.robustness.store_format` for the
on-disk contract) and guarantees:

- **Atomicity** — every snapshot and every manifest update lands via
  temp-file + fsync + ``os.replace``; a preemption at ANY instruction leaves
  the store readable (a crash between temp and rename leaves debris the
  manifest never references — a "torn write").
- **Integrity** — each payload's CRC32 and byte count ride in the manifest;
  bitrot and truncation are detected at read time, not merged into results.
- **Monotonic recovery** — steps strictly increase, and :meth:`latest` walks
  newest→oldest, skipping torn/corrupt/missing/invalid snapshots with one
  named :class:`~torchmetrics_tpu.utilities.exceptions.CheckpointStoreWarning`
  each, returning the newest snapshot that passes BOTH the file-level checks
  and the caller's semantic validation (typically ``Metric.load_checkpoint``'s
  validate-all-then-apply, which raises ``StateRestoreError`` without
  half-restoring).
- **Rank-awareness** — on a multi-process ``jax.distributed`` group only
  ``write_rank`` (default process 0) persists; other ranks' :meth:`save`
  calls are no-ops, so replicated evaluations don't trample one directory.
  Pass ``write_rank=None`` (every rank writes — give each its own directory)
  for replica-regime metrics whose per-rank states differ.

Inspect a store without a Python process that can import jax with
``python tools/metricdoctor.py verify|list|prune <dir>``.
"""
from __future__ import annotations

import errno
import os
import pickle
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.robustness import store_format as _fmt
from torchmetrics_tpu.utilities.exceptions import CheckpointStoreWarning, StateRestoreError

__all__ = ["CheckpointStore"]


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


class CheckpointStore:
    """Atomic snapshot store for one evaluation's checkpoint payloads.

    Args:
        directory: store root; created on first write.
        keep_last: retention — after every save, only the newest ``keep_last``
            snapshots survive (``None`` keeps everything).
        fingerprint: optional PR-2 registry fingerprint pinned into the
            manifest; a later :meth:`save` or :meth:`latest` against a store
            written with a DIFFERENT fingerprint raises
            :class:`StateRestoreError` naming both (metric definition drift).
        write_rank: the ``jax.process_index()`` that persists snapshots
            (default 0); ``None`` makes every rank a writer.
    """

    def __init__(
        self,
        directory: str,
        keep_last: Optional[int] = 3,
        fingerprint: Optional[str] = None,
        write_rank: Optional[int] = 0,
    ) -> None:
        if keep_last is not None and keep_last < 1:
            raise ValueError(f"keep_last must be >= 1 (or None to keep everything), got {keep_last}")
        self.directory = str(directory)
        self.keep_last = keep_last
        self.fingerprint = fingerprint
        self.write_rank = write_rank

    # ------------------------------------------------------------------ misc
    @property
    def is_writer(self) -> bool:
        """Whether THIS process persists snapshots (rank-aware gate)."""
        return self.write_rank is None or _process_index() == self.write_rank

    def _manifest(self) -> Dict[str, Any]:
        manifest = _fmt.read_manifest(self.directory)
        if manifest is None:
            return _fmt.empty_manifest(self.fingerprint)
        if (
            self.fingerprint is not None
            and manifest["fingerprint"] is not None
            and manifest["fingerprint"] != self.fingerprint
        ):
            raise StateRestoreError(
                f"checkpoint store {self.directory} was written with registry fingerprint"
                f" {manifest['fingerprint']}, this evaluation declares {self.fingerprint} —"
                " the metric definition changed; start a fresh store directory"
            )
        return manifest

    def steps(self) -> List[int]:
        """Manifest snapshot steps, ascending (no file-level validation)."""
        return [int(e["step"]) for e in self._manifest()["snapshots"]]

    def last_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def verify(self) -> Dict[str, Any]:
        """Full integrity report (see :func:`store_format.verify_store`)."""
        return _fmt.verify_store(self.directory)

    # ------------------------------------------------------------------ save
    def save(self, payload: Dict[str, Any], step: int) -> Optional[str]:
        """Persist ``payload`` (a plain picklable dict) as the snapshot at
        ``step``; returns the file name, or ``None`` on non-writer ranks.

        Steps are strictly monotonic per store: saving at ``step <=`` the
        newest manifest step raises. The snapshot file is published before
        the manifest references it, so every manifest entry always points at
        a fully-written file.
        """
        if not self.is_writer:
            return None
        if _obs_trace.ENABLED:
            with _obs_trace.span("robustness.store.save", step=step):
                return self._save(payload, step)
        return self._save(payload, step)

    def _save(self, payload: Dict[str, Any], step: int) -> str:
        step = int(step)
        manifest = self._manifest()
        last = manifest["snapshots"][-1]["step"] if manifest["snapshots"] else None
        if last is not None and step <= int(last):
            raise ValueError(
                f"snapshot steps must be strictly monotonic: store {self.directory} is at"
                f" step {last}, refusing step {step}"
            )
        os.makedirs(self.directory, exist_ok=True)
        data = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        crc = _fmt.payload_crc(data)
        if faults._ACTIVE:
            # bitrot drill: the manifest records the TRUE crc, the file gets
            # the mangled bytes — exactly what at-rest corruption looks like
            data = faults.mutate_bytes("store.payload", data)
        name = _fmt.snapshot_filename(step)
        path = os.path.join(self.directory, name)
        # torn-write drill: crash between the temp write and the rename. The
        # real atomic_write keeps temp+rename inseparable, so the drill plants
        # the temp file itself and dies where a preempted process would.
        if faults._ACTIVE:
            try:
                faults.fire("store.write.torn")
            except BaseException:
                with open(path + ".tmp-torn", "wb") as fh:
                    fh.write(data)
                raise
            # disk-exhaustion drill: a full filesystem fails the write with
            # ENOSPC before anything lands — the degradation path's trigger
            try:
                faults.fire("store.write.enospc")
            except faults.FaultInjected as err:
                raise OSError(errno.ENOSPC, f"injected disk exhaustion: {err}") from None
        _fmt.atomic_write(path, data)
        manifest["snapshots"].append({"step": step, "file": name, "crc32": crc, "bytes": len(data)})
        if manifest["fingerprint"] is None:
            manifest["fingerprint"] = self.fingerprint
        # apply keep_last retention in memory BEFORE the single manifest
        # write (one fsync per save, not two), manifest-first so a crash
        # mid-unlink leaves unreferenced files, never dangling references
        victims: List[Dict[str, Any]] = []
        if self.keep_last is not None and len(manifest["snapshots"]) > self.keep_last:
            victims = manifest["snapshots"][: len(manifest["snapshots"]) - self.keep_last]
            manifest["snapshots"] = manifest["snapshots"][len(manifest["snapshots"]) - self.keep_last:]
        _fmt.write_manifest(self.directory, manifest)
        for entry in victims:
            try:
                os.unlink(os.path.join(self.directory, entry["file"]))
            except OSError:
                pass  # already gone — the manifest no longer references it
        # the store health counters also feed the live plane (obs/live.py):
        # fire when either recorder is on — still nothing on the default path
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_counters.inc("robustness.store.save")
            _obs_counters.set_gauge("robustness.store.snapshot_bytes", len(data))
        return name

    # ------------------------------------------------------------------ load
    def latest(
        self, validate: Optional[Callable[[Dict[str, Any]], None]] = None
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """The newest valid snapshot as ``(step, payload)``, or ``None``.

        Walks the manifest newest→oldest. A snapshot is skipped — with one
        :class:`CheckpointStoreWarning` naming the step and the defect — when
        its file is missing (deleted), its size/CRC32 disagree with the
        manifest (torn content, bitrot), it fails to unpickle, or the
        caller's ``validate(payload)`` hook raises ``StateRestoreError``
        (schema drift, truncated dict). The recovery ladder therefore never
        half-restores: it returns the newest snapshot that is valid END TO
        END, or ``None`` when none is.
        """
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_counters.inc("robustness.store.load")
        if _obs_trace.ENABLED:
            with _obs_trace.span("robustness.store.load"):
                return self._latest(validate)
        return self._latest(validate)

    def _latest(
        self, validate: Optional[Callable[[Dict[str, Any]], None]]
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        manifest = self._manifest()
        for entry in reversed(manifest["snapshots"]):
            step = int(entry["step"])
            try:
                data = _fmt.read_snapshot_bytes(self.directory, entry)
            except FileNotFoundError:
                self._skip(step, "manifest points at a deleted snapshot file")
                continue
            except (OSError, _fmt.StoreFormatError) as err:
                self._skip(step, str(err))
                continue
            try:
                payload = pickle.loads(data)
            except Exception as err:
                self._skip(step, f"payload does not unpickle ({type(err).__name__}: {err})")
                continue
            if not isinstance(payload, dict):
                self._skip(step, f"payload is a {type(payload).__name__}, expected a dict")
                continue
            if validate is not None:
                try:
                    validate(payload)
                except StateRestoreError as err:
                    self._skip(step, f"payload fails validation ({err})")
                    continue
            return step, payload
        return None

    def _skip(self, step: int, why: str) -> None:
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_counters.inc("robustness.store.recovery_skipped")
        warnings.warn(
            f"checkpoint store {self.directory}: skipping snapshot at step {step} — {why};"
            " falling back to the next-newest snapshot",
            CheckpointStoreWarning,
            stacklevel=3,
        )

    # ----------------------------------------------------------------- prune
    def prune(self, keep_last: Optional[int] = None) -> List[str]:
        """Drop snapshots beyond the newest ``keep_last`` (default: the
        store's own retention) plus any torn-write temp debris; returns the
        removed file names. No-op on non-writer ranks."""
        if not self.is_writer:
            return []
        keep = self.keep_last if keep_last is None else keep_last
        manifest = _fmt.read_manifest(self.directory)
        if manifest is None:
            return []
        _, removed = _fmt.prune_entries(self.directory, manifest, keep, drop_temp=True)
        return removed
