# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""StateGuard: in-program input sanitization and poison detection.

The reference validates inputs eagerly on host (``_input_validation``), which
a donated, ``lax.scan``-ingested pipeline cannot afford: one NaN/Inf or
out-of-range label in a single ``update()`` batch silently poisons
elementwise state forever. This module compiles the per-family **domain
contract** (finite, probs in [0, 1], labels < num_classes) *into* the update
step as fixed-shape masking, under one of three policies:

``propagate``
    Today's behavior — the batch is applied untouched; the guard only counts
    invalid rows and latches the poison probe if state goes non-finite.
``mask``
    Only valid rows are accumulated (one fresh per-row update, vmapped, then
    a segment-reduce that spills invalid rows — the ``parallel/sliced.py``
    cell fold, with validity as the cell). Invalid rows are counted, never
    applied.
``reject``
    Whole-batch veto: the candidate state is computed, then every leaf is
    ``where(batch_ok, new, old)``-selected, so an invalid batch leaves state
    bitwise untouched.

Every check is a fixed-shape device reduction — zero host sync, safe under
``jit``/``lax.scan``/donation/``SlicedPlan``. The verdict counters ride the
metric's own state registry (``guard_*`` states registered via
:meth:`~torchmetrics_tpu.metric.Metric.add_state`), so they checkpoint,
sync, fold and slice exactly like any other state.

The **poison probe** is one cheap in-program finiteness reduction over the
float state leaves, folded into the guarded update: corruption is detected
at the batch that caused it, not at ``compute()``. The serve plane
(``serve/stream.py``) reads the ``guard_poisoned`` latch per applied batch
and rolls back to its known-good ring.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

#: guard verdict counters registered on the metric by :func:`enable_guard`.
#: All scalars: int32 "sum" states except the "max" latch ``guard_poisoned``.
GUARD_STATES: Tuple[str, ...] = (
    "guard_nan_rows",
    "guard_inf_rows",
    "guard_domain_rows",
    "guard_masked_rows",
    "guard_rejected_batches",
    "guard_poisoned",
)

GUARD_POLICIES: Tuple[str, ...] = ("propagate", "mask", "reject")

#: array reductions the mask policy can fold row-decomposed states with
#: (mirrors ``parallel/sliced.py:_SLICEABLE_REDUCTIONS`` minus "merge")
_MASKABLE_REDUCTIONS = frozenset({"sum", "max", "min"})


class ArgSpec(NamedTuple):
    """Domain contract for one positional ``update`` argument.

    Checks are dtype-aware so one spec covers both prob/logit *and* label
    encodings of the same argument: ``lo``/``hi`` range checks apply only to
    floating inputs, ``values``/``num_classes`` membership checks only to
    integer inputs. Elements equal to ``ignore_index`` are exempt from the
    domain checks (they are sentinels, not data). Non-finite elements are
    flagged by ``finite`` and excluded from the domain count, so a NaN is
    never double-billed.
    """

    name: str = "arg"
    finite: bool = False
    lo: Optional[float] = None
    hi: Optional[float] = None
    num_classes: Optional[int] = None
    values: Optional[Tuple[int, ...]] = None
    ignore_index: Optional[int] = None


class GuardVerdict(NamedTuple):
    """Fixed-shape per-batch verdict — int32/bool device scalars that ride
    step outputs with zero host sync."""

    nan_rows: Array
    inf_rows: Array
    domain_rows: Array
    invalid_rows: Array
    batch_ok: Array


class DomainContract(NamedTuple):
    """Per-family input-domain contract: one :class:`ArgSpec` per positional
    ``update`` argument (extra arguments are unchecked)."""

    args: Tuple[ArgSpec, ...]
    family: str = ""

    def row_invalid(self, *batch: Any) -> Tuple[Array, Array, Array]:
        """Per-row (nan, inf, domain) violation masks, each bool ``(rows,)``.

        A "row" is an index along dim 0 of the batched arguments; trailing
        dims are flattened per row, so a single bad class score invalidates
        its whole sample.
        """
        rows = None
        for a in batch:
            a = jnp.asarray(a)
            if a.ndim >= 1:
                rows = a.shape[0]
                break
        if rows is None:
            raise ValueError("guard contract needs at least one batched (ndim >= 1) input")
        zeros = jnp.zeros((rows,), dtype=bool)
        nan_any, inf_any, dom_any = zeros, zeros, zeros
        for spec, a in zip(self.args, batch):
            a = jnp.asarray(a)
            if a.ndim == 0:
                continue
            flat = a.reshape((a.shape[0], -1))
            exempt = jnp.zeros_like(flat, dtype=bool)
            if spec.ignore_index is not None:
                exempt = flat == spec.ignore_index
            if jnp.issubdtype(flat.dtype, jnp.floating):
                nonfinite_nan = jnp.isnan(flat) & ~exempt
                nonfinite_inf = jnp.isinf(flat) & ~exempt
                if spec.finite:
                    nan_any = nan_any | jnp.any(nonfinite_nan, axis=1)
                    inf_any = inf_any | jnp.any(nonfinite_inf, axis=1)
                bad = jnp.zeros_like(flat, dtype=bool)
                if spec.lo is not None:
                    bad = bad | (flat < spec.lo)
                if spec.hi is not None:
                    bad = bad | (flat > spec.hi)
                # integer membership checks also apply to float-encoded labels
                # (a JSON frame with one NaN floats the whole target array)
                if spec.values is not None and spec.lo is None and spec.hi is None:
                    ok = jnp.zeros_like(flat, dtype=bool)
                    for v in spec.values:
                        ok = ok | (flat == v)
                    bad = bad | ~ok
                if spec.num_classes is not None and flat.ndim == 2 and a.ndim == 1:
                    bad = bad | (flat < 0) | (flat >= spec.num_classes)
                bad = bad & jnp.isfinite(flat) & ~exempt
                dom_any = dom_any | jnp.any(bad, axis=1)
            else:
                bad = jnp.zeros_like(flat, dtype=bool)
                if spec.values is not None:
                    ok = jnp.zeros_like(flat, dtype=bool)
                    for v in spec.values:
                        ok = ok | (flat == v)
                    bad = bad | ~ok
                elif spec.num_classes is not None:
                    bad = bad | (flat < 0) | (flat >= spec.num_classes)
                bad = bad & ~exempt
                dom_any = dom_any | jnp.any(bad, axis=1)
        return nan_any, inf_any, dom_any

    def check_batch(self, *batch: Any) -> GuardVerdict:
        """Compile the contract over one batch into a :class:`GuardVerdict`."""
        nan_any, inf_any, dom_any = self.row_invalid(*batch)
        invalid = nan_any | inf_any | dom_any
        return GuardVerdict(
            nan_rows=jnp.sum(nan_any).astype(jnp.int32),
            inf_rows=jnp.sum(inf_any).astype(jnp.int32),
            domain_rows=jnp.sum(dom_any).astype(jnp.int32),
            invalid_rows=jnp.sum(invalid).astype(jnp.int32),
            batch_ok=~jnp.any(invalid),
        )


def check_batch(contract: DomainContract, *batch: Any) -> GuardVerdict:
    """Pure functional form of :meth:`DomainContract.check_batch`."""
    return contract.check_batch(*batch)


# --------------------------------------------------------------- eligibility
def guard_ineligibility(metric: Any, policy: str) -> Optional[str]:
    """Why ``metric`` cannot run under ``policy`` — or ``None`` if it can.

    Mirrors ``parallel.sliced_ineligibility``: a *reason string* rather than
    a bool so the refusal can name the offending state. ``propagate`` never
    rewrites the update and is always eligible.
    """
    if policy not in GUARD_POLICIES:
        raise ValueError(f"unknown guard policy {policy!r}; expected one of {GUARD_POLICIES}")
    if policy == "propagate":
        return None
    name = type(metric).__name__
    if getattr(metric, "_sharded_update_unsupported", None):
        return f"{name}.update cannot run traced: {metric._sharded_update_unsupported}"
    if getattr(metric, "_host_counters", ()):
        return f"{name} keeps host-side counters {metric._host_counters} the traced guard cannot restore"
    for state, default in metric._defaults.items():
        if state in GUARD_STATES:
            continue
        if isinstance(default, list):
            return f"state {state!r} is a list ('cat') state — rows cannot be unappended in-graph"
        red = metric._reductions.get(state)
        if policy == "mask" and not (isinstance(red, str) and red in _MASKABLE_REDUCTIONS):
            return (
                f"state {state!r} has reduction {red!r}; mask-policy row folding supports"
                f" {sorted(_MASKABLE_REDUCTIONS)} only"
            )
    if policy == "mask" and getattr(metric, "full_state_update", False):
        return f"{name} declares full_state_update=True; per-row decomposition from defaults is unsound"
    return None


# ------------------------------------------------------------------- install
def enable_guard(
    metric: Any,
    policy: str = "mask",
    contract: Optional[DomainContract] = None,
    probe: bool = True,
) -> Any:
    """Install the StateGuard on ``metric`` in place and return it.

    Registers the ``guard_*`` counter states and re-binds the instance
    ``update`` with the guarded closure (via ``Metric._rewrap``, so pickling
    and ``__setstate__`` re-install it automatically). ``contract`` defaults
    to the metric's own :meth:`~torchmetrics_tpu.metric.Metric.domain_contract`.
    """
    if policy not in GUARD_POLICIES:
        raise ValueError(f"unknown guard policy {policy!r}; expected one of {GUARD_POLICIES}")
    if getattr(metric, "_guard_policy", None) is not None:
        raise ValueError(f"{type(metric).__name__} is already guarded (policy={metric._guard_policy!r})")
    contract = contract if contract is not None else metric.domain_contract()
    if contract is None:
        raise ValueError(
            f"{type(metric).__name__} declares no domain contract (see metriclint ML013);"
            " pass contract= explicitly or implement domain_contract()"
        )
    reason = guard_ineligibility(metric, policy)
    if reason is not None:
        raise ValueError(f"metric ineligible for guard policy {policy!r}: {reason}")
    for state in GUARD_STATES:
        if state in metric._defaults:
            raise ValueError(f"state name {state!r} is reserved for the StateGuard plane")
    for state in GUARD_STATES[:-1]:
        metric.add_state(state, jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
    # the poison latch merges as max so a tripped shard/leaf taints the fold
    metric.add_state("guard_poisoned", jnp.zeros((), jnp.int32), dist_reduce_fx="max")
    metric._guard_policy = policy
    metric._guard_contract = contract
    metric._guard_probe = bool(probe)
    if hasattr(metric, "validate_args"):
        # the compiled contract subsumes eager host validation — which would
        # both host-sync per batch and raise on the very batches the mask and
        # reject policies exist to absorb
        metric.validate_args = False
    metric._rewrap()
    return metric


def _guard_wrap_update(metric: Any):
    """The guarded raw update — installed by ``Metric._rewrap`` *inside* the
    transactional ``_wrap_update`` wrapper, so count/state rollback on
    exception covers the guard counters too."""
    raw = metric.__class__.update.__get__(metric)
    sig = inspect.signature(metric.__class__.update)
    policy: str = metric._guard_policy
    contract: DomainContract = metric._guard_contract

    @functools.wraps(metric.__class__.update)
    def guarded(*args: Any, **kwargs: Any) -> None:
        bound = sig.bind(metric, *args, **kwargs)
        if bound.kwargs:
            raise TypeError(
                f"guarded update of {type(metric).__name__} accepts positionally-bindable arguments only"
            )
        batch = tuple(jnp.asarray(a) for a in bound.args[1:])
        verdict = contract.check_batch(*batch)
        if policy == "mask":
            _mask_apply(metric, raw, batch, verdict)
        elif policy == "reject":
            _reject_apply(metric, raw, batch, verdict)
        else:
            raw(*batch)
        _accumulate_verdict(metric, verdict, policy)
        if metric._guard_probe:
            bad = ~state_finiteness(metric)
            metric.guard_poisoned = jnp.maximum(metric.guard_poisoned, bad.astype(jnp.int32))

    return guarded


def _plain_states(metric: Any) -> Tuple[str, ...]:
    return tuple(k for k in metric._defaults if k not in GUARD_STATES)


def _reject_apply(metric: Any, raw, batch: Tuple[Array, ...], verdict: GuardVerdict) -> None:
    """Whole-batch veto: compute the candidate state, then select old/new per
    leaf on ``batch_ok`` — an invalid batch leaves state bitwise untouched
    (``where(False, new, old)`` is elementwise ``old``)."""
    prior = {k: getattr(metric, k) for k in _plain_states(metric)}
    raw(*batch)
    ok = verdict.batch_ok
    for k, old in prior.items():
        new = getattr(metric, k)
        setattr(metric, k, jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), new, old))


def _mask_apply(metric: Any, raw, batch: Tuple[Array, ...], verdict: GuardVerdict) -> None:
    """Accumulate only valid rows: one fresh update per row, vmapped
    (``parallel/sliced.py:_row_states`` staging), then a segment-reduce where
    invalid rows carry the spill segment and fall off — exact for integer
    count states, reassociated summation for float ones."""
    nan_any, inf_any, dom_any = metric._guard_contract.row_invalid(*batch)
    invalid_row = nan_any | inf_any | dom_any
    in_axes = tuple(0 if a.ndim >= 1 else None for a in batch)
    rows = next(a.shape[0] for a, ax in zip(batch, in_axes) if ax == 0)
    staged = tuple(
        a.reshape((rows, 1) + a.shape[1:]) if ax == 0 else a for a, ax in zip(batch, in_axes)
    )
    states = _plain_states(metric)
    saved = {k: getattr(metric, k) for k in states}

    def one(*row: Any) -> Dict[str, Any]:
        for k in states:
            setattr(metric, k, metric._defaults[k])
        raw(*row)
        return {k: getattr(metric, k) for k in states}

    try:
        per_row = jax.vmap(one, in_axes=in_axes)(*staged)
    finally:
        # drop tracers: the host-side object must only ever hold the carry
        for k, v in saved.items():
            setattr(metric, k, v)

    seg = invalid_row.astype(jnp.int32)  # valid rows -> cell 0, invalid -> spill
    any_valid = jnp.any(~invalid_row)
    for k in states:
        red = metric._reductions[k]
        fresh = _segment_reduce(red, per_row[k], seg)
        if red == "sum":
            merged = saved[k] + fresh
        elif red == "max":
            merged = jnp.maximum(saved[k], fresh)
        else:
            merged = jnp.minimum(saved[k], fresh)
        # all-invalid batch: segment identities never leak into the carry
        setattr(metric, k, jnp.where(any_valid, merged, saved[k]))


def _segment_reduce(red: str, rows: Array, seg: Array) -> Array:
    """Fold the per-row leading axis into the single valid cell; spilled rows
    carry segment id 1 and are sliced off (``parallel/sliced.py:302`` with
    ``num_cells=1``)."""
    if red == "sum":
        return jax.ops.segment_sum(rows, seg, num_segments=2)[0]
    if red == "max":
        return jax.ops.segment_max(rows, seg, num_segments=2)[0]
    if red == "min":
        return jax.ops.segment_min(rows, seg, num_segments=2)[0]
    raise ValueError(f"unexpected maskable reduction {red!r}")  # pragma: no cover - guard_ineligibility


def _accumulate_verdict(metric: Any, verdict: GuardVerdict, policy: str) -> None:
    metric.guard_nan_rows = metric.guard_nan_rows + verdict.nan_rows
    metric.guard_inf_rows = metric.guard_inf_rows + verdict.inf_rows
    metric.guard_domain_rows = metric.guard_domain_rows + verdict.domain_rows
    if policy == "mask":
        metric.guard_masked_rows = metric.guard_masked_rows + verdict.invalid_rows
    elif policy == "reject":
        metric.guard_rejected_batches = metric.guard_rejected_batches + (
            1 - verdict.batch_ok.astype(jnp.int32)
        )


# --------------------------------------------------------------- poison probe
def state_finiteness(metric: Any) -> Array:
    """One in-program finiteness reduction over the float state leaves —
    scalar bool ``True`` iff no float leaf carries NaN/Inf. Integer leaves
    are finite by construction and skipped; guard counters are excluded."""
    ok = jnp.asarray(True)
    for k in _plain_states(metric):
        for leaf in jax.tree_util.tree_leaves(getattr(metric, k)):
            leaf = jnp.asarray(leaf)
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


# ----------------------------------------------------------- host-side reads
def guarded_policy(metric: Any) -> Optional[str]:
    """The installed guard policy, or ``None`` when unguarded — the serve
    plane's feature probe (no isinstance, works through wrappers)."""
    return getattr(metric, "_guard_policy", None)


def guard_counters(metric: Any) -> Dict[str, int]:
    """Host snapshot of the cumulative guard counters (forces a sync — serve
    plane / gauges only, never inside compiled code)."""
    return {
        "nan_rows": int(metric.guard_nan_rows),
        "inf_rows": int(metric.guard_inf_rows),
        "domain_rows": int(metric.guard_domain_rows),
        "masked_rows": int(metric.guard_masked_rows),
        "rejected_batches": int(metric.guard_rejected_batches),
        "poisoned": int(metric.guard_poisoned),
    }


def batch_verdict_host(metric: Any, batch: Tuple[Any, ...]) -> Optional[Dict[str, int]]:
    """Re-run the contract over a (host) batch and return the verdict as
    plain ints — the dead-letter ledger's poison-quarantine record. ``None``
    when the metric is unguarded or the batch cannot be checked."""
    contract = getattr(metric, "_guard_contract", None)
    if contract is None:
        return None
    try:
        v = contract.check_batch(*batch)
        return {
            "nan_rows": int(v.nan_rows),
            "inf_rows": int(v.inf_rows),
            "domain_rows": int(v.domain_rows),
            "invalid_rows": int(v.invalid_rows),
            "batch_ok": bool(v.batch_ok),
        }
    except Exception:  # malformed batch: the quarantine must still land
        return None
