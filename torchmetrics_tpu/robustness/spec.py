# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""State-registry specs and restore-time validation.

``Metric.add_state`` declares each state's kind (fixed-shape array vs
append/"cat" list), dtype, shape and distributed reduction — a complete
schema. This module turns that schema into:

- :class:`StateSpec` / :func:`build_state_specs` — the per-state spec,
- :func:`spec_fingerprint` — a stable digest of the whole registry, embedded
  in checkpoints so schema drift is caught at restore time,
- :func:`validate_state_tree` — leaf-by-leaf validation of an incoming
  pytree against the registry, raising
  :class:`~torchmetrics_tpu.utilities.exceptions.StateRestoreError` that
  names the offending state and expected-vs-got.

A ``num_classes=5`` confusion matrix restored into a ``num_classes=7``
metric fails HERE with a readable message instead of detonating later inside
jit with an opaque shape error.
"""
from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

from torchmetrics_tpu.utilities.exceptions import StateRestoreError

#: reductions that preserve the accumulator shape; their states must match
#: the default shape exactly. "cat"/None/custom states grow along a leading
#: axis (concatenate/stack), so only trailing dims are pinned.
_ELEMENTWISE_REDUCTIONS = ("sum", "mean", "min", "max")


class StateSpec(NamedTuple):
    """Declared contract of one metric state."""

    kind: str  # "array" | "list" | "merge" (mergeable sketch pytree)
    dtype: Optional[str]  # None for list states; sketch CLASS NAME for merge states
    shape: Optional[Tuple]  # None for list states; per-leaf (field, dtype, shape) for merge states
    reduction: str  # reduction name, "none", or the callable's qualname


def _reduction_token(reduction: Any) -> str:
    if isinstance(reduction, str):
        return reduction
    if reduction is None:
        return "none"
    return getattr(reduction, "__qualname__", getattr(reduction, "__name__", "callable"))


def build_state_specs(metric: Any) -> Dict[str, StateSpec]:
    """Per-state :class:`StateSpec` for every registered state of ``metric``."""
    from torchmetrics_tpu.sketch.registry import is_sketch_state

    specs: Dict[str, StateSpec] = {}
    for name, default in metric._defaults.items():
        token = _reduction_token(metric._reductions.get(name))
        if isinstance(default, list):
            specs[name] = StateSpec("list", None, None, token)
        elif is_sketch_state(default):
            # fixed-shape pytree: the spec pins class name + EVERY leaf's
            # dtype/shape, so a capacity/levels mismatch is a spec mismatch
            leaves = tuple(
                (field, str(leaf.dtype), tuple(int(d) for d in leaf.shape))
                for field, leaf in zip(type(default)._fields, default)
            )
            specs[name] = StateSpec("merge", type(default).__name__, leaves, token)
        else:
            specs[name] = StateSpec("array", str(default.dtype), tuple(int(d) for d in default.shape), token)
    return specs


def spec_fingerprint(metric: Any) -> str:
    """Stable digest of the metric's class name + full state registry.

    Two metrics share a fingerprint iff a state tree of one is schema-valid
    for the other: same state names, kinds, dtypes, shapes and reductions.
    """
    specs = build_state_specs(metric)
    canon = [type(metric).__name__] + [
        [name, spec.kind, spec.dtype, list(spec.shape) if spec.shape is not None else None, spec.reduction]
        for name, spec in sorted(specs.items())
    ]
    return hashlib.sha256(json.dumps(canon, separators=(",", ":")).encode()).hexdigest()[:16]


def _shape_compatible(got: Tuple[int, ...], want: Tuple[int, ...], elementwise: bool) -> bool:
    """Default dims of size 0 are wildcards (empty-accumulator conventions);
    non-elementwise states grow along leading axes, so only the trailing
    ``len(want)`` dims are pinned."""
    if elementwise:
        return len(got) == len(want) and all(w in (g, 0) for g, w in zip(got, want))
    if len(got) < len(want):
        return False
    tail = got[len(got) - len(want) :]
    return all(w in (g, 0) for g, w in zip(tail, want))


def _dtype_safe_widening(got: Any, want: Any) -> bool:
    try:
        return bool(np.can_cast(got, want, casting="safe"))
    except TypeError:  # extension dtypes (bfloat16, ...) outside numpy's lattice
        return False


#: serialized-sketch payload marker (checkpoints store sketch states as a
#: plain ``{"__sketch__": class_name, "leaves": {field: ndarray}}`` dict so
#: the checkpoint stays msgpack/orbax-serializable)
SKETCH_PAYLOAD_KEY = "__sketch__"


def _validate_sketch_state(cls: str, name: str, default: Any, value: Any, strict: bool) -> Any:
    """Validate (and, for serialized payloads, reconstruct) one mergeable
    sketch state against its default: class, field set, and every leaf's
    shape and dtype must match the fixed-shape contract EXACTLY — sketch
    leaves never grow, so a capacity/levels mismatch is a hard error naming
    the state and leaf."""
    from torchmetrics_tpu.sketch.registry import sketch_state_class

    want_cls = type(default)
    fields = want_cls._fields
    if isinstance(value, dict):
        if value.get(SKETCH_PAYLOAD_KEY) != want_cls.__name__:
            raise StateRestoreError(
                f"state {name!r} of {cls}: expected a serialized {want_cls.__name__} sketch payload,"
                f" got {value.get(SKETCH_PAYLOAD_KEY)!r} — was this checkpoint written by a"
                " differently-configured metric?"
            )
        leaves_in = value.get("leaves")
        if not isinstance(leaves_in, dict) or sorted(leaves_in) != sorted(fields):
            got = sorted(leaves_in) if isinstance(leaves_in, dict) else type(leaves_in).__name__
            raise StateRestoreError(
                f"state {name!r} of {cls}: sketch payload leaves {got} do not match the declared"
                f" fields {sorted(fields)} — truncated or corrupted payload?"
            )
        try:
            sketch_state_class(want_cls.__name__)
        except KeyError as err:
            raise StateRestoreError(f"state {name!r} of {cls}: {err}") from None
        value = want_cls(*[leaves_in[field] for field in fields])
    elif type(value) is not want_cls:
        raise StateRestoreError(
            f"state {name!r} of {cls}: expected a {want_cls.__name__} sketch state,"
            f" got {type(value).__name__}"
        )
    checked = []
    for field, want_leaf, got_leaf in zip(fields, default, value):
        if not hasattr(got_leaf, "dtype") or not hasattr(got_leaf, "shape"):
            got_leaf = np.asarray(got_leaf)
        got_shape = tuple(int(d) for d in got_leaf.shape)
        want_shape = tuple(int(d) for d in want_leaf.shape)
        if got_shape != want_shape:
            raise StateRestoreError(
                f"state {name!r} of {cls}: sketch leaf {field!r} has shape {got_shape}, expected"
                f" {want_shape} — sketch states are fixed-shape (capacity/levels mismatch?)"
            )
        if got_leaf.dtype != want_leaf.dtype:
            if strict:
                raise StateRestoreError(
                    f"state {name!r} of {cls}: sketch leaf {field!r} has dtype {got_leaf.dtype},"
                    f" expected {want_leaf.dtype} (strict restore; pass strict=False to allow"
                    " safe widenings)"
                )
            if not _dtype_safe_widening(got_leaf.dtype, want_leaf.dtype):
                raise StateRestoreError(
                    f"state {name!r} of {cls}: cannot coerce sketch leaf {field!r} dtype"
                    f" {got_leaf.dtype} to {want_leaf.dtype} — only safe widenings are allowed"
                    " in non-strict restore"
                )
            got_leaf = got_leaf.astype(want_leaf.dtype)
        checked.append(got_leaf)
    return want_cls(*checked)


def validate_state_tree(metric: Any, tree: Dict[str, Any], strict: bool = True) -> Dict[str, Any]:
    """Validate ``tree`` against ``metric``'s state registry.

    Returns the (possibly dtype-coerced) tree to install; never mutates the
    metric, so callers can validate a whole checkpoint before applying any of
    it. Strict mode demands the exact registry key set and exact dtypes;
    non-strict mode drops unknown keys, allows missing ones, and coerces only
    SAFE dtype widenings (``int32 -> int64``, ``float16 -> float32``, ...) —
    lossy narrowing always raises.
    """
    cls = type(metric).__name__
    defaults = metric._defaults
    unknown = sorted(k for k in tree if k not in defaults)
    if unknown and strict:
        raise StateRestoreError(
            f"Unknown metric state(s) {unknown} for {cls}: the registry declares {sorted(defaults)}"
        )
    if strict:
        missing = sorted(k for k in defaults if k not in tree)
        if missing:
            raise StateRestoreError(
                f"Missing metric state(s) {missing} for {cls}: a strict restore must cover every registered state"
            )

    from torchmetrics_tpu.sketch.registry import is_sketch_state

    out: Dict[str, Any] = {}
    for name, value in tree.items():
        if name not in defaults:
            continue  # non-strict: ignore unknown leaves
        default = defaults[name]
        reduction = metric._reductions.get(name)
        token = _reduction_token(reduction)
        if is_sketch_state(default):
            out[name] = _validate_sketch_state(cls, name, default, value, strict)
            continue
        if isinstance(default, list):
            if not isinstance(value, (list, tuple)):
                raise StateRestoreError(
                    f"state {name!r} of {cls}: expected a list ('{token}') state, got {type(value).__name__}"
                )
            out[name] = list(value)
            continue
        if isinstance(value, (list, tuple)):
            raise StateRestoreError(
                f"state {name!r} of {cls}: expected an array (shape {tuple(default.shape)}, dtype {default.dtype}),"
                f" got a {type(value).__name__} of {len(value)} element(s)"
            )
        if not hasattr(value, "dtype") or not hasattr(value, "shape"):
            value = np.asarray(value)
        got_shape = tuple(int(d) for d in value.shape)
        want_shape = tuple(int(d) for d in default.shape)
        if not _shape_compatible(got_shape, want_shape, token in _ELEMENTWISE_REDUCTIONS):
            raise StateRestoreError(
                f"state {name!r} of {cls}: expected shape {want_shape} (reduction {token!r}),"
                f" got shape {got_shape} — was this checkpoint written by a differently-configured metric?"
            )
        if value.dtype != default.dtype:
            if strict:
                raise StateRestoreError(
                    f"state {name!r} of {cls}: expected dtype {default.dtype}, got {value.dtype}"
                    " (strict restore; pass strict=False to allow safe widenings)"
                )
            if not _dtype_safe_widening(value.dtype, default.dtype):
                raise StateRestoreError(
                    f"state {name!r} of {cls}: cannot coerce dtype {value.dtype} to {default.dtype} —"
                    " only safe widenings are allowed in non-strict restore"
                )
            value = value.astype(default.dtype)
        out[name] = value
    return out
