# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Preemption-safe streaming evaluation.

On preemptible TPU fleets a multi-hour evaluation WILL be killed mid-stream;
without durable progress a death at batch 1.9M restarts from zero.
:class:`StreamingEvaluator` closes that gap by wrapping a ``Metric``, a
``MetricCollection``, or a custom (e.g. sharded) update step over a batch
iterable with:

- an **exactly-once batch cursor**: every snapshot records the number of
  fully-applied batches; :meth:`resume` fast-forwards the (deterministically
  re-creatable) stream past exactly that many batches and continues, so no
  batch is ever double-counted or skipped relative to the restored state —
  batches applied after the last snapshot die with the process and are
  simply replayed.
- a **snapshot policy**: every N batches and/or every T seconds, the metric's
  deep self-validating checkpoint (PR 2) plus the cursor is persisted through
  a :class:`~torchmetrics_tpu.robustness.store.CheckpointStore` (atomic,
  CRC'd, retention-pruned, rank-aware).
- a **watchdog**: each update (and the final compute/sync) optionally runs
  under a wall-clock deadline; a stall raises
  :class:`~torchmetrics_tpu.utilities.exceptions.StallError` instead of
  hanging the fleet — ``on_stall="snapshot_then_raise"`` persists the
  last-good state first so the supervisor can kill and resume.

The update/sync watchdog runs the step on a daemon worker thread (the same
trade as ``Metric._sync_dist_bounded``): an abandoned step cannot be
cancelled and its state must be considered poisoned — which is why the stall
snapshot is taken from the checkpoint captured BEFORE the stalled step, never
from the live (possibly half-mutated) metric.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional

from torchmetrics_tpu.obs import attribution as _obs_attr
from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.robustness.store import CheckpointStore
from torchmetrics_tpu.utilities.exceptions import StallError, StateRestoreError

__all__ = ["StreamingEvaluator"]

#: payload layout version for the runner's snapshot dict
RUNNER_PAYLOAD_VERSION = 1

_ON_STALL = ("raise", "snapshot_then_raise")


def _default_update(target: Any, batch: Any) -> None:
    """Positional-splat convention: a tuple batch is ``update(*batch)``,
    anything else is ``update(batch)`` — matches how eval loops usually zip
    preds/targets. Pass ``update_fn`` for anything richer (kwargs, sharded
    steps: ``lambda m, b: sharded_update(m, mesh, *b)``)."""
    if isinstance(batch, tuple):
        target.update(*batch)
    else:
        target.update(batch)


class StreamingEvaluator:
    """Drive a metric over a batch stream with durable, resumable progress.

    Args:
        metric: a ``Metric`` or ``MetricCollection`` accumulating the stream.
        store: the durable :class:`CheckpointStore`; ``None`` runs without
            durability (the watchdog still works).
        snapshot_every_n: persist a snapshot after every N applied batches.
        snapshot_every_s: persist a snapshot when at least T seconds passed
            since the last one (checked after each batch; combines with
            ``snapshot_every_n`` as an OR).
        update_fn: ``update_fn(metric, batch)`` override for the per-batch
            step (sharded/jitted steps, kwargs batches).
        watchdog_timeout_s: wall-clock deadline per update and for the final
            compute/sync; ``None`` disables the watchdog.
        on_stall: ``"raise"`` surfaces :class:`StallError` immediately;
            ``"snapshot_then_raise"`` first persists the last-good state
            (pre-stall cursor) to ``store``.
        fused: drive the one-dispatch fused evaluation plane
            (``parallel/fused.py``): a ``FusedCollectionPlan`` is built from
            the metric at the first batch (after any resume restore, so the
            carry picks up the restored states) and every batch costs ONE
            compiled call regardless of collection size. Fold-back into the
            member metrics happens only at snapshot/compute boundaries —
            never per batch — so the exactly-once cursor, the snapshot
            payloads and the final ``compute()`` are byte-for-byte the
            unfused protocol. Mutually exclusive with ``update_fn``. Note
            ``on_stall="snapshot_then_raise"`` captures a payload per batch
            and therefore folds back per batch — correct, but it forfeits
            the fused plane's per-batch savings.
        fused_options: kwargs for the plan build (``cat_capacity``,
            ``example_batch``, ``donate``, ``mesh``, ``axis_name``);
            ``example_batch`` defaults to the first batch.
        window_ring: a :class:`~torchmetrics_tpu.parallel.windowing.WindowRing`
            wrapping the SAME ``metric``: after every applied batch the ring
            observes the cursor and closes the open window when its
            ``every_n``/``every_s`` trigger fires; the ring's closed windows
            ride every snapshot payload (kill-and-resume restores them with
            the open state), and its ``window.<Class>.*`` probe publishes
            through the live plane while the drive runs. Mutually exclusive
            with ``fused=True``: a rotation resets the metric mid-stream,
            which the fused plane's donated carry cannot observe.

    ``metric`` may also be a
    :class:`~torchmetrics_tpu.parallel.sliced.SlicedPlan`: the evaluator then
    drives ``plan.update(*batch)`` per batch (batches are ``(keys, *arrays)``
    tuples), snapshots the plan's whole carry (slice table included) through
    the store under ``kind="sliced"``, and the final result is
    ``plan.compute_all()``. Mutually exclusive with ``fused``/``update_fn``/
    ``window_ring`` — the plan owns its own dispatch and state layout.

    One evaluator instance drives one pass: :meth:`run` starts from batch 0
    (and demands a fresh store), :meth:`resume` restores the newest valid
    snapshot — or starts from 0 on an empty store, so supervisors can always
    call ``resume()``. A long-lived service instead pumps the open-loop form
    (:meth:`serve_open` / :meth:`serve_step` / :meth:`serve_close`), where the
    FEED positions itself at the restored cursor rather than replaying the
    stream past it — the ``metricserve`` daemon's drive protocol.
    """

    def __init__(
        self,
        metric: Any,
        store: Optional[CheckpointStore] = None,
        snapshot_every_n: Optional[int] = None,
        snapshot_every_s: Optional[float] = None,
        update_fn: Optional[Callable[[Any, Any], None]] = None,
        watchdog_timeout_s: Optional[float] = None,
        on_stall: str = "raise",
        fused: bool = False,
        fused_options: Optional[Dict[str, Any]] = None,
        window_ring: Optional[Any] = None,
    ) -> None:
        if snapshot_every_n is not None and snapshot_every_n < 1:
            raise ValueError(f"snapshot_every_n must be >= 1, got {snapshot_every_n}")
        if snapshot_every_s is not None and snapshot_every_s <= 0:
            raise ValueError(f"snapshot_every_s must be > 0, got {snapshot_every_s}")
        if watchdog_timeout_s is not None and watchdog_timeout_s <= 0:
            raise ValueError(f"watchdog_timeout_s must be > 0 (or None to disable), got {watchdog_timeout_s}")
        if on_stall not in _ON_STALL:
            raise ValueError(f"on_stall must be one of {_ON_STALL}, got {on_stall!r}")
        if store is not None and not isinstance(store, CheckpointStore):
            raise ValueError(f"store must be a CheckpointStore, got {type(store).__name__}")
        if fused and update_fn is not None:
            raise ValueError("fused=True drives the FusedCollectionPlan itself; it cannot combine with update_fn")
        self._is_plan = False
        if type(metric).__name__ == "SlicedPlan":  # cheap gate before the parallel import
            from torchmetrics_tpu.parallel.sliced import SlicedPlan

            self._is_plan = isinstance(metric, SlicedPlan)
        if self._is_plan and (fused or update_fn is not None or window_ring is not None):
            raise ValueError(
                "a SlicedPlan target owns its own dispatch and state layout; it cannot"
                " combine with fused/update_fn/window_ring"
            )
        if window_ring is not None:
            from torchmetrics_tpu.parallel.windowing import WindowRing

            if not isinstance(window_ring, WindowRing):
                raise ValueError(f"window_ring must be a WindowRing, got {type(window_ring).__name__}")
            if window_ring.target is not metric:
                raise ValueError("window_ring must wrap the SAME metric object this evaluator drives")
            if fused:
                raise ValueError(
                    "window_ring cannot combine with fused=True: a window rotation resets the"
                    " metric mid-stream, which the fused plane's donated carry cannot observe"
                )
        self.metric = metric
        self.store = store
        self.snapshot_every_n = snapshot_every_n
        self.snapshot_every_s = snapshot_every_s
        self.update_fn = update_fn or _default_update
        self.fused = bool(fused)
        self.fused_options = dict(fused_options or {})
        self.window_ring = window_ring
        #: the live FusedCollectionPlan while a fused drive is in flight
        self._fused_plan: Optional[Any] = None
        self.watchdog_timeout_s = watchdog_timeout_s
        self.on_stall = on_stall
        #: number of batches fully applied to the metric state
        self.cursor = 0
        self._last_snapshot_t: Optional[float] = None
        self._last_good_payload: Optional[Dict[str, Any]] = None
        #: optional veto over CADENCE snapshots only (explicit snapshot() is
        #: never gated): the serve plane's StateGuard points this at the
        #: poison probe so a just-corrupted state cannot reach disk in the
        #: window between the apply and the rollback
        self.snapshot_gate: Optional[Callable[[], bool]] = None
        # per-drive loop state, installed by _begin_drive (also the open-loop
        # serve_open): the hoisted apply callable and the stall-policy flag
        self._apply_batch: Optional[Callable[[Any], None]] = None
        self._snapshotting_stalls = False
        # live-plane producer state (obs/live.py): deadline of the in-flight
        # bounded step (the watchdog-margin probe reads it while the step
        # runs — a stalled update shows a shrinking margin in real time),
        # last persisted snapshot size, and the throughput EWMA
        self._watchdog_deadline: Optional[float] = None
        self._snapshot_bytes_last: Optional[int] = None
        self._ewma_sps: Optional[float] = None
        self._last_batch_t: Optional[float] = None
        # honor TM_TPU_PUBLISH exactly once per process (no-op when unset):
        # constructing an evaluator is the natural "a long run starts here"
        _obs_live.maybe_enable_from_env()
        if store is not None and store.fingerprint is None:
            # pin the metric's registry fingerprint into the manifest so a
            # drifted metric definition is refused with a NAMED error at the
            # store door, before any snapshot is even read
            store.fingerprint = self._fingerprint()

    # ----------------------------------------------------------- checkpoints
    def _is_collection(self) -> bool:
        if self._is_plan:
            return False
        from torchmetrics_tpu.collections import MetricCollection

        return isinstance(self.metric, MetricCollection)

    def _kind(self) -> str:
        if self._is_plan:
            return "sliced"
        return "collection" if self._is_collection() else "metric"

    def _fingerprint(self) -> str:
        """PR-2 registry fingerprint of the wrapped target: the metric's deep
        checkpoint fingerprint, a digest over every member's for a collection,
        or the plan's stable fingerprint for a ``SlicedPlan`` target."""
        from torchmetrics_tpu.robustness.checkpoint import checkpoint_fingerprint

        if self._is_plan:
            return self.metric.stable_fingerprint()
        if self._is_collection():
            import hashlib
            import json

            canon = sorted(
                (name, checkpoint_fingerprint(m))
                for name, m in self.metric.items(keep_base=True, copy_state=False)
            )
            return hashlib.sha256(json.dumps(canon, separators=(",", ":")).encode()).hexdigest()[:16]
        return checkpoint_fingerprint(self.metric)

    def _checkpoint(self) -> Dict[str, Any]:
        if self._is_plan:
            return self.metric.save_checkpoint()  # the whole carry, table included
        if self._is_collection():
            # copy_state=True materializes per-member states out of compute-
            # group aliasing, so each member checkpoints its own (equal) state
            return {name: m.save_checkpoint() for name, m in self.metric.items(keep_base=True, copy_state=True)}
        return self.metric.save_checkpoint()

    def _restore_checkpoint(self, checkpoint: Dict[str, Any]) -> None:
        if self._is_plan:
            self.metric.load_checkpoint(checkpoint)  # validate-ALL-then-apply (PR 10)
            return
        if not self._is_collection():
            self.metric.load_checkpoint(checkpoint)  # validate-ALL-then-apply (PR 2)
            return
        live = dict(self.metric.items(keep_base=True, copy_state=False))
        missing = sorted(set(live) - set(checkpoint))
        extra = sorted(set(checkpoint) - set(live))
        if missing or extra:
            raise StateRestoreError(
                "snapshot does not match the MetricCollection:"
                + (f" missing member(s) {missing}" if missing else "")
                + (f" unexpected member(s) {extra}" if extra else "")
            )
        # each member's load_checkpoint is atomic, but a member failing after
        # an earlier one applied would half-restore the COLLECTION — snapshot
        # every member first and roll the group back together on any failure
        prior = [
            (
                m,
                m._copy_state_dict(),
                m._update_count,
                {attr: getattr(m, attr) for attr in getattr(m, "_host_counters", ())},
            )
            for m in live.values()
        ]
        try:
            for name, member in live.items():
                member.load_checkpoint(checkpoint[name])
        except Exception:
            for member, tree, count, host_counters in prior:
                member._install_state_tree(tree)  # self-snapshot: trusted
                member._update_count = count
                member._computed = None
                for attr, val in host_counters.items():
                    setattr(member, attr, val)
            raise

    def _payload(self) -> Dict[str, Any]:
        if self._fused_plan is not None:
            # a payload is a host boundary: the carried fused totals fold
            # back into the member metrics first, so every snapshot (periodic,
            # stall capture, final) serializes exactly the applied batches
            self._fused_plan.fold_back()
        payload = {
            "payload_version": RUNNER_PAYLOAD_VERSION,
            "cursor": self.cursor,
            "kind": self._kind(),
            "checkpoint": self._checkpoint(),
        }
        if self.window_ring is not None:
            # the closed windows travel WITH the open state + cursor: a
            # resumed run's ring is exactly the killed run's at that snapshot
            payload["window"] = self.window_ring.payload()
        return payload

    def _validate_payload(self, payload: Dict[str, Any]) -> None:
        """``CheckpointStore.latest`` hook: raise ``StateRestoreError`` for a
        payload this evaluator cannot resume from. Restores the metric as a
        side effect when valid — ``load_checkpoint`` is validate-ALL-then-
        apply, so a raising payload leaves the metric untouched and the
        store's recovery ladder moves on to an older snapshot."""
        missing = [k for k in ("payload_version", "cursor", "checkpoint") if k not in payload]
        if missing:
            raise StateRestoreError(f"runner snapshot is missing key(s) {missing} — truncated payload?")
        version = payload["payload_version"]
        if not isinstance(version, int) or version < 1 or version > RUNNER_PAYLOAD_VERSION:
            raise StateRestoreError(
                f"runner snapshot payload_version {version!r} is not supported"
                f" (this build reads <= {RUNNER_PAYLOAD_VERSION})"
            )
        cursor = payload["cursor"]
        if not isinstance(cursor, int) or cursor < 0:
            raise StateRestoreError(f"runner snapshot cursor {cursor!r} is not a non-negative int")
        kind = self._kind()
        if payload.get("kind") != kind:
            raise StateRestoreError(
                f"runner snapshot was written for a {payload.get('kind')!r} target, this"
                f" evaluator wraps a {kind!r}"
            )
        ring_parts = None
        if self.window_ring is None and "window" in payload:
            raise StateRestoreError(
                "runner snapshot carries a window-ring payload but this evaluator has no"
                " window_ring attached — resuming would silently DROP the closed windows"
                " (and the next snapshot would erase them from the store); attach the ring"
                " or point at an un-windowed store"
            )
        if self.window_ring is not None:
            if "window" not in payload:
                raise StateRestoreError(
                    "runner snapshot carries no window-ring payload but this evaluator has a"
                    " window_ring attached — the snapshot came from an un-windowed run"
                )
            # validate WITHOUT applying: if the metric checkpoint below is
            # rejected, the live ring must not be left holding this
            # snapshot's closed windows (validate-ALL-then-apply holds
            # across BOTH restores)
            ring_parts = self.window_ring.validated_parts(payload["window"])
        self._restore_checkpoint(payload["checkpoint"])
        if ring_parts is not None:
            self.window_ring.apply_parts(ring_parts)

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> Optional[int]:
        """Persist the current state + cursor now; returns the step written
        (the cursor), or ``None`` without a store / on non-writer ranks / when
        the store already holds this step (idempotent re-snapshot)."""
        if self.store is None or not self.store.is_writer:
            return None  # non-writer ranks skip even the host-copy of the payload
        last = self.store.last_step()
        if last is not None and self.cursor <= last:
            return None
        name = self.store.save(self._payload(), step=self.cursor)
        if name is None:
            return None
        self._last_snapshot_t = time.monotonic()
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_counters.inc("runner.snapshot")
            self._attribution_boundary()
            try:
                self._snapshot_bytes_last = os.path.getsize(os.path.join(self.store.directory, name))
            except OSError:
                self._snapshot_bytes_last = None
            if self._snapshot_bytes_last is not None:
                # "what would survive a kill" next to "where the run is":
                # operators correlate the two without opening the store
                _obs_counters.set_gauge("runner.snapshot.bytes_last", self._snapshot_bytes_last)
        return self.cursor

    def _attribution_boundary(self) -> None:
        """Refresh the per-metric ``metric.<Class>.state_bytes`` gauges (and
        the cost-ledger registry) at a snapshot boundary, so the live plane
        shows the state-memory footprint next to throughput. Callers guard
        with the trace/live flags."""
        if self._is_plan:
            self.metric.publish_gauges()  # slice.table.* + the plan's state-bytes row
            return
        if self._is_collection():
            for name, member in self.metric.items(keep_base=True, copy_state=False):
                _obs_attr.note_instance(type(member).__name__, name)
                _obs_attr.metric_boundary(member)
        else:
            _obs_attr.metric_boundary(self.metric)

    def _maybe_snapshot(self) -> None:
        if self.store is None:
            return
        if self.snapshot_gate is not None and not self.snapshot_gate():
            return
        due_n = self.snapshot_every_n is not None and self.cursor % self.snapshot_every_n == 0
        due_s = (
            self.snapshot_every_s is not None
            and self._last_snapshot_t is not None
            and time.monotonic() - self._last_snapshot_t >= self.snapshot_every_s
        )
        if due_n or due_s:
            self.snapshot()

    # -------------------------------------------------------------- watchdog
    def _bounded(self, fn: Callable[..., Any], what: str, *args: Any) -> Any:
        """Run ``fn(*args)`` under the watchdog deadline (same daemon-thread
        trade as ``Metric._sync_dist_bounded``: a timed-out step cannot be
        cancelled and its state is poisoned — the caller must treat a
        StallError as fatal for this process and resume in a fresh one).
        Taking ``*args`` lets the drive loop pass the batch to one hoisted
        per-drive callable instead of allocating a closure per batch."""
        if not self.watchdog_timeout_s:
            return fn(*args)
        box: Dict[str, Any] = {}

        def _worker() -> None:
            try:
                box["value"] = fn(*args)
            except BaseException as err:
                box["err"] = err

        thread = threading.Thread(target=_worker, daemon=True, name=f"tm-tpu-runner-{what}")
        # published BEFORE the step starts so the live watchdog-margin probe
        # decays across the whole deadline window; deliberately NOT cleared on
        # a stall — the abandoned step is dead, the margin stays <= 0 and the
        # health state stays "stalled" for post-mortem scrapes
        self._watchdog_deadline = time.monotonic() + self.watchdog_timeout_s
        thread.start()
        thread.join(self.watchdog_timeout_s)
        if thread.is_alive():
            if _obs_trace.ENABLED or _obs_live.ENABLED:
                _obs_counters.inc("runner.watchdog_stall")
            if _obs_trace.ENABLED:
                _obs_trace.instant("runner.watchdog_stall", what=what, cursor=self.cursor)
            saved = None
            if self.on_stall == "snapshot_then_raise" and self.store is not None:
                saved = self._stall_snapshot()
            raise StallError(
                f"evaluation {what} at batch cursor {self.cursor} exceeded the"
                f" {self.watchdog_timeout_s}s watchdog deadline"
                + (f" — last-good state saved at step {saved}" if saved is not None else "")
                + "; the stalled step cannot be cancelled, resume in a fresh process"
            )
        self._watchdog_deadline = None  # step finished inside the deadline
        if "err" in box:
            raise box["err"]
        return box.get("value")

    def _stall_snapshot(self) -> Optional[int]:
        """Persist the pre-stall payload captured before the stalled step —
        NEVER the live metric, which the abandoned worker thread may still be
        mutating."""
        if self._last_good_payload is None:
            return None
        payload = self._last_good_payload
        last = self.store.last_step() if self.store.is_writer else None
        if last is not None and int(payload["cursor"]) <= last:
            return None  # the periodic policy already persisted this step
        if self.store.save(payload, step=int(payload["cursor"])) is None:
            return None
        return int(payload["cursor"])

    # ------------------------------------------------------------------ run
    def run(self, batches: Iterable[Any]) -> Any:
        """Evaluate the stream from batch 0 and return ``compute()``.

        Demands a fresh position: if the store already holds snapshots, this
        raises (use :meth:`resume`, or point the evaluator at a new
        directory) — silently re-running from 0 over a dirty store would
        violate step monotonicity at the first snapshot anyway.
        """
        if self.store is not None and self.store.is_writer and self.store.last_step() is not None:
            raise ValueError(
                f"store {self.store.directory} already holds snapshots up to step"
                f" {self.store.last_step()} — use resume() to continue, or a fresh directory"
            )
        if _obs_trace.ENABLED:
            with _obs_trace.span("runner.run", metric=type(self.metric).__name__):
                return self._drive(batches, skip=0)
        return self._drive(batches, skip=0)

    def resume(self, batches: Iterable[Any]) -> Any:
        """Restore the newest valid snapshot, fast-forward ``batches`` past
        the recorded cursor, evaluate the remainder and return ``compute()``.

        ``batches`` must be the SAME deterministic stream the interrupted run
        consumed (same order, same content) — the exactly-once guarantee is
        relative to the stream, and the fast-forward is positional. On an
        empty (or entirely-invalid) store the evaluation starts from batch 0.
        """
        if _obs_trace.ENABLED:
            with _obs_trace.span("runner.resume", metric=type(self.metric).__name__):
                return self._resume(batches)
        return self._resume(batches)

    def _resume(self, batches: Iterable[Any]) -> Any:
        restored = self.store.latest(validate=self._validate_payload) if self.store is not None else None
        if restored is None:
            self.cursor = 0
        else:
            step, payload = restored
            # _validate_payload already installed the checkpoint
            self.cursor = int(payload["cursor"])
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_counters.inc("runner.resume")
        if _obs_trace.ENABLED:
            _obs_trace.instant("runner.resume", cursor=self.cursor, restored=restored is not None)
        return self._drive(batches, skip=self.cursor)

    # ------------------------------------------------------------ live plane
    def _live_probe(self) -> Dict[str, Any]:
        """Sampled by the :mod:`~torchmetrics_tpu.obs.live` publisher thread
        (and every ``/metrics``/``/healthz`` request) while a drive is in
        flight: the exactly-once cursor, snapshot freshness/size, and the
        REAL-TIME watchdog margin — reads of immutable floats/ints under the
        GIL, so no locking against the driving thread is needed."""
        now = time.monotonic()
        gauges: Dict[str, Any] = {"runner.cursor": self.cursor}
        if self._last_snapshot_t is not None:
            gauges["runner.snapshot.age_s"] = now - self._last_snapshot_t
        if self._snapshot_bytes_last is not None:
            gauges["runner.snapshot.bytes_last"] = self._snapshot_bytes_last
        if self.watchdog_timeout_s:
            gauges["runner.watchdog.timeout_s"] = self.watchdog_timeout_s
            deadline = self._watchdog_deadline
            gauges["runner.watchdog.margin_s"] = (
                self.watchdog_timeout_s if deadline is None else deadline - now
            )
        if self._ewma_sps is not None:
            gauges["runner.throughput.samples_per_s"] = self._ewma_sps
        return gauges

    @staticmethod
    def _batch_size(batch: Any) -> int:
        """Best-effort samples-per-batch for the progress counters: leading
        dim of the first tuple element (the preds array), else ``len``, else 1."""
        head = batch[0] if isinstance(batch, tuple) and batch else batch
        try:
            return int(head.shape[0])
        except Exception:
            try:
                return len(head)
            except Exception:
                return 1

    def _record_progress(self, batch: Any) -> None:
        """Per-batch producer: progress counters + EWMA throughput gauge.
        Callers guard with the live/trace flags — nothing here runs (or
        allocates) on the disabled path."""
        n = self._batch_size(batch)
        _obs_counters.inc("runner.progress.batches")
        _obs_counters.inc("runner.progress.samples", n)
        # also a registry gauge (not just the live probe) so the cursor rides
        # every published payload — including the final flush after the drive
        # ends and the probe is gone
        _obs_counters.set_gauge("runner.cursor", self.cursor)
        now = time.monotonic()
        if self._last_batch_t is not None and now > self._last_batch_t:
            inst = n / (now - self._last_batch_t)
            self._ewma_sps = inst if self._ewma_sps is None else 0.2 * inst + 0.8 * self._ewma_sps
            _obs_counters.set_gauge("runner.throughput.samples_per_s", self._ewma_sps)
        self._last_batch_t = now

    def _register_probes(self, force: bool = False) -> None:
        """Per-instance probe names: two evaluators driving concurrently in
        one process must not clobber (or, on finish, unregister) each
        other's live telemetry. ``force`` registers even with the live plane
        off — the serve daemon answers ``/healthz``/``/metrics`` itself, so
        its streams' watchdog margins must be probe-visible regardless."""
        if not (force or _obs_live.ENABLED):
            return
        _obs_live.register_probe(f"runner-{id(self)}", self._live_probe)
        if self.window_ring is not None:
            _obs_live.register_probe(f"window-{id(self)}", self.window_ring.probe)
        if self._is_plan:
            _obs_live.register_probe(f"sliced-{id(self)}", self.metric.live_probe)

    def _unregister_probes(self) -> None:
        for prefix in ("runner", "window", "sliced"):
            _obs_live.unregister_probe(f"{prefix}-{id(self)}")

    def _drive(self, batches: Iterable[Any], skip: int) -> Any:
        if _obs_live.ENABLED:
            self._register_probes()
            try:
                return self._drive_impl(batches, skip)
            finally:
                self._unregister_probes()
        return self._drive_impl(batches, skip)

    def _make_apply(self) -> Callable[[Any], None]:
        """The per-batch step, hoisted to ONE per-drive callable: the loop
        used to allocate a fresh lambda (re-reading ``self.update_fn`` and
        ``self.metric``) for every batch — per-batch host cost the fused
        plane exists to eliminate. Fused drives build the plan lazily at the
        first batch, so ``resume()`` restores state first and the plan's
        carry seeds from the restored members."""
        if self._is_plan:
            plan = self.metric
            return lambda batch: plan.update(*batch) if isinstance(batch, tuple) else plan.update(batch)
        if not self.fused:
            update_fn, metric = self.update_fn, self.metric
            return lambda batch: update_fn(metric, batch)

        def apply_fused(batch: Any) -> None:
            plan = self._fused_plan
            if plan is None:
                plan = self._build_fused_plan(batch)
            if isinstance(batch, tuple):
                plan.update(*batch)
            else:
                plan.update(batch)

        return apply_fused

    def _build_fused_plan(self, batch: Any) -> Any:
        from torchmetrics_tpu.parallel.fused import FusedCollectionPlan

        options = dict(self.fused_options)
        options.setdefault("example_batch", batch if isinstance(batch, tuple) else (batch,))
        self._fused_plan = FusedCollectionPlan(self.metric, **options)
        return self._fused_plan

    def _begin_drive(self, start: int) -> None:
        self.cursor = start
        self._last_snapshot_t = time.monotonic()
        self._fused_plan = None  # one plan per drive, built at the first batch
        self._snapshotting_stalls = bool(
            self.on_stall == "snapshot_then_raise" and self.watchdog_timeout_s
        )
        self._apply_batch = self._make_apply()

    def _step_impl(self, batch: Any) -> None:
        if self._snapshotting_stalls:
            # the stall snapshot must pre-date the (possibly half-applied)
            # stalled update; capture costs one host round-trip per batch
            # (plus a fused fold-back) and is only paid when the policy
            # asks for it
            self._last_good_payload = self._payload()
        self._bounded(self._apply_batch, "update", batch)
        self.cursor += 1
        if _obs_live.ENABLED or _obs_trace.ENABLED:
            self._record_progress(batch)
        if self.window_ring is not None:
            # rotation happens AFTER the batch fully applied and BEFORE
            # its snapshot, so every snapshot's ring is cursor-consistent
            self.window_ring.observe(self.cursor)
        if faults._ACTIVE:  # preemption drill: die after batch k, before its snapshot
            faults.fire("runner.preempt")
        self._maybe_snapshot()

    def _finish_drive(self) -> Any:
        if self._fused_plan is not None:
            # the drive is over: fold the carried totals into the members so
            # the final snapshot AND compute() see them (non-writer ranks
            # never reach _payload, so this fold cannot ride it)
            self._fused_plan.fold_back()
        # final snapshot so a completed pass is restorable/auditable ...
        self.snapshot()
        if self._snapshotting_stalls:
            self._last_good_payload = self._payload()
        # ... then compute (which may sync across the process group) under the
        # same watchdog deadline
        compute = self.metric.compute_all if self._is_plan else self.metric.compute
        result = self._bounded(compute, "compute")
        if _obs_trace.ENABLED:
            # the evaluation is over: every plane (spans, xla records, state
            # bytes, sync bytes) is final — emit the cost ledger. compute()
            # already emitted for Metric/MetricCollection targets; this
            # covers custom update_fn targets too, newest write wins.
            _obs_attr.maybe_emit()
        return result

    def _drive_impl(self, batches: Iterable[Any], skip: int) -> Any:
        self._begin_drive(skip)
        stream = iter(batches)
        skipped = 0
        while skipped < skip:
            try:
                next(stream)
            except StopIteration:
                raise ValueError(
                    f"cannot fast-forward: the stream ended after {skipped} batch(es) but the"
                    f" snapshot cursor is {skip} — resume() needs the same stream the"
                    " interrupted run consumed"
                ) from None
            skipped += 1
        for batch in stream:
            self._step_impl(batch)
        return self._finish_drive()

    # --------------------------------------------------------- open-loop serve
    def serve_open(self) -> int:
        """Open the evaluator for open-loop (service) driving; returns the
        cursor to serve from.

        Unlike :meth:`resume`, no fast-forward happens: the newest valid
        snapshot (if any) is restored through the same validate-all-then-apply
        ladder, and the CALLER — the ``metricserve`` ingest protocol —
        positions its feed at the returned cursor. A fresh store opens at 0.
        Pair every open with :meth:`serve_close`; batches arrive one at a
        time through :meth:`serve_step`.
        """
        restored = self.store.latest(validate=self._validate_payload) if self.store is not None else None
        start = 0
        if restored is not None:
            _step, payload = restored
            # _validate_payload already installed the checkpoint
            start = int(payload["cursor"])
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_counters.inc("runner.resume")
        self._begin_drive(start)
        # forced: the serve daemon's /healthz reads these probes even when
        # the live publisher is off
        self._register_probes(force=True)
        return start

    def serve_step(self, batch: Any) -> None:
        """Apply ONE batch under the drive invariants (watchdog, windows,
        cadence snapshots, fault points) — the service's per-ingest step."""
        self._step_impl(batch)

    def serve_skip(self) -> None:
        """Advance the cursor past ONE batch WITHOUT applying it — the serve
        plane's poison-batch escape hatch. The skipped seq still moves the
        durable watermark (window rotation + cadence snapshot run as if the
        batch had been applied), so a restore after the skip does not ask the
        client to replay the quarantined batch."""
        self.cursor += 1
        if self.window_ring is not None:
            self.window_ring.observe(self.cursor)
        self._maybe_snapshot()

    def serve_close(self) -> Any:
        """Final snapshot + compute, then release the live probes. The
        returned value is :meth:`~SlicedPlan.compute_all` for plan targets,
        ``metric.compute()`` otherwise — same contract as :meth:`run`."""
        try:
            return self._finish_drive()
        finally:
            self._unregister_probes()
