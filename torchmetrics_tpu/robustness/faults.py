# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Deterministic fault injection for the sync and restore paths.

Production TPU fleets lose hosts, corrupt DCN payloads, and preempt workers
mid-epoch; code that only ever runs on the happy path is untested exactly
where it matters most. This module plants **zero-cost-when-off** injection
points inside ``Metric.sync()`` / ``utilities/distributed.py`` /
``Metric.update`` and the durability layer (``CheckpointStore`` /
``StreamingEvaluator``) so tests (single-process and the real 2-process
``jax.distributed`` suite) can rehearse those failures deterministically.

Injection points
----------------

=========================  =====================  ==================================
point                      kinds                  fires
=========================  =====================  ==================================
``sync.attempt``           fail, delay            at the start of every ``Metric.sync`` attempt
``sync.state_gather``      fail, delay            before each state's gather inside ``_sync_dist``
                                                  (use ``after=`` to leave earlier states
                                                  overwritten — a genuine mid-sync failure)
``gather_bytes.pre``       fail, delay            before the object-gather collective
``gather_bytes.payload``   corrupt, truncate      on the wire buffer of ``_gather_objects_via_bytes``
``sync.sketch_state``      corrupt                on the per-rank gathered sketch states of a
                                                  ``dist_reduce_fx="merge"`` sync (``arg`` = which
                                                  rank's payload to mangle; fires in lockstep on
                                                  every process so the group agrees on the error)
``update.preempt``         preempt                after a completed ``Metric.update`` (raises
                                                  :class:`SimulatedPreemption` — checkpoint/restore drills)
``runner.preempt``         preempt                in ``StreamingEvaluator`` after batch k is applied,
                                                  BEFORE its snapshot (``after=k`` kills at batch k+1 —
                                                  kill-and-resume drills)
``store.write.torn``       fail, preempt          in ``CheckpointStore.save`` between the temp write
                                                  and the rename: the temp file survives, the manifest
                                                  never references it (a torn write)
``store.payload``          corrupt, truncate      on the snapshot bytes as written to disk; the
                                                  manifest keeps the TRUE crc, so ``latest()`` detects
                                                  the bitrot and falls back
``feed.stage``             fail, delay            on the ``DeviceFeed`` staging thread, per batch
                                                  staged — the captured error must propagate to the
                                                  consumer's next ``get()``, never stall the drive
                                                  loop until the watchdog
``serve.accept``           fail, delay            in ``ServeDaemon.create_stream`` before the spec
                                                  is admitted — a rejected create must leave no
                                                  stream directory behind
``serve.ingest``           fail, delay            in ``Stream.offer`` after decode, before the
                                                  batch is admitted to the queue — a failed
                                                  admission must NOT advance ``next_seq`` (the
                                                  client retries the same seq)
``serve.drain``            fail, delay, preempt   at the top of ``Stream.drain`` — a daemon killed
                                                  mid-drain must restart from the last snapshot
                                                  with no double count
``serve.worker.crash``     fail, delay, preempt   in the stream worker immediately before a batch
                                                  is applied — the supervisor must restart the
                                                  worker and replay the retained batch;
                                                  ``count >= poison_threshold`` turns the same
                                                  batch into a dead-letter quarantine drill
``store.write.enospc``     fail                   in ``CheckpointStore.save`` just before the
                                                  atomic write — surfaces as ``OSError(ENOSPC)``,
                                                  the disk-exhaustion degradation drill
``deadletter.write``       fail                   before a stream's ``deadletter.jsonl`` rewrite —
                                                  surfaces as ``OSError(ENOSPC)``; quarantine must
                                                  stay in memory and re-persist when disk recovers
=========================  =====================  ==================================

Every point above is registered in :data:`KNOWN_POINTS`;
:func:`install_from_env` rejects a ``TM_TPU_FAULTS`` entry naming anything
else, so a typo'd chaos schedule fails loudly instead of silently never
firing. In-process :func:`inject` accepts arbitrary points (tests plant
private ones).

Faults are scoped with the :func:`inject` context manager (in-process tests)
or installed from the ``TM_TPU_FAULTS`` environment variable (subprocess
workers), e.g.::

    TM_TPU_FAULTS="corrupt:gather_bytes.payload:rank=1;fail:sync.attempt:count=2"

Grammar: ``;``-separated faults, each ``kind:point[:key=value]*`` with keys
``rank`` (only that ``jax.process_index()``; default all), ``after`` (skip
the first N matching hits), ``count`` (fire at most N times; default
unbounded), ``arg`` (seconds for ``delay``, bytes for ``corrupt``/``truncate``).
All injection is deterministic — no randomness — so 2-process scenarios stay
in lockstep and failures reproduce bit-for-bit.
"""
from __future__ import annotations

import os
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Iterator, List, Optional

_KINDS = ("fail", "delay", "corrupt", "truncate", "preempt")

#: every injection point wired into the codebase (the docstring table above).
#: ``install_from_env`` validates against this set; ``inject``/``install``
#: deliberately do not, so tests can plant private points.
KNOWN_POINTS = frozenset(
    {
        "sync.attempt",
        "sync.state_gather",
        "sync.state_apply",
        "sync.sketch_state",
        "gather_arrays.pre",
        "gather_bytes.pre",
        "gather_bytes.payload",
        "update.preempt",
        "runner.preempt",
        "store.write.torn",
        "store.write.enospc",
        "store.payload",
        "feed.stage",
        "serve.accept",
        "serve.ingest",
        "serve.drain",
        "serve.worker.crash",
        "deadletter.write",
    }
)


class FaultInjected(RuntimeError):
    """Raised by a ``fail`` fault — stands in for a transient transport error."""


class SimulatedPreemption(RuntimeError):
    """Raised by a ``preempt`` fault — stands in for host preemption between updates."""


@dataclass
class Fault:
    """One deterministic fault at one injection point."""

    kind: str
    point: str
    rank: Optional[int] = None  # None = every process
    after: int = 0  # skip the first `after` matching hits
    count: Optional[int] = None  # fire at most `count` times (None = unbounded)
    arg: float = 1.0  # delay seconds / corrupt-truncate byte count
    _hits: int = field(default=0, repr=False, compare=False)

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; expected one of {_KINDS}")
        if self.after < 0 or (self.count is not None and self.count < 0):
            raise ValueError("`after` and `count` must be non-negative")

    def _should_fire(self, point: str, rank: int) -> bool:
        """Match + hit accounting: a matching call counts as a hit whether or
        not it fires, so ``after``/``count`` windows are deterministic."""
        if point != self.point or (self.rank is not None and rank != self.rank):
            return False
        hit = self._hits
        self._hits = hit + 1
        if hit < self.after:
            return False
        return self.count is None or hit < self.after + self.count


#: the live fault list. Hot paths guard with ``if faults._ACTIVE:`` — one
#: attribute load + truth test when no faults are installed.
_ACTIVE: List[Fault] = []


def active() -> bool:
    """True when any fault is installed."""
    return bool(_ACTIVE)


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def install(*faults: Fault) -> None:
    """Install faults for the rest of the process (tests prefer :func:`inject`)."""
    _ACTIVE.extend(faults)


def clear() -> None:
    """Remove every installed fault and reset hit counters."""
    for f in _ACTIVE:
        f._hits = 0
    del _ACTIVE[:]


@contextmanager
def inject(*faults: Fault) -> Iterator[None]:
    """Scope faults to a ``with`` block; counters reset on exit."""
    _ACTIVE.extend(faults)
    try:
        yield
    finally:
        for f in faults:
            f._hits = 0
            # remove by IDENTITY: dataclass equality would match (and evict)
            # a distinct but equal fault installed by e.g. TM_TPU_FAULTS
            for i, installed in enumerate(_ACTIVE):
                if installed is f:
                    del _ACTIVE[i]
                    break


def fire(point: str) -> None:
    """Trigger ``fail``/``delay``/``preempt`` faults registered at ``point``."""
    if not _ACTIVE:
        return
    rank = _rank()
    for f in _ACTIVE:
        if f.kind in ("fail", "delay", "preempt") and f._should_fire(point, rank):
            if f.kind == "delay":
                time.sleep(f.arg)
            elif f.kind == "preempt":
                raise SimulatedPreemption(f"injected preemption at {point!r} (rank {rank})")
            else:
                raise FaultInjected(f"injected failure at {point!r} (rank {rank})")


def mutate_bytes(point: str, data: bytes, header_len: int = 0) -> bytes:
    """Apply ``corrupt``/``truncate`` faults registered at ``point`` to a wire
    buffer, leaving the first ``header_len`` bytes intact (corruption strikes
    the payload, so integrity headers can detect it)."""
    if not _ACTIVE:
        return data
    rank = _rank()
    for f in _ACTIVE:
        if f.kind in ("corrupt", "truncate") and f._should_fire(point, rank):
            n = max(1, int(f.arg))
            if f.kind == "truncate":
                keep = max(header_len, len(data) - n)
                data = data[:keep]
            elif len(data) > header_len:
                lo = header_len + (len(data) - header_len) // 2
                window = data[lo : lo + n]
                data = data[:lo] + bytes(b ^ 0xFF for b in window) + data[lo + len(window) :]
    return data


def corrupt_index(point: str, n: int) -> Optional[int]:
    """Index (< ``n``) whose payload a ``corrupt`` fault at ``point`` asks the
    caller to mangle, or ``None``. ``arg`` selects the payload (rank) index;
    rank-unscoped faults fire identically on every process, keeping a
    multi-process group in lockstep about WHICH payload went bad."""
    if not _ACTIVE:
        return None
    rank = _rank()
    for f in _ACTIVE:
        if f.kind == "corrupt" and f._should_fire(point, rank):
            return int(f.arg) % max(n, 1)
    return None


def install_from_env(value: Optional[str] = None) -> List[Fault]:
    """Parse ``TM_TPU_FAULTS`` (or ``value``) and install the faults it names.

    Entries naming a point outside :data:`KNOWN_POINTS` raise ``ValueError``
    listing the valid points: a fault that can never fire is a chaos test
    silently testing nothing.
    """
    spec = os.environ.get("TM_TPU_FAULTS", "") if value is None else value
    faults: List[Fault] = []
    for item in filter(None, (part.strip() for part in spec.split(";"))):
        fields = item.split(":")
        if len(fields) < 2:
            raise ValueError(f"malformed TM_TPU_FAULTS entry {item!r}: expected 'kind:point[:key=value]*'")
        kind, point, kwargs = fields[0], fields[1], {}
        if point not in KNOWN_POINTS:
            raise ValueError(
                f"unknown TM_TPU_FAULTS point {point!r} in {item!r} — it would never fire;"
                f" known points: {', '.join(sorted(KNOWN_POINTS))}"
            )
        for opt in fields[2:]:
            key, _, val = opt.partition("=")
            if key not in ("rank", "after", "count", "arg"):
                raise ValueError(f"unknown TM_TPU_FAULTS option {key!r} in {item!r}")
            kwargs[key] = float(val) if key == "arg" else int(val)
        faults.append(Fault(kind=kind, point=point, **kwargs))
    install(*faults)
    return faults


if os.environ.get("TM_TPU_FAULTS"):
    install_from_env()
