# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Self-validating checkpoint helpers.

``Metric.save_checkpoint()`` captures the metric — and, via the deep metric
walk, every wrapper child — as one plain dict of host numpy arrays plus the
schema fingerprint, format version and update count. The dict round-trips
through orbax / msgpack / pickle unchanged, and ``Metric.load_checkpoint()``
re-validates everything before touching any state: a truncated payload, a
corrupted leaf, or a schema mismatch (different ``num_classes``, renamed
state, changed reduction) raises
:class:`~torchmetrics_tpu.utilities.exceptions.StateRestoreError` while the
live metric keeps its previous state — never a half-restored metric.
"""
from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Tuple

import numpy as np

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.robustness.spec import spec_fingerprint, validate_state_tree
from torchmetrics_tpu.utilities.exceptions import StateRestoreError

#: host-counter value types a checkpoint may carry. Counters holding runtime
#: objects (e.g. ``PerceptualPathLength``'s generator model) are execution
#: context, not restorable state — they are skipped on save so the checkpoint
#: stays a plain serializable dict, and left untouched on load.
_PLAIN_COUNTER_TYPES = (bool, int, float, str, bytes, type(None), np.ndarray, np.generic)

#: bump when the checkpoint layout changes; loaders refuse newer versions
CHECKPOINT_FORMAT_VERSION = 1

_ENTRY_KEYS = ("fingerprint", "update_count", "state")
_TOP_KEYS = ("format_version", "class", "fingerprint", "metrics")


def _walk(metric: Any) -> List[Tuple[str, Any]]:
    # the deep walk lives with the sharded regime; imported lazily to keep
    # robustness importable without the parallel machinery
    from torchmetrics_tpu.parallel.sharded import _walk_metrics

    return _walk_metrics(metric)


def checkpoint_fingerprint(metric: Any) -> str:
    """Digest over the spec fingerprints of the metric and every wrapper child."""
    canon = sorted((path, spec_fingerprint(m)) for path, m in _walk(metric))
    return hashlib.sha256(json.dumps(canon, separators=(",", ":")).encode()).hexdigest()[:16]


def save_checkpoint(metric: Any) -> Dict[str, Any]:
    """Snapshot ``metric`` (deep: wrapper children included) as a plain dict."""
    if _obs_trace.ENABLED:
        with _obs_trace.span("checkpoint.save", metric=type(metric).__name__):
            _obs_counters.inc("checkpoint.save")
            return _save_checkpoint(metric)
    return _save_checkpoint(metric)


def _serialize_state(value: Any) -> Any:
    """One state as plain host data: list states -> list of ndarrays, sketch
    states -> a marked ``{"__sketch__": class, "leaves": {...}}`` dict (so the
    checkpoint stays a plain serializable dict), arrays -> ndarray."""
    from torchmetrics_tpu.robustness.spec import SKETCH_PAYLOAD_KEY
    from torchmetrics_tpu.sketch.registry import is_sketch_state

    if isinstance(value, list):
        return [np.asarray(x) for x in value]
    if is_sketch_state(value):
        return {
            SKETCH_PAYLOAD_KEY: type(value).__name__,
            "leaves": {field: np.asarray(leaf) for field, leaf in zip(type(value)._fields, value)},
        }
    return np.asarray(value)


def _save_checkpoint(metric: Any) -> Dict[str, Any]:
    metrics: Dict[str, Any] = {}
    for path, m in _walk(metric):
        tree = m.state_tree(include_count=True)
        count = int(tree.pop("_update_count"))
        state = {name: _serialize_state(v) for name, v in tree.items()}
        metrics[path] = {
            "fingerprint": spec_fingerprint(m),
            "update_count": count,
            "state": state,
            "host_counters": {
                attr: getattr(m, attr)
                for attr in getattr(m, "_host_counters", ())
                if isinstance(getattr(m, attr), _PLAIN_COUNTER_TYPES)
            },
        }
    return {
        "format_version": CHECKPOINT_FORMAT_VERSION,
        "class": type(metric).__name__,
        "fingerprint": checkpoint_fingerprint(metric),
        "metrics": metrics,
    }


def load_checkpoint(metric: Any, checkpoint: Dict[str, Any], strict: bool = True) -> None:
    """Validate ``checkpoint`` end-to-end, then install it into ``metric``.

    Validation runs over EVERY entry before any state is applied, so a bad
    checkpoint leaves the metric untouched.
    """
    if _obs_trace.ENABLED:
        with _obs_trace.span("checkpoint.load", metric=type(metric).__name__, strict=strict):
            _obs_counters.inc("checkpoint.load")
            return _load_checkpoint(metric, checkpoint, strict=strict)
    return _load_checkpoint(metric, checkpoint, strict=strict)


def _load_checkpoint(metric: Any, checkpoint: Dict[str, Any], strict: bool = True) -> None:
    if not isinstance(checkpoint, dict):
        raise StateRestoreError(
            f"checkpoint for {type(metric).__name__} must be a dict, got {type(checkpoint).__name__} —"
            " truncated or corrupted payload?"
        )
    missing_top = [k for k in _TOP_KEYS if k not in checkpoint]
    if missing_top:
        raise StateRestoreError(
            f"checkpoint for {type(metric).__name__} is missing key(s) {missing_top} — truncated or corrupted payload?"
        )
    version = checkpoint["format_version"]
    if not isinstance(version, int) or version < 1 or version > CHECKPOINT_FORMAT_VERSION:
        raise StateRestoreError(
            f"checkpoint format_version {version!r} is not supported (this build reads <= {CHECKPOINT_FORMAT_VERSION})"
        )
    entries = checkpoint["metrics"]
    if not isinstance(entries, dict):
        raise StateRestoreError("checkpoint 'metrics' section must be a dict — truncated or corrupted payload?")

    walk = _walk(metric)
    live_paths = [path for path, _ in walk]
    if strict:
        extra = sorted(set(entries) - set(live_paths))
        absent = sorted(set(live_paths) - set(entries))
        if extra or absent:
            raise StateRestoreError(
                f"checkpoint structure does not match {type(metric).__name__}:"
                + (f" unexpected entries {extra}" if extra else "")
                + (f" missing entries {absent}" if absent else "")
            )

    # phase 1: validate every entry without mutating anything
    staged: List[Tuple[Any, Dict[str, Any], int, Dict[str, Any]]] = []
    for path, m in walk:
        entry = entries.get(path)
        if entry is None:
            continue  # non-strict: leave this child as-is
        where = f"{type(m).__name__} at {path!r}" if path else type(m).__name__
        if not isinstance(entry, dict) or any(k not in entry for k in _ENTRY_KEYS):
            raise StateRestoreError(f"checkpoint entry for {where} is malformed — truncated or corrupted payload?")
        if not isinstance(entry["state"], dict):
            raise StateRestoreError(f"checkpoint entry for {where}: 'state' must be a dict, got"
                                    f" {type(entry['state']).__name__}")
        validated = validate_state_tree(m, entry["state"], strict=strict)
        if entry["fingerprint"] != spec_fingerprint(m):
            # leaves are individually compatible but the registry still
            # disagrees (renamed reduction, extra state in non-strict, ...)
            raise StateRestoreError(
                f"checkpoint spec fingerprint mismatch for {where}: metric declares {spec_fingerprint(m)},"
                f" checkpoint was written with {entry['fingerprint']}"
            )
        counters = dict(entry.get("host_counters", {}))
        # counters restore via setattr: accept ONLY declared _host_counters
        # with plain values, or a corrupted payload could clobber arbitrary
        # metric attributes (e.g. ``_defaults``) despite passing state checks
        declared = set(getattr(m, "_host_counters", ()))
        bad = sorted(k for k in counters if k not in declared or not isinstance(counters[k], _PLAIN_COUNTER_TYPES))
        if bad:
            if strict:
                raise StateRestoreError(
                    f"checkpoint entry for {where} carries host counter(s) {bad} the metric does not declare"
                    " (or non-plain values) — corrupted payload?"
                )
            counters = {k: v for k, v in counters.items() if k not in bad}
        staged.append((m, validated, int(entry["update_count"]), counters))

    # phase 2: apply — every entry already validated (so this cannot
    # half-fail); the trusted installer skips re-validating what phase 1 did
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.sketch.registry import is_sketch_state

    def _to_device(v: Any) -> Any:
        # jnp.array, not asarray: asarray can alias the deserialized numpy
        # buffer zero-copy on CPU, and a later donated step would overwrite
        # memory jax does not own (nondeterministic state corruption)
        if isinstance(v, list):
            return [jnp.array(x) for x in v]
        if is_sketch_state(v):  # validation already reconstructed the pytree
            return jax.tree_util.tree_map(jnp.array, v)
        return jnp.array(v)

    for m, validated, count, counters in staged:
        tree = {name: _to_device(v) for name, v in validated.items()}
        tree["_update_count"] = count
        m._install_state_tree(tree)
        m._computed = None
        for attr, val in counters.items():
            setattr(m, attr, val)
