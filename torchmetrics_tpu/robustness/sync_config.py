# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Fault-tolerance policy for multi-host metric sync.

On a production fleet preemption and host loss are routine (ROADMAP
north-star); a straggler rank must not hang ``Metric.sync()`` forever and a
transient DCN hiccup must not abort an evaluation epoch. :class:`SyncConfig`
makes the policy explicit and threads through ``Metric.sync()`` /
``Metric.compute()``'s implicit sync.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

_ON_ERROR_CHOICES = ("raise", "local")


@dataclass(frozen=True)
class SyncConfig:
    """Policy for one cross-process state synchronization.

    Args:
        timeout_s: wall-clock budget for a single sync attempt. ``None``
            (default) calls the collectives directly; a number runs them on a
            daemon worker thread and raises
            :class:`~torchmetrics_tpu.utilities.exceptions.SyncError` when the
            budget elapses (last-resort straggler protection — an abandoned
            attempt's collective cannot be cancelled, so after a timeout the
            process group should be considered poisoned and re-initialized
            before the next sync).
        retries: additional attempts after the first failure. Every rank must
            use the same value — a retry re-enters the collective on all
            ranks, so divergent configs desynchronize the group.
        backoff_base_s: sleep before the first retry.
        backoff_factor: multiplier applied per further retry.
        backoff_max_s: cap on a single backoff sleep.
        on_error: ``"raise"`` (default) surfaces a ``SyncError`` once attempts
            are exhausted; ``"local"`` degrades to the metric's local-only
            state with a single :class:`SyncWarning` — best-effort eval
            logging keeps flowing with per-host values instead of dying.
    """

    timeout_s: Optional[float] = None
    retries: int = 0
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_max_s: float = 8.0
    on_error: str = "raise"

    def __post_init__(self) -> None:
        if self.on_error not in _ON_ERROR_CHOICES:
            raise ValueError(f"`on_error` must be one of {_ON_ERROR_CHOICES}, got {self.on_error!r}")
        if self.retries < 0:
            raise ValueError(f"`retries` must be >= 0, got {self.retries}")
        if self.timeout_s is not None and self.timeout_s <= 0:
            raise ValueError(f"`timeout_s` must be positive or None, got {self.timeout_s}")
        if self.backoff_base_s < 0 or self.backoff_factor < 1 or self.backoff_max_s < 0:
            raise ValueError(
                "backoff parameters must satisfy backoff_base_s >= 0, backoff_factor >= 1, backoff_max_s >= 0"
            )

    @property
    def attempts(self) -> int:
        return self.retries + 1

    def backoff(self, attempt: int) -> float:
        """Sleep before retrying after failed attempt ``attempt`` (0-based)."""
        return min(self.backoff_max_s, self.backoff_base_s * self.backoff_factor**attempt)


#: module default used when neither the metric nor the call provides a config
DEFAULT_SYNC_CONFIG = SyncConfig()
