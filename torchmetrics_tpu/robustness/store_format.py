# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""On-disk checkpoint-store format primitives — STDLIB ONLY.

This module defines the durable layout a :class:`~torchmetrics_tpu.robustness.
store.CheckpointStore` directory follows and every operation that needs no
metric semantics: atomic byte writes, CRC32 integrity, manifest read/write,
verification and retention pruning. It deliberately imports nothing beyond
the standard library so ``tools/metricdoctor.py`` can load it by file path
and verify/list/prune a checkpoint directory WITHOUT importing jax (the same
contract ``tools/metricscope.py`` keeps with ``torchmetrics_tpu.obs``).

Directory layout::

    <store>/
      manifest.json                  # see MANIFEST schema below
      snapshot-000000000004.ckpt     # pickled payload, CRC32 recorded in manifest
      snapshot-000000000006.ckpt
      snapshot-000000000008.ckpt.tmp-a1b2c3   # torn write: crash before os.replace

Manifest schema (version 1)::

    {"store_format_version": 1,
     "fingerprint": "<16-hex registry fingerprint or null>",
     "snapshots": [{"step": 4, "file": "snapshot-000000000004.ckpt",
                    "crc32": 123456789, "bytes": 4096}, ...]}   # ascending step

Every write is atomic: bytes land in a ``.tmp-*`` sibling, are fsync'd, and
``os.replace`` publishes them — a reader never observes a half-written
snapshot or manifest, only a missing one (torn write: the temp file survives,
the manifest never references it). Snapshot steps are strictly monotonic so
the newest valid snapshot is always the resume point.
"""
from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import Any, Dict, List, Optional, Tuple

MANIFEST_NAME = "manifest.json"
SNAPSHOT_PREFIX = "snapshot-"
SNAPSHOT_SUFFIX = ".ckpt"
STORE_FORMAT_VERSION = 1

_MANIFEST_KEYS = ("store_format_version", "fingerprint", "snapshots")
_ENTRY_KEYS = ("step", "file", "crc32", "bytes")


class StoreFormatError(ValueError):
    """The on-disk store violates the format contract (bad manifest, wrong
    version, non-monotonic steps). File-level damage to an individual
    snapshot is NOT this error — it is reported per-snapshot by
    :func:`verify_store` / skipped by ``CheckpointStore.latest()``."""


def snapshot_filename(step: int) -> str:
    """Canonical file name for the snapshot at ``step`` (zero-padded so
    lexicographic order equals step order)."""
    return f"{SNAPSHOT_PREFIX}{int(step):012d}{SNAPSHOT_SUFFIX}"


def payload_crc(data: bytes) -> int:
    """CRC32 of a snapshot payload as recorded in the manifest."""
    return zlib.crc32(data) & 0xFFFFFFFF


def _fsync_dir(directory: str) -> None:
    # directory fsync publishes the rename itself; best-effort on platforms
    # (or filesystems) that refuse O_RDONLY directory descriptors
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` atomically: temp sibling + fsync +
    ``os.replace`` + directory fsync. A crash at any point leaves either the
    old file or the new one — never a torn ``path``."""
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp-", dir=directory)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(directory)


def empty_manifest(fingerprint: Optional[str] = None) -> Dict[str, Any]:
    return {"store_format_version": STORE_FORMAT_VERSION, "fingerprint": fingerprint, "snapshots": []}


def read_manifest(directory: str) -> Optional[Dict[str, Any]]:
    """Parse and structurally validate ``manifest.json``.

    Returns ``None`` when no manifest exists (fresh/empty store); raises
    :class:`StoreFormatError` on a malformed or wrong-version manifest —
    the store as a whole is unusable then, there is nothing to fall back to.
    """
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r") as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as err:
        raise StoreFormatError(f"unreadable checkpoint-store manifest {path}: {err}") from err
    if not isinstance(manifest, dict) or any(k not in manifest for k in _MANIFEST_KEYS):
        raise StoreFormatError(f"malformed checkpoint-store manifest {path}: expected keys {_MANIFEST_KEYS}")
    version = manifest["store_format_version"]
    if not isinstance(version, int) or version < 1 or version > STORE_FORMAT_VERSION:
        raise StoreFormatError(
            f"checkpoint-store format version {version!r} is not supported"
            f" (this build reads <= {STORE_FORMAT_VERSION})"
        )
    entries = manifest["snapshots"]
    if not isinstance(entries, list) or any(
        not isinstance(e, dict) or any(k not in e for k in _ENTRY_KEYS) for e in entries
    ):
        raise StoreFormatError(f"malformed snapshot list in {path}: each entry needs keys {_ENTRY_KEYS}")
    steps = [int(e["step"]) for e in entries]
    if steps != sorted(set(steps)):
        raise StoreFormatError(f"snapshot steps in {path} are not strictly increasing: {steps}")
    return manifest


def write_manifest(directory: str, manifest: Dict[str, Any]) -> None:
    atomic_write(
        os.path.join(directory, MANIFEST_NAME),
        json.dumps(manifest, indent=1, sort_keys=True).encode(),
    )


def read_snapshot_bytes(directory: str, entry: Dict[str, Any]) -> bytes:
    """Read one manifest entry's payload, enforcing the recorded CRC32.

    Raises ``FileNotFoundError`` for a deleted snapshot and
    :class:`StoreFormatError` for a size or CRC mismatch (bitrot, torn
    content) — callers decide whether to fall back or surface.
    """
    path = os.path.join(directory, entry["file"])
    with open(path, "rb") as fh:
        data = fh.read()
    if len(data) != int(entry["bytes"]):
        raise StoreFormatError(
            f"snapshot {entry['file']} (step {entry['step']}) is {len(data)} bytes,"
            f" manifest records {entry['bytes']} — torn or truncated payload"
        )
    crc = payload_crc(data)
    if crc != int(entry["crc32"]):
        raise StoreFormatError(
            f"snapshot {entry['file']} (step {entry['step']}) fails its CRC32 check"
            f" (got {crc}, manifest records {entry['crc32']}) — corrupt payload"
        )
    return data


def temp_files(directory: str) -> List[str]:
    """Orphaned ``.tmp-*`` files: the residue of torn writes (crash between
    temp publish and rename). Never referenced by the manifest; safe to prune."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(n for n in names if ".tmp-" in n)


def verify_store(directory: str) -> Dict[str, Any]:
    """Full integrity report for one store directory.

    Returns ``{"ok": bool, "manifest_ok": bool, "problems": [str, ...],
    "snapshots": [{"step", "file", "bytes", "valid", "problem"}, ...],
    "torn_temp_files": [...], "fingerprint": ...}``. ``ok`` means the
    manifest parses AND every listed snapshot passes its size+CRC check;
    torn temp files are reported but are not failures (they are expected
    debris after a crash-during-save).
    """
    report: Dict[str, Any] = {
        "ok": True,
        "manifest_ok": True,
        "fingerprint": None,
        "problems": [],
        "snapshots": [],
        "torn_temp_files": temp_files(directory),
    }
    if not os.path.isdir(directory):
        report["ok"] = report["manifest_ok"] = False
        report["problems"].append(f"not a directory: {directory}")
        return report
    try:
        manifest = read_manifest(directory)
    except StoreFormatError as err:
        report["ok"] = report["manifest_ok"] = False
        report["problems"].append(str(err))
        return report
    if manifest is None:
        report["problems"].append("no manifest.json — empty or never-written store")
        return report
    report["fingerprint"] = manifest["fingerprint"]
    for entry in manifest["snapshots"]:
        row = {"step": int(entry["step"]), "file": entry["file"], "bytes": int(entry["bytes"]),
               "valid": True, "problem": None}
        try:
            read_snapshot_bytes(directory, entry)
        except FileNotFoundError:
            row["valid"] = False
            row["problem"] = "missing file (manifest points at a deleted snapshot)"
        except (OSError, StoreFormatError) as err:
            row["valid"] = False
            row["problem"] = str(err)
        if not row["valid"]:
            report["ok"] = False
            report["problems"].append(f"step {row['step']}: {row['problem']}")
        report["snapshots"].append(row)
    return report


def prune_entries(
    directory: str, manifest: Dict[str, Any], keep_last: Optional[int], drop_temp: bool = True
) -> Tuple[Dict[str, Any], List[str]]:
    """Apply ``keep_last`` retention: drop the oldest manifest entries beyond
    the newest ``keep_last`` and delete their files (manifest first, so a
    crash mid-prune leaves unreferenced files, never dangling references).

    Returns ``(new_manifest, removed_file_names)``. ``keep_last=None`` keeps
    everything (still drops torn temp files when ``drop_temp``).
    """
    removed: List[str] = []
    entries = list(manifest["snapshots"])
    if keep_last is not None and keep_last >= 0 and len(entries) > keep_last:
        victims = entries[: len(entries) - keep_last]
        manifest = dict(manifest, snapshots=entries[len(entries) - keep_last:])
        write_manifest(directory, manifest)
        for entry in victims:
            try:
                os.unlink(os.path.join(directory, entry["file"]))
            except OSError:
                pass  # already gone — the manifest no longer references it
            removed.append(entry["file"])
    if drop_temp:
        for name in temp_files(directory):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass
            removed.append(name)
    return manifest, removed
