# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Robustness layer: validated state restore, fault-tolerant sync, fault injection.

Three fronts (ARCHITECTURE.md §9):

- :mod:`~torchmetrics_tpu.robustness.spec` — per-state specs, a stable
  registry fingerprint, and restore-time validation behind
  ``Metric.load_state_tree(strict=...)``.
- :mod:`~torchmetrics_tpu.robustness.checkpoint` — self-validating
  ``Metric.save_checkpoint()`` / ``load_checkpoint()`` dict helpers.
- :mod:`~torchmetrics_tpu.robustness.sync_config` /
  :mod:`~torchmetrics_tpu.robustness.faults` — :class:`SyncConfig`
  (timeout/retries/backoff/degrade-to-local) threaded through
  ``Metric.sync()``, plus the deterministic fault-injection harness the
  tests drive it with.

And the durability layer on top (ARCHITECTURE.md §12):

- :mod:`~torchmetrics_tpu.robustness.store` — :class:`CheckpointStore`:
  atomic (temp + fsync + ``os.replace``), CRC32-verified, retention-pruned,
  rank-aware snapshot directory with a torn/corrupt-skipping ``latest()``
  recovery ladder (inspect offline with ``tools/metricdoctor.py``).
- :mod:`~torchmetrics_tpu.robustness.runner` — :class:`StreamingEvaluator`:
  preemption-safe evaluation over a batch stream with an exactly-once batch
  cursor, snapshot-every-N/T policies, ``resume()`` fast-forward, and a
  stall watchdog.
"""
from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.robustness.checkpoint import (
    CHECKPOINT_FORMAT_VERSION,
    checkpoint_fingerprint,
    load_checkpoint,
    save_checkpoint,
)
from torchmetrics_tpu.robustness.guard import (
    GUARD_POLICIES,
    GUARD_STATES,
    ArgSpec,
    DomainContract,
    GuardVerdict,
    check_batch,
    enable_guard,
    guard_counters,
    guard_ineligibility,
    guarded_policy,
    state_finiteness,
)
from torchmetrics_tpu.robustness.runner import StreamingEvaluator
from torchmetrics_tpu.robustness.spec import StateSpec, build_state_specs, spec_fingerprint, validate_state_tree
from torchmetrics_tpu.robustness.store import CheckpointStore
from torchmetrics_tpu.robustness.sync_config import DEFAULT_SYNC_CONFIG, SyncConfig

__all__ = [
    "CHECKPOINT_FORMAT_VERSION",
    "ArgSpec",
    "CheckpointStore",
    "DEFAULT_SYNC_CONFIG",
    "DomainContract",
    "GUARD_POLICIES",
    "GUARD_STATES",
    "GuardVerdict",
    "StateSpec",
    "StreamingEvaluator",
    "SyncConfig",
    "build_state_specs",
    "check_batch",
    "checkpoint_fingerprint",
    "enable_guard",
    "faults",
    "guard_counters",
    "guard_ineligibility",
    "guarded_policy",
    "load_checkpoint",
    "save_checkpoint",
    "spec_fingerprint",
    "state_finiteness",
]
