// Copyright The TorchMetrics-TPU contributors.
// Licensed under the Apache License, Version 2.0.
//
// COCO run-length-encoding mask codec.
//
// Native replacement for the pycocotools C extension (`mask.pyx` /
// `maskApi.c`) that the reference delegates RLE work to
// (reference detection/mean_ap.py:824-857): encode/decode of Fortran-order
// binary masks, run areas, and crowd-aware IoU between RLE pairs. RLE is
// byte-string/run work — branchy, sequential, host-native — which is why it
// lives in C++ rather than XLA (SURVEY.md §2.6).
//
// Format: counts[] holds alternating run lengths over the column-major
// flattened mask, starting with the number of leading zeros.

#include <cstdint>
#include <cstddef>
#include <algorithm>

extern "C" {

// Encode a column-major binary mask into run lengths.
// counts_out must have room for size+1 entries; returns the run count.
uint64_t rle_encode(const uint8_t* mask, uint64_t size, uint32_t* counts_out) {
    uint64_t n = 0;
    uint8_t current = 0;  // runs start with zeros
    uint64_t run = 0;
    for (uint64_t i = 0; i < size; ++i) {
        uint8_t v = mask[i] ? 1 : 0;
        if (v != current) {
            counts_out[n++] = static_cast<uint32_t>(run);
            run = 0;
            current = v;
        }
        ++run;
    }
    counts_out[n++] = static_cast<uint32_t>(run);
    return n;
}

// Decode run lengths back into a column-major binary mask of `size` bytes.
void rle_decode(const uint32_t* counts, uint64_t n, uint8_t* mask_out, uint64_t size) {
    uint64_t pos = 0;
    uint8_t value = 0;
    for (uint64_t i = 0; i < n && pos < size; ++i) {
        uint64_t run = counts[i];
        if (run > size - pos) run = size - pos;
        for (uint64_t j = 0; j < run; ++j) mask_out[pos + j] = value;
        pos += run;
        value = 1 - value;
    }
}

// Total foreground area (sum of odd-indexed runs).
uint64_t rle_area(const uint32_t* counts, uint64_t n) {
    uint64_t area = 0;
    for (uint64_t i = 1; i < n; i += 2) area += counts[i];
    return area;
}

// Intersection area of two RLEs via a two-pointer run walk.
static uint64_t rle_intersection(const uint32_t* a, uint64_t na, const uint32_t* b, uint64_t nb) {
    uint64_t ia = 0, ib = 0;          // run indices
    uint64_t ea = a[0], eb = b[0];    // absolute end positions of current runs
    uint64_t pos = 0;                 // current absolute position
    uint64_t inter = 0;
    while (ia < na && ib < nb) {
        uint64_t next = std::min(ea, eb);
        if ((ia & 1) && (ib & 1)) inter += next - pos;  // both in a 1-run
        pos = next;
        if (ea == next) { ++ia; if (ia < na) ea += a[ia]; }
        if (eb == next) { ++ib; if (ib < nb) eb += b[ib]; }
    }
    return inter;
}

// Crowd-aware IoU between one detection RLE and one ground-truth RLE
// (pycocotools semantics: iscrowd => union = area(dt)).
double rle_iou_pair(const uint32_t* dt, uint64_t ndt, const uint32_t* gt, uint64_t ngt, int iscrowd) {
    uint64_t inter = rle_intersection(dt, ndt, gt, ngt);
    uint64_t area_dt = rle_area(dt, ndt);
    uint64_t area_gt = rle_area(gt, ngt);
    uint64_t uni = iscrowd ? area_dt : area_dt + area_gt - inter;
    if (uni == 0) return 0.0;
    return static_cast<double>(inter) / static_cast<double>(uni);
}

// Full IoU matrix between D detection and G ground-truth RLEs.
// Flattened run buffers with per-mask offsets/lengths; out is row-major (D, G).
void rle_iou_matrix(
    const uint32_t* dt_runs, const uint64_t* dt_offsets, const uint64_t* dt_lengths, uint64_t n_dt,
    const uint32_t* gt_runs, const uint64_t* gt_offsets, const uint64_t* gt_lengths, uint64_t n_gt,
    const uint8_t* gt_iscrowd, double* out) {
    for (uint64_t d = 0; d < n_dt; ++d) {
        for (uint64_t g = 0; g < n_gt; ++g) {
            out[d * n_gt + g] = rle_iou_pair(
                dt_runs + dt_offsets[d], dt_lengths[d],
                gt_runs + gt_offsets[g], gt_lengths[g],
                gt_iscrowd ? gt_iscrowd[g] : 0);
        }
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Polygon -> RLE rasterization, following the published COCO convention
// (pycocotools maskApi `rleFrPoly`): vertices are upsampled 5x, the boundary
// is traced with integer line stepping, downsampled crossings per column give
// the y-boundary points, and sorted crossing positions become run lengths
// (even-odd fill in column-major order).

#include <cmath>
#include <cstdlib>
#include <vector>

extern "C" {

// xy: k vertex pairs (x0, y0, x1, y1, ...); out buffer sized h*w+2.
// Returns the number of runs written.
uint64_t rle_from_polygon(const double* xy, uint64_t k, uint64_t h, uint64_t w,
                          uint32_t* counts_out) {
    const double scale = 5.0;
    std::vector<long> x(k + 1), y(k + 1);
    for (uint64_t j = 0; j < k; ++j) {
        x[j] = static_cast<long>(scale * xy[2 * j + 0] + 0.5);
        y[j] = static_cast<long>(scale * xy[2 * j + 1] + 0.5);
    }
    x[k] = x[0];
    y[k] = y[0];

    // dense boundary points via integer line stepping
    std::vector<long> u, v;
    for (uint64_t j = 0; j < k; ++j) {
        long xs = x[j], xe = x[j + 1], ys = y[j], ye = y[j + 1];
        long dx = std::labs(xe - xs), dy = std::labs(ys - ye);
        bool flip = (dx >= dy && xs > xe) || (dx < dy && ys > ye);
        if (flip) { std::swap(xs, xe); std::swap(ys, ye); }
        double s = dx >= dy ? static_cast<double>(ye - ys) / std::max<long>(dx, 1)
                            : static_cast<double>(xe - xs) / std::max<long>(dy, 1);
        if (dx >= dy) {
            for (long d = 0; d <= dx; ++d) {
                long t = flip ? dx - d : d;
                u.push_back(t + xs);
                v.push_back(static_cast<long>(ys + s * t + 0.5));
            }
        } else {
            for (long d = 0; d <= dy; ++d) {
                long t = flip ? dy - d : d;
                v.push_back(t + ys);
                u.push_back(static_cast<long>(xs + s * t + 0.5));
            }
        }
    }

    // column crossings, downsampled back to the pixel grid
    std::vector<uint32_t> a;
    for (size_t j = 1; j < u.size(); ++j) {
        if (u[j] == u[j - 1]) continue;
        double xd = static_cast<double>(u[j] < u[j - 1] ? u[j] : u[j] - 1);
        xd = (xd + 0.5) / scale - 0.5;
        if (std::floor(xd) != xd || xd < 0 || xd > static_cast<double>(w) - 1.0) continue;
        double yd = static_cast<double>(v[j] < v[j - 1] ? v[j] : v[j - 1]);
        yd = (yd + 0.5) / scale - 0.5;
        if (yd < 0) yd = 0;
        else if (yd > static_cast<double>(h)) yd = static_cast<double>(h);
        yd = std::ceil(yd);
        a.push_back(static_cast<uint32_t>(xd * static_cast<double>(h) + yd));
    }

    // even-odd fill: sorted crossing positions delta-encode into runs
    a.push_back(static_cast<uint32_t>(h * w));
    std::sort(a.begin(), a.end());
    uint32_t prev = 0;
    for (auto& val : a) {
        uint32_t t = val;
        val -= prev;
        prev = t;
    }
    std::vector<uint32_t> b;
    size_t j = 0;
    b.push_back(a[j++]);
    while (j < a.size()) {
        if (a[j] > 0) {
            b.push_back(a[j++]);
        } else {
            ++j;
            if (j < a.size()) b[b.size() - 1] += a[j++];
        }
    }
    for (size_t i = 0; i < b.size(); ++i) counts_out[i] = b[i];
    return b.size();
}

}  // extern "C"
