// Copyright The TorchMetrics-TPU contributors.
// Licensed under the Apache License, Version 2.0.
//
// COCO run-length-encoding mask codec.
//
// Native replacement for the pycocotools C extension (`mask.pyx` /
// `maskApi.c`) that the reference delegates RLE work to
// (reference detection/mean_ap.py:824-857): encode/decode of Fortran-order
// binary masks, run areas, and crowd-aware IoU between RLE pairs. RLE is
// byte-string/run work — branchy, sequential, host-native — which is why it
// lives in C++ rather than XLA (SURVEY.md §2.6).
//
// Format: counts[] holds alternating run lengths over the column-major
// flattened mask, starting with the number of leading zeros.

#include <cstdint>
#include <cstddef>
#include <algorithm>

extern "C" {

// Encode a column-major binary mask into run lengths.
// counts_out must have room for size+1 entries; returns the run count.
uint64_t rle_encode(const uint8_t* mask, uint64_t size, uint32_t* counts_out) {
    uint64_t n = 0;
    uint8_t current = 0;  // runs start with zeros
    uint64_t run = 0;
    for (uint64_t i = 0; i < size; ++i) {
        uint8_t v = mask[i] ? 1 : 0;
        if (v != current) {
            counts_out[n++] = static_cast<uint32_t>(run);
            run = 0;
            current = v;
        }
        ++run;
    }
    counts_out[n++] = static_cast<uint32_t>(run);
    return n;
}

// Decode run lengths back into a column-major binary mask of `size` bytes.
void rle_decode(const uint32_t* counts, uint64_t n, uint8_t* mask_out, uint64_t size) {
    uint64_t pos = 0;
    uint8_t value = 0;
    for (uint64_t i = 0; i < n && pos < size; ++i) {
        uint64_t run = counts[i];
        if (run > size - pos) run = size - pos;
        for (uint64_t j = 0; j < run; ++j) mask_out[pos + j] = value;
        pos += run;
        value = 1 - value;
    }
}

// Total foreground area (sum of odd-indexed runs).
uint64_t rle_area(const uint32_t* counts, uint64_t n) {
    uint64_t area = 0;
    for (uint64_t i = 1; i < n; i += 2) area += counts[i];
    return area;
}

// Intersection area of two RLEs via a two-pointer run walk.
static uint64_t rle_intersection(const uint32_t* a, uint64_t na, const uint32_t* b, uint64_t nb) {
    uint64_t ia = 0, ib = 0;          // run indices
    uint64_t ea = a[0], eb = b[0];    // absolute end positions of current runs
    uint64_t pos = 0;                 // current absolute position
    uint64_t inter = 0;
    while (ia < na && ib < nb) {
        uint64_t next = std::min(ea, eb);
        if ((ia & 1) && (ib & 1)) inter += next - pos;  // both in a 1-run
        pos = next;
        if (ea == next) { ++ia; if (ia < na) ea += a[ia]; }
        if (eb == next) { ++ib; if (ib < nb) eb += b[ib]; }
    }
    return inter;
}

// Crowd-aware IoU between one detection RLE and one ground-truth RLE
// (pycocotools semantics: iscrowd => union = area(dt)).
double rle_iou_pair(const uint32_t* dt, uint64_t ndt, const uint32_t* gt, uint64_t ngt, int iscrowd) {
    uint64_t inter = rle_intersection(dt, ndt, gt, ngt);
    uint64_t area_dt = rle_area(dt, ndt);
    uint64_t area_gt = rle_area(gt, ngt);
    uint64_t uni = iscrowd ? area_dt : area_dt + area_gt - inter;
    if (uni == 0) return 0.0;
    return static_cast<double>(inter) / static_cast<double>(uni);
}

// Full IoU matrix between D detection and G ground-truth RLEs.
// Flattened run buffers with per-mask offsets/lengths; out is row-major (D, G).
void rle_iou_matrix(
    const uint32_t* dt_runs, const uint64_t* dt_offsets, const uint64_t* dt_lengths, uint64_t n_dt,
    const uint32_t* gt_runs, const uint64_t* gt_offsets, const uint64_t* gt_lengths, uint64_t n_gt,
    const uint8_t* gt_iscrowd, double* out) {
    for (uint64_t d = 0; d < n_dt; ++d) {
        for (uint64_t g = 0; g < n_gt; ++g) {
            out[d * n_gt + g] = rle_iou_pair(
                dt_runs + dt_offsets[d], dt_lengths[d],
                gt_runs + gt_offsets[g], gt_lengths[g],
                gt_iscrowd ? gt_iscrowd[g] : 0);
        }
    }
}

}  // extern "C"
