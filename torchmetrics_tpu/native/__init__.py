# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Native (C++) host extensions.

- ``rle_codec.cpp`` — COCO RLE mask codec (encode/decode/area/IoU/polygon).
- ``edit_distance.cpp`` — batched Levenshtein DP for the text error rates.

Each source is compiled on first use with the system ``g++`` into a cached
shared object (keyed by source hash) and bound via ``ctypes``. Pure-numpy
fallbacks keep everything working where no compiler exists — an involuntary
fallback warns exactly once per extension; set ``TM_TPU_DISABLE_NATIVE=1``
to skip native compilation deliberately (and silently).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Dict, Optional, Sequence

_HERE = Path(__file__).parent
_libs: Dict[str, Optional[ctypes.CDLL]] = {}

#: operator escape hatch: force the numpy fallbacks without touching g++
_DISABLE_ENV = "TM_TPU_DISABLE_NATIVE"


def _native_disabled() -> bool:
    # read per call (not at import) so tests and operators can toggle live;
    # callers hit this at most a handful of times per metric evaluation
    return os.environ.get(_DISABLE_ENV, "0") == "1"


def _build_library(stem: str, extra_flags: Sequence[str] = ()) -> Optional[ctypes.CDLL]:
    """Compile ``<stem>.cpp`` with g++ (cached by source hash)."""
    src = _HERE / f"{stem}.cpp"
    try:
        src_hash = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    except OSError:
        return None  # source not shipped — callers use their numpy fallbacks
    cache_dir = Path(os.environ.get("TM_TPU_NATIVE_CACHE", Path(tempfile.gettempdir()) / "tm_tpu_native"))
    cache_dir.mkdir(parents=True, exist_ok=True)
    so_path = cache_dir / f"{stem}_{src_hash}.so"
    if not so_path.exists():
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", *extra_flags, str(src), "-o", str(so_path)]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (subprocess.SubprocessError, FileNotFoundError):
            return None
    try:
        return ctypes.CDLL(str(so_path))
    except OSError:
        return None


def _bind_rle(lib: ctypes.CDLL) -> None:
    lib.rle_encode.restype = ctypes.c_uint64
    lib.rle_encode.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
    lib.rle_decode.restype = None
    lib.rle_decode.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64]
    lib.rle_area.restype = ctypes.c_uint64
    lib.rle_area.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rle_iou_pair.restype = ctypes.c_double
    lib.rle_iou_pair.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.rle_iou_matrix.restype = None
    lib.rle_iou_matrix.argtypes = [ctypes.c_void_p] * 3 + [ctypes.c_uint64] + [ctypes.c_void_p] * 3 + [ctypes.c_uint64] + [ctypes.c_void_p] * 2
    lib.rle_from_polygon.restype = ctypes.c_uint64
    lib.rle_from_polygon.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p]


def _bind_edit(lib: ctypes.CDLL) -> None:
    lib.batch_edit_distance.restype = None
    lib.batch_edit_distance.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
    ]


def _get_library(stem: str, bind, extra_flags: Sequence[str] = ()) -> Optional[ctypes.CDLL]:
    """Build/load + bind prototypes once per process, cached by stem."""
    if _native_disabled():
        return None  # checked before the cache so re-enabling works in-process
    if stem not in _libs:
        lib = _build_library(stem, extra_flags)
        if lib is not None:
            bind(lib)
        else:
            # warn exactly once per extension (the None is cached): every
            # subsequent call silently uses the numpy fallback
            from torchmetrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(
                f"native extension {stem!r} is unavailable (g++ missing or compilation failed); falling back to"
                f" the numpy implementation. Set {_DISABLE_ENV}=1 to opt out of native compilation and silence"
                " this warning.",
                UserWarning,
            )
        _libs[stem] = lib
    return _libs[stem]


def get_rle_library() -> Optional[ctypes.CDLL]:
    """The compiled codec, or ``None`` if compilation isn't possible."""
    return _get_library("rle_codec", _bind_rle)


def get_edit_library() -> Optional[ctypes.CDLL]:
    """The compiled batched edit-distance kernel, or ``None``."""
    return _get_library("edit_distance", _bind_edit, extra_flags=("-fopenmp",))


def native_available() -> bool:
    return get_rle_library() is not None
