# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Native (C++) host extensions.

Currently: the COCO RLE mask codec (``rle_codec.cpp``), compiled on first use
with the system ``g++`` into a cached shared object and bound via ``ctypes``.
A pure-numpy fallback keeps everything working where no compiler exists.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).parent / "rle_codec.cpp"
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _build_library() -> Optional[ctypes.CDLL]:
    """Compile the codec with g++ (cached by source hash)."""
    src_hash = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    cache_dir = Path(os.environ.get("TM_TPU_NATIVE_CACHE", Path(tempfile.gettempdir()) / "tm_tpu_native"))
    cache_dir.mkdir(parents=True, exist_ok=True)
    so_path = cache_dir / f"rle_codec_{src_hash}.so"
    if not so_path.exists():
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", str(_SRC), "-o", str(so_path)]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (subprocess.SubprocessError, FileNotFoundError):
            return None
    try:
        lib = ctypes.CDLL(str(so_path))
    except OSError:
        return None
    lib.rle_encode.restype = ctypes.c_uint64
    lib.rle_encode.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
    lib.rle_decode.restype = None
    lib.rle_decode.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64]
    lib.rle_area.restype = ctypes.c_uint64
    lib.rle_area.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rle_iou_pair.restype = ctypes.c_double
    lib.rle_iou_pair.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.rle_iou_matrix.restype = None
    lib.rle_iou_matrix.argtypes = [ctypes.c_void_p] * 3 + [ctypes.c_uint64] + [ctypes.c_void_p] * 3 + [ctypes.c_uint64] + [ctypes.c_void_p] * 2
    lib.rle_from_polygon.restype = ctypes.c_uint64
    lib.rle_from_polygon.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p]
    return lib


def get_rle_library() -> Optional[ctypes.CDLL]:
    """The compiled codec, or ``None`` if compilation isn't possible."""
    global _lib, _lib_tried
    if not _lib_tried:
        _lib = _build_library()
        _lib_tried = True
    return _lib


def native_available() -> bool:
    return get_rle_library() is not None
