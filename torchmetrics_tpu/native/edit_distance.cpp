// Copyright The TorchMetrics-TPU contributors.
// Licensed under the Apache License, Version 2.0.
//
// Batched Levenshtein edit distance over interned token-id sequences — the
// host-side hot loop of WER/CER/MER/WIL/WIP on large corpora (the reference
// runs this as a per-sentence Python DP, src/torchmetrics/functional/text/
// helper.py:34-51). Two-row DP, one pair per OpenMP task.

#include <algorithm>
#include <cstdint>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// pred_tok / tgt_tok: flattened uint64 token ids for all pairs.
// pred_off / tgt_off: n_pairs+1 offsets into the flattened arrays.
// out: n_pairs edit distances.
void batch_edit_distance(const uint64_t* pred_tok, const int64_t* pred_off,
                         const uint64_t* tgt_tok, const int64_t* tgt_off,
                         int64_t n_pairs, int64_t substitution_cost,
                         int64_t* out) {
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic, 16) if (n_pairs > 64)
#endif
  for (int64_t k = 0; k < n_pairs; ++k) {
    const uint64_t* p = pred_tok + pred_off[k];
    const uint64_t* t = tgt_tok + tgt_off[k];
    const int64_t m = pred_off[k + 1] - pred_off[k];
    const int64_t n = tgt_off[k + 1] - tgt_off[k];
    if (m == 0) { out[k] = n; continue; }
    if (n == 0) { out[k] = m; continue; }
    std::vector<int64_t> row(static_cast<size_t>(n) + 1);
    for (int64_t j = 0; j <= n; ++j) row[j] = j;
    for (int64_t i = 1; i <= m; ++i) {
      int64_t diag = row[0];
      row[0] = i;
      const uint64_t pi = p[i - 1];
      for (int64_t j = 1; j <= n; ++j) {
        const int64_t sub = diag + (pi == t[j - 1] ? 0 : substitution_cost);
        diag = row[j];
        row[j] = std::min({sub, diag + 1, row[j - 1] + 1});
      }
    }
    out[k] = row[n];
  }
}

}  // extern "C"
