# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Bounded-memory mergeable sketches (ARCHITECTURE.md §11).

Every ``dist_reduce_fx="cat"`` metric state accumulates unbounded memory
with data-dependent shapes — it can never live inside the jit-compiled
sharded step. The sketches here trade exactness for **O(1) fixed-shape
state with hard error bounds**, each exposing the same four pure functions:

    init(...) -> State            # fixed-shape pytree (a NamedTuple)
    update(State, x) -> State     # jit-safe, shape-preserving
    merge(State, State) -> State  # jit-safe, shape-preserving,
                                  # associative/commutative (up to fp)
    query(State, ...) -> value    # quantile/cdf/mean/sample/...

``merge`` is what plugs them into the metric runtime: states registered
with ``add_state(..., dist_reduce_fx="merge")`` sync across ranks by
pairwise merge (riding the retry/rollback sync path), reduce across mesh
devices inside ``shard_map``, and checkpoint/restore with per-leaf
validation — see :mod:`torchmetrics_tpu.sketch.registry`.

Sketches:

- :class:`KLLSketch` — streaming quantiles/ranks (Karnin-Lang-Liberty
  compactors, deterministic variant) with an exact queryable rank-error
  bound (:func:`kll_error_bound`);
- :class:`HistogramSketch` — fixed-bin streaming histogram (exact merge);
- :class:`ReservoirSketch` — uniform sample via tagged top-k, PRNG key
  threaded through the state (no hidden RNG);
- :class:`MomentsSketch` — Chan/Welford parallel-merge count/mean/M2;
- :class:`HLLSketch` — HyperLogLog distinct count, union merge by register
  max, error ``1.04/sqrt(m)`` (:func:`hll_error_bound`);
- :class:`CountMinSketch` — Count-Min frequency grid + SpaceSaving-style
  heavy-hitter table; point queries upper-bound the true count.
"""
from torchmetrics_tpu.sketch.countmin import (
    CountMinSketch,
    cm_error_bound,
    cm_heavy_hitters,
    cm_init,
    cm_merge,
    cm_point_query,
    cm_state_bytes,
    cm_update,
)
from torchmetrics_tpu.sketch.histogram import (
    HistogramSketch,
    hist_cdf,
    hist_counts,
    hist_init,
    hist_merge,
    hist_quantile,
    hist_update,
)
from torchmetrics_tpu.sketch.hll import (
    MAX_PRECISION,
    MIN_PRECISION,
    HLLSketch,
    hll_cardinality,
    hll_error_bound,
    hll_init,
    hll_merge,
    hll_precision,
    hll_state_bytes,
    hll_update,
)
from torchmetrics_tpu.sketch.moments import (
    MomentsSketch,
    moments_count,
    moments_init,
    moments_mean,
    moments_merge,
    moments_std,
    moments_update,
    moments_variance,
)
from torchmetrics_tpu.sketch.quantile import (
    MAX_STREAM,
    KLLSketch,
    kll_cdf,
    kll_error_bound,
    kll_geometry,
    kll_init,
    kll_levels_for,
    kll_merge,
    kll_quantile,
    kll_rank,
    kll_state_bytes,
    kll_update,
)
from torchmetrics_tpu.sketch.registry import (
    is_sketch_state,
    merge_states,
    reduce_merge_states,
    register_sketch_state,
    registered_sketch_classes,
    sketch_state_class,
)
from torchmetrics_tpu.sketch.reservoir import (
    ReservoirSketch,
    reservoir_init,
    reservoir_merge,
    reservoir_sample,
    reservoir_update,
)

__all__ = [
    "CountMinSketch",
    "HLLSketch",
    "HistogramSketch",
    "KLLSketch",
    "MAX_PRECISION",
    "MAX_STREAM",
    "MIN_PRECISION",
    "MomentsSketch",
    "ReservoirSketch",
    "cm_error_bound",
    "cm_heavy_hitters",
    "cm_init",
    "cm_merge",
    "cm_point_query",
    "cm_state_bytes",
    "cm_update",
    "hist_cdf",
    "hist_counts",
    "hist_init",
    "hist_merge",
    "hist_quantile",
    "hist_update",
    "hll_cardinality",
    "hll_error_bound",
    "hll_init",
    "hll_merge",
    "hll_precision",
    "hll_state_bytes",
    "hll_update",
    "is_sketch_state",
    "kll_cdf",
    "kll_error_bound",
    "kll_geometry",
    "kll_init",
    "kll_levels_for",
    "kll_merge",
    "kll_quantile",
    "kll_rank",
    "kll_state_bytes",
    "kll_update",
    "merge_states",
    "moments_count",
    "moments_init",
    "moments_mean",
    "moments_merge",
    "moments_std",
    "moments_update",
    "moments_variance",
    "reduce_merge_states",
    "register_sketch_state",
    "registered_sketch_classes",
    "reservoir_init",
    "reservoir_merge",
    "reservoir_sample",
    "reservoir_update",
    "sketch_state_class",
]
