# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Chan/Welford parallel-merge running moments (count / mean / M2).

The numerically-stable streaming mean+variance state, with Chan et al.'s
pairwise combine as the merge — the textbook example of a mergeable
fixed-shape state, and the template every other sketch here follows. Works
elementwise over any state shape (scalars, per-class vectors, images)."""
from __future__ import annotations

from typing import NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.sketch.registry import register_sketch_state

Array = jax.Array


class MomentsSketch(NamedTuple):
    """Registered pytree state of the running moments."""

    count: Array  #: () int32 number of points folded in (exact to 2**31-1;
    #: a float32 count would silently stall at 2**24 on long streams)
    mean: Array  #: (shape) running mean
    m2: Array  #: (shape) running sum of squared deviations


def moments_init(
    shape: Tuple[int, ...] = (), dtype: Union[jnp.dtype, type] = jnp.float32
) -> MomentsSketch:
    """Empty moments accumulator over values of ``shape``."""
    dtype = jnp.dtype(dtype)
    return MomentsSketch(
        count=jnp.asarray(0, jnp.int32),
        mean=jnp.zeros(shape, dtype),
        m2=jnp.zeros(shape, dtype),
    )


def moments_merge(a: MomentsSketch, b: MomentsSketch) -> MomentsSketch:
    """Chan et al. parallel combine — jit-safe, shape-preserving, exact in
    count and stable in mean/M2 (no catastrophic cancellation)."""
    if a.mean.shape != b.mean.shape:
        raise ValueError(
            f"cannot merge moments over different shapes: {a.mean.shape} vs {b.mean.shape}"
        )
    dtype = a.mean.dtype
    n = a.count + b.count
    an, bn = a.count.astype(dtype), b.count.astype(dtype)
    safe_n = jnp.maximum(n, 1).astype(dtype)
    delta = b.mean - a.mean
    mean = a.mean + delta * (bn / safe_n)
    m2 = a.m2 + b.m2 + jnp.square(delta) * (an * bn / safe_n)
    return MomentsSketch(count=n, mean=mean, m2=m2)


def moments_update(state: MomentsSketch, x: Array) -> MomentsSketch:
    """Fold a batch (leading axis = batch) in via batch-Welford + Chan merge."""
    x = jnp.asarray(x, state.mean.dtype)
    if x.ndim == state.mean.ndim:  # single observation
        x = x[None]
    if x.shape[0] == 0:
        return state
    n_b = jnp.asarray(x.shape[0], jnp.int32)
    mean_b = jnp.mean(x, axis=0)
    m2_b = jnp.sum(jnp.square(x - mean_b), axis=0)
    return moments_merge(state, MomentsSketch(count=n_b, mean=mean_b, m2=m2_b))


def moments_mean(state: MomentsSketch) -> Array:
    """Running mean (NaN when empty)."""
    return jnp.where(state.count > 0, state.mean, jnp.nan)


def moments_variance(state: MomentsSketch, ddof: int = 0) -> Array:
    """Running variance with ``ddof`` degrees-of-freedom correction."""
    denom = (state.count - ddof).astype(state.m2.dtype)
    return jnp.where(denom > 0, state.m2 / jnp.where(denom > 0, denom, 1.0), jnp.nan)


def moments_std(state: MomentsSketch, ddof: int = 0) -> Array:
    return jnp.sqrt(moments_variance(state, ddof))


def moments_count(state: MomentsSketch) -> Array:
    return state.count


register_sketch_state(MomentsSketch, moments_merge)
