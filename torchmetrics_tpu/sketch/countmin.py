# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Count-Min sketch with a SpaceSaving-style heavy-hitter track.

Frequency estimation in fixed memory: a ``[depth, width]`` int32 counter grid
where every item increments one cell per row (row-seeded murmur hashes) and a
point query takes the **min** over rows — always an upper bound on the true
count, and at most ``true + (e/width) * N`` with probability ``1 - e^-depth``
(Cormode & Muthukrishnan 2005). The grid merge is exact elementwise addition,
so it is associative/commutative and rides ``dist_reduce_fx="merge"``
unchanged.

Top-k label skew needs names, not just counts, so a fixed-``k`` candidate
table rides along (SpaceSaving-style: the minimum-estimate candidate is
evicted when a larger newcomer arrives, with estimates re-scored against the
counter grid). The table is a heuristic view — merge re-scores the union of
both sides' candidates against the merged grid and keeps the top ``k`` with a
deterministic (count desc, key asc) tie-break, so merged tables are
reproducible even though candidate *recall* is approximate.

Items are opaque 32-bit tags exactly as in :mod:`torchmetrics_tpu.sketch.hll`
(integers cast, floats bit-cast).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.sketch.hll import _as_tags, _fmix32
from torchmetrics_tpu.sketch.registry import register_sketch_state

Array = jax.Array


class CountMinSketch(NamedTuple):
    """Registered pytree state of the Count-Min + heavy-hitter sketch."""

    counts: Array  #: (depth, width) int32 counter grid
    hh_keys: Array  #: (k,) uint32 heavy-hitter candidate tags
    hh_counts: Array  #: (k,) int32 candidate count estimates (0 = empty slot)
    count: Array  #: () int32 total items folded in


def _row_seeds(depth: int) -> Array:
    """Deterministic per-row hash seeds — a pure function of the row index,
    so any two sketches of the same depth hash identically and merge exactly."""
    rows = jnp.arange(1, depth + 1, dtype=jnp.uint32)
    return _fmix32(rows * jnp.uint32(0x9E3779B9))


def _columns(tags: Array, depth: int, width: int) -> Array:
    """(depth, n) column index per row for each tag."""
    seeds = _row_seeds(depth)
    h = _fmix32(tags[None, :] ^ seeds[:, None])
    return (h % jnp.uint32(width)).astype(jnp.int32)


def cm_init(depth: int = 4, width: int = 1024, k: int = 32) -> CountMinSketch:
    """Empty Count-Min grid with a ``k``-slot heavy-hitter table.

    Defaults give overestimate ``<= e/1024 * N ~ 0.27% of N`` per query with
    probability ``1 - e^-4 ~ 98%`` in 16 KiB of grid state.
    """
    if depth < 1:
        raise ValueError(f"need depth >= 1, got {depth}")
    if width < 2:
        raise ValueError(f"need width >= 2, got {width}")
    if k < 1:
        raise ValueError(f"need k >= 1, got {k}")
    return CountMinSketch(
        counts=jnp.zeros((depth, width), jnp.int32),
        hh_keys=jnp.zeros((k,), jnp.uint32),
        hh_counts=jnp.zeros((k,), jnp.int32),
        count=jnp.asarray(0, jnp.int32),
    )


def _point(counts: Array, tags: Array) -> Array:
    """Min-over-rows count estimate for each tag (the CM upper bound)."""
    depth, width = counts.shape
    cols = _columns(tags, depth, width)
    gathered = jnp.take_along_axis(counts, cols, axis=1)  # (depth, n)
    return jnp.min(gathered, axis=0)


def cm_update(state: CountMinSketch, x: Array) -> CountMinSketch:
    """Fold a batch of tags in (jit-safe; shapes preserved).

    The grid takes one vectorized scatter-add; the heavy-hitter table is then
    maintained per-item with a ``lax.scan`` over the batch (fixed-shape
    carry), scoring candidates against the post-batch grid.
    """
    tags = _as_tags(x)
    if tags.size == 0:
        return state
    depth, width = state.counts.shape
    cols = _columns(tags, depth, width)
    rows = jnp.broadcast_to(jnp.arange(depth, dtype=jnp.int32)[:, None], cols.shape)
    counts = state.counts.at[rows, cols].add(1)

    def track(carry, tag):
        keys, cnts = carry
        est = _point(counts, tag[None])[0]
        tracked = (keys == tag) & (cnts > 0)
        any_tracked = jnp.any(tracked)
        pos_min = jnp.argmin(cnts)
        pos = jnp.where(any_tracked, jnp.argmax(tracked), pos_min)
        admit = any_tracked | (est > cnts[pos_min])
        new_cnt = jnp.where(any_tracked, jnp.maximum(cnts[pos], est), est)
        keys = jnp.where(admit, keys.at[pos].set(tag), keys)
        cnts = jnp.where(admit, cnts.at[pos].set(new_cnt), cnts)
        return (keys, cnts), None

    (hh_keys, hh_counts), _ = jax.lax.scan(track, (state.hh_keys, state.hh_counts), tags)
    return CountMinSketch(
        counts=counts,
        hh_keys=hh_keys,
        hh_counts=hh_counts,
        count=state.count + jnp.asarray(tags.size, jnp.int32),
    )


def _top_k(keys: Array, ests: Array, k: int) -> Tuple[Array, Array]:
    """Deterministic top-``k`` by (count desc, key asc); zero counts lose."""
    order = jnp.lexsort((keys, -ests))
    return keys[order[:k]], ests[order[:k]]


def cm_merge(a: CountMinSketch, b: CountMinSketch) -> CountMinSketch:
    """Merge: grid counts add EXACTLY (same geometry hashes identically);
    the heavy-hitter union is re-scored against the merged grid and the top
    ``k`` kept with a deterministic tie-break."""
    if a.counts.shape != b.counts.shape or a.hh_keys.shape != b.hh_keys.shape:
        raise ValueError(
            "cannot merge Count-Min sketches of different geometry:"
            f" {a.counts.shape}+{a.hh_keys.shape} vs {b.counts.shape}+{b.hh_keys.shape}"
        )
    counts = a.counts + b.counts
    cand_keys = jnp.concatenate([a.hh_keys, b.hh_keys])
    valid = jnp.concatenate([a.hh_counts > 0, b.hh_counts > 0])
    ests = jnp.where(valid, _point(counts, cand_keys), 0)
    # drop later duplicates of the same key so one item can't hold two slots
    same = (cand_keys[None, :] == cand_keys[:, None]) & valid[:, None] & valid[None, :]
    dup_of_earlier = jnp.any(jnp.tril(same, -1), axis=1)
    ests = jnp.where(dup_of_earlier, 0, ests)
    hh_keys, hh_counts = _top_k(cand_keys, ests, a.hh_keys.shape[0])
    return CountMinSketch(counts=counts, hh_keys=hh_keys, hh_counts=hh_counts, count=a.count + b.count)


def cm_point_query(state: CountMinSketch, x: Array) -> Array:
    """Estimated count(s) for tag(s) ``x`` — never below the true count."""
    tags = _as_tags(x)
    return _point(state.counts, tags)


def cm_heavy_hitters(state: CountMinSketch) -> Tuple[Array, Array]:
    """``(keys, counts)`` candidate table sorted by (count desc, key asc);
    slots with count 0 are empty."""
    return _top_k(state.hh_keys, state.hh_counts, state.hh_keys.shape[0])


def cm_error_bound(state: CountMinSketch) -> float:
    """Additive overestimate bound ``(e/width) * N`` that holds per point
    query with probability ``1 - e^-depth`` (host-side; reads ``count``)."""
    import math

    depth, width = state.counts.shape
    return math.e / width * int(state.count)


def cm_state_bytes(depth: int = 4, width: int = 1024, k: int = 32) -> int:
    """Fixed state footprint in bytes for a given geometry."""
    return depth * width * 4 + k * 8 + 4


register_sketch_state(CountMinSketch, cm_merge)
