# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Registry of mergeable sketch-state types.

A *sketch state* is a fixed-shape pytree of arrays (a ``NamedTuple`` — jax
treats those as pytree nodes natively) together with a pure, jit-safe,
shape-preserving binary ``merge``. Registering the pair here is what makes a
type usable as a ``dist_reduce_fx="merge"`` metric state: the runtime
(``Metric._sync_dist``, ``Metric._reduce_states``, ``parallel.sharded``)
finds the merge through this registry, and checkpoint/spec validation finds
the class back by name when deserializing.

The registry is the whole protocol — sketches never import the metric
runtime, so new sketch types (count-min, HLL, ...) drop in with one
:func:`register_sketch_state` call.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Sequence, Tuple, Type

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import trace as _obs_trace

_MERGE_FNS: Dict[Type, Callable[[Any, Any], Any]] = {}
_BY_NAME: Dict[str, Type] = {}


def register_sketch_state(cls: Type, merge_fn: Callable[[Any, Any], Any]) -> Type:
    """Register ``cls`` (a NamedTuple pytree of arrays) with its pairwise
    ``merge_fn``. Returns ``cls`` so it can be used as a decorator helper."""
    if not (isinstance(cls, type) and hasattr(cls, "_fields")):
        raise TypeError(f"sketch state class must be a NamedTuple type, got {cls!r}")
    _MERGE_FNS[cls] = merge_fn
    _BY_NAME[cls.__name__] = cls
    return cls


def is_sketch_state(value: Any) -> bool:
    """True when ``value`` is an instance of a registered sketch-state type."""
    return type(value) in _MERGE_FNS


def sketch_state_class(name: str) -> Type:
    """Resolve a registered sketch class by name (checkpoint deserialization)."""
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"unknown sketch state class {name!r}; registered: {sorted(_BY_NAME)}"
        ) from None


def registered_sketch_classes() -> Tuple[Type, ...]:
    return tuple(_MERGE_FNS)


def _is_traced(state: Any) -> bool:
    import jax

    return any(isinstance(leaf, jax.core.Tracer) for leaf in jax.tree_util.tree_leaves(state))


def merge_states(a: Any, b: Any) -> Any:
    """Pairwise-merge two sketch states of the same registered type.

    jit-safe and shape-preserving; the obs counter only bumps on HOST merges
    (a traced merge would count once per trace, not per execution, which
    reads as an undercount — so traced calls are excluded rather than lied
    about).
    """
    if type(a) is not type(b):
        raise TypeError(
            f"cannot merge sketch states of different types: {type(a).__name__} vs {type(b).__name__}"
        )
    merge_fn = _MERGE_FNS.get(type(a))
    if merge_fn is None:
        raise TypeError(f"{type(a).__name__} is not a registered sketch state type")
    if _obs_trace.ENABLED and not _is_traced(a):
        _obs_counters.inc("sketch.merge")
        _obs_counters.inc(f"sketch.merge.{type(a).__name__}")
    return merge_fn(a, b)


def reduce_merge_states(states: Sequence[Any]) -> Any:
    """Reduce a sequence of sketch states (one per rank/device) by pairwise
    left-fold merge — the ``_REDUCTION_MAP["merge"]`` entry.

    Tagged with an obs span so a cross-rank merge-reduction shows up in
    metricscope like every other sync phase.
    """
    states = list(states)
    if not states:
        raise ValueError("reduce_merge_states: empty state sequence")
    if len(states) == 1:
        return states[0]
    if _obs_trace.ENABLED and not _is_traced(states[0]):
        with _obs_trace.span(
            "sketch.merge_reduce", kind=type(states[0]).__name__, parts=len(states)
        ):
            return functools.reduce(merge_states, states)
    return functools.reduce(merge_states, states)
