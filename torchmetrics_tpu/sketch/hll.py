# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""HyperLogLog — fixed-shape mergeable distinct-count sketch.

The canonical "millions of users" counter: ``m = 2**precision`` one-byte-ish
registers (stored int32 for scatter-max friendliness) estimate the number of
DISTINCT values folded in with relative standard error ``1.04/sqrt(m)``
(Flajolet et al. 2007), independent of stream length. Merging two sketches of
the same precision is an elementwise register ``max`` — exactly the union of
the two multisets, so it is associative, commutative, and idempotent: folding
the same shard twice cannot double-count, which is what makes the fleet-fold
and window regimes safe for cardinality.

Values are hashed on-device with the murmur3 finalizer (``fmix32``), an
avalanche permutation of the 32-bit value — inputs are taken as opaque
32-bit tags (integers cast, floats bit-cast), so "distinct" means distinct
bit patterns.
"""
from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.sketch.registry import register_sketch_state

Array = jax.Array

#: precision bounds: below 4 the bias correction breaks down, above 16 the
#: register file (2**p int32) stops being "small sketch state"
MIN_PRECISION = 4
MAX_PRECISION = 16


class HLLSketch(NamedTuple):
    """Registered pytree state of the HyperLogLog sketch."""

    registers: Array  #: (m,) int32 max leading-zero rank seen per register
    count: Array  #: () int32 total values folded in (not distinct count)


def _fmix32(h: Array) -> Array:
    """Murmur3 32-bit finalizer: a full-avalanche bijection on uint32."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _as_tags(x: Array) -> Array:
    """Flatten input to opaque uint32 tags (floats bit-cast, ints cast)."""
    x = jnp.ravel(jnp.asarray(x))
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    return x.astype(jnp.uint32)


def hll_init(precision: int = 12) -> HLLSketch:
    """Empty HyperLogLog with ``2**precision`` registers.

    The default ``precision=12`` (4096 registers, 16 KiB of int32 state) has
    ~1.6% standard error — the usual production point for user counting.
    """
    if not MIN_PRECISION <= precision <= MAX_PRECISION:
        raise ValueError(f"need {MIN_PRECISION} <= precision <= {MAX_PRECISION}, got {precision}")
    return HLLSketch(
        registers=jnp.zeros((1 << precision,), jnp.int32),
        count=jnp.asarray(0, jnp.int32),
    )


def hll_precision(state: HLLSketch) -> int:
    """Recover the precision from the (static) register-file shape."""
    m = state.registers.shape[0]
    return int(m).bit_length() - 1


def hll_update(state: HLLSketch, x: Array) -> HLLSketch:
    """Fold a batch of tags in (jit-safe scatter-max; shapes preserved)."""
    tags = _as_tags(x)
    if tags.size == 0:
        return state
    p = hll_precision(state)
    h = _fmix32(tags)
    idx = (h >> jnp.uint32(32 - p)).astype(jnp.int32)
    # rank = leading zeros of the remaining (32-p)-bit suffix, plus one;
    # an all-zero suffix gets the max rank 32-p+1
    suffix = h << jnp.uint32(p)
    rho = jnp.minimum(jax.lax.clz(suffix).astype(jnp.int32) + 1, 32 - p + 1)
    return HLLSketch(
        registers=state.registers.at[idx].max(rho),
        count=state.count + jnp.asarray(tags.size, jnp.int32),
    )


def hll_merge(a: HLLSketch, b: HLLSketch) -> HLLSketch:
    """Union merge: elementwise register max (idempotent on shared items).
    Both sketches must share the precision (register-file shape)."""
    if a.registers.shape != b.registers.shape:
        raise ValueError(
            f"cannot merge HLL sketches of different precision: {a.registers.shape} vs {b.registers.shape}"
        )
    return HLLSketch(
        registers=jnp.maximum(a.registers, b.registers),
        count=a.count + b.count,
    )


def hll_cardinality(state: HLLSketch) -> Array:
    """Bias-corrected estimate of the number of distinct tags folded in.

    The raw harmonic-mean estimate ``alpha_m * m^2 / sum(2^-M_j)`` is
    corrected at both ends (Flajolet et al. 2007 §4): linear counting
    ``m * ln(m/V)`` when the estimate is small and some registers are still
    empty, and the 32-bit-hash saturation correction when the estimate
    approaches ``2^32``. Pure jnp; jit-safe.
    """
    m = state.registers.shape[0]
    if m >= 128:
        alpha = 0.7213 / (1.0 + 1.079 / m)
    else:
        alpha = {16: 0.673, 32: 0.697, 64: 0.709}[m]
    regs = state.registers.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    raw = alpha * m * m / jnp.sum(jnp.exp2(-regs))
    zeros = jnp.sum(state.registers == 0).astype(raw.dtype)
    # small-range: linear counting while empty registers remain
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
    # large-range: correct 32-bit hash-collision saturation
    two32 = jnp.asarray(2.0**32, est.dtype)
    est = jnp.where(est > two32 / 30.0, -two32 * jnp.log1p(-est / two32), est)
    return est


def hll_error_bound(state: HLLSketch) -> float:
    """Published relative standard error of :func:`hll_cardinality`:
    ``1.04 / sqrt(m)`` (e.g. ~1.6% at precision 12)."""
    return 1.04 / float(state.registers.shape[0]) ** 0.5


def hll_state_bytes(precision: int = 12) -> int:
    """Fixed state footprint in bytes for a given precision."""
    return (1 << precision) * 4 + 4


register_sketch_state(HLLSketch, hll_merge)
