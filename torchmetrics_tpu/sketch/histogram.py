# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Fixed-bin streaming histogram — the simplest mergeable sketch.

Bin edges are fixed at ``init`` (a data-range decision, like AUROC's binned
thresholds), so the state is a single count vector plus out-of-range tallies
and merging is elementwise addition: exactly associative/commutative, and
the per-value resolution is the bin width — no stream-length dependence.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.sketch.registry import register_sketch_state

Array = jax.Array


class HistogramSketch(NamedTuple):
    """Registered pytree state of the fixed-bin histogram."""

    edges: Array  #: (bins+1,) monotonically increasing bin edges (constant)
    counts: Array  #: (bins,) int32 in-range counts
    low: Array  #: () int32 count of values < edges[0]
    high: Array  #: () int32 count of values > edges[-1]
    count: Array  #: () int32 total values folded in


def hist_init(bins: int, lo: float, hi: float, dtype: Union[jnp.dtype, type] = jnp.float32) -> HistogramSketch:
    """Empty histogram of ``bins`` equal-width bins over ``[lo, hi]``."""
    if bins < 1:
        raise ValueError(f"need bins >= 1, got {bins}")
    if not lo < hi:
        raise ValueError(f"need lo < hi, got ({lo}, {hi})")
    return HistogramSketch(
        edges=jnp.linspace(lo, hi, bins + 1, dtype=jnp.dtype(dtype)),
        counts=jnp.zeros((bins,), jnp.int32),
        low=jnp.asarray(0, jnp.int32),
        high=jnp.asarray(0, jnp.int32),
        count=jnp.asarray(0, jnp.int32),
    )


def hist_update(state: HistogramSketch, x: Array) -> HistogramSketch:
    """Fold a batch in (jit-safe scatter-add; shapes preserved)."""
    x = jnp.ravel(jnp.asarray(x)).astype(state.edges.dtype)
    if x.size == 0:
        return state
    bins = state.counts.shape[0]
    below = jnp.sum(x < state.edges[0]).astype(jnp.int32)
    above = jnp.sum(x > state.edges[-1]).astype(jnp.int32)
    idx = jnp.clip(jnp.searchsorted(state.edges, x, side="right") - 1, 0, bins - 1)
    in_range = (x >= state.edges[0]) & (x <= state.edges[-1])
    counts = state.counts.at[idx].add(in_range.astype(jnp.int32))
    return HistogramSketch(
        edges=state.edges,
        counts=counts,
        low=state.low + below,
        high=state.high + above,
        count=state.count + jnp.asarray(x.size, jnp.int32),
    )


def hist_merge(a: HistogramSketch, b: HistogramSketch) -> HistogramSketch:
    """Exact merge: counts add. Both sketches must share the edge vector
    (same shape is enforced here; same values are the caller's init contract,
    validated host-side by the state-spec machinery)."""
    if a.edges.shape != b.edges.shape:
        raise ValueError(
            f"cannot merge histograms with different bin counts: {a.edges.shape} vs {b.edges.shape}"
        )
    return HistogramSketch(
        edges=a.edges,
        counts=a.counts + b.counts,
        low=a.low + b.low,
        high=a.high + b.high,
        count=a.count + b.count,
    )


def hist_counts(state: HistogramSketch) -> Tuple[Array, Array, Array]:
    """``(counts, low, high)`` — in-range per-bin counts plus out-of-range tallies."""
    return state.counts, state.low, state.high


def hist_cdf(state: HistogramSketch, v: Union[float, Array]) -> Array:
    """Approximate CDF at ``v`` (linear interpolation within a bin)."""
    dtype = state.edges.dtype
    v = jnp.asarray(v, dtype)
    cum = jnp.cumsum(state.counts).astype(dtype)
    padded = jnp.concatenate([jnp.zeros((1,), dtype), cum])
    bins = state.counts.shape[0]
    pos = jnp.clip(jnp.searchsorted(state.edges, v, side="right") - 1, 0, bins - 1)
    width = state.edges[pos + 1] - state.edges[pos]
    frac = jnp.clip((v - state.edges[pos]) / jnp.where(width > 0, width, 1.0), 0.0, 1.0)
    below = state.low.astype(dtype) + padded[pos] + frac * state.counts[pos].astype(dtype)
    below = jnp.where(v < state.edges[0], 0.0, below)
    below = jnp.where(v >= state.edges[-1], state.count.astype(dtype) - state.high.astype(dtype), below)
    return below / jnp.maximum(state.count, 1).astype(dtype)


def hist_quantile(state: HistogramSketch, q: Union[float, Array]) -> Array:
    """Approximate ``q``-quantile from the binned CDF (interpolated; clamps
    to the histogram range; NaN on an empty sketch)."""
    dtype = state.edges.dtype
    q = jnp.asarray(q, dtype)
    total = state.count.astype(dtype)
    cum = state.low.astype(dtype) + jnp.cumsum(state.counts).astype(dtype)
    padded = jnp.concatenate([state.low.astype(dtype)[None], cum])
    target = jnp.clip(q * total, 0.0, total)
    bins = state.counts.shape[0]
    pos = jnp.clip(jnp.searchsorted(padded, target, side="left") - 1, 0, bins - 1)
    binc = state.counts[pos].astype(dtype)
    frac = jnp.clip((target - padded[pos]) / jnp.where(binc > 0, binc, 1.0), 0.0, 1.0)
    out = state.edges[pos] + frac * (state.edges[pos + 1] - state.edges[pos])
    return jnp.where(state.count > 0, out, jnp.asarray(jnp.nan, dtype))


register_sketch_state(HistogramSketch, hist_merge)
