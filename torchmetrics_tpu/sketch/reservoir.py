# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Counter-based mergeable reservoir sample with explicit PRNG key threading.

The classic "exponential tags" formulation (Efraimidis & Spirakis A-Res with
unit weights): every incoming value draws a uniform tag and the reservoir
keeps the ``capacity`` values with the LARGEST tags. That makes the sample

- **uniform** — each point's tag is iid, so the top-``capacity`` set is a
  uniform sample without replacement;
- **mergeable** — the merged reservoir is the top-``capacity`` of the tag
  union: exactly associative and commutative on the ``(value, tag)`` pairs;
- **jit-safe** — update/merge are a concat + top-k, all fixed shapes.

Randomness is explicit: the PRNG key lives IN the state and every update
splits it, so replaying the same stream from the same ``init`` seed is
bit-reproducible — there is no hidden global RNG anywhere (the rule that
keeps ``Metric`` updates traceable)."""
from __future__ import annotations

from typing import NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.sketch.registry import register_sketch_state

Array = jax.Array


class ReservoirSketch(NamedTuple):
    """Registered pytree state of the uniform reservoir sample."""

    values: Array  #: (capacity,) sampled values (junk beyond `filled` slots)
    tags: Array  #: (capacity,) float32 uniform tags; -inf marks an empty slot
    count: Array  #: () int32 total values seen
    key: Array  #: (2,) uint32 threaded PRNG key (jax.random.PRNGKey layout)


def reservoir_init(
    capacity: int,
    seed: int = 0,
    dtype: Union[jnp.dtype, type] = jnp.float32,
    rank: int = 0,
) -> ReservoirSketch:
    """Empty reservoir of ``capacity`` slots, randomness rooted at ``seed``.

    **Multi-rank/multi-replica use MUST pass a distinct ``rank``** (e.g.
    ``jax.process_index()``): two reservoirs initialized from the same
    ``(seed, rank)`` draw bit-identical tag sequences, so a merge of their
    samples selects the SAME stream positions on both sides — a perfectly
    correlated "sample" that silently voids the uniformity guarantee
    :func:`reservoir_merge` relies on. ``rank`` is folded into the key here
    (rather than auto-read from the backend) so building a sketch never
    touches — or blocks on — device initialization.
    """
    if capacity < 1:
        raise ValueError(f"need capacity >= 1, got {capacity}")
    key = jax.random.PRNGKey(seed)
    if rank:
        key = jax.random.fold_in(key, rank)
    return ReservoirSketch(
        values=jnp.zeros((capacity,), jnp.dtype(dtype)),
        tags=jnp.full((capacity,), -jnp.inf, jnp.float32),
        count=jnp.asarray(0, jnp.int32),
        key=key,
    )


def _top_capacity(values: Array, tags: Array, capacity: int) -> Tuple[Array, Array]:
    order = jnp.argsort(-tags)[:capacity]
    return values[order], tags[order]


def reservoir_update(state: ReservoirSketch, x: Array) -> ReservoirSketch:
    """Fold a batch in: draw one tag per value from the threaded key, keep the
    top-``capacity`` tagged values (jit-safe; shapes preserved)."""
    x = jnp.ravel(jnp.asarray(x)).astype(state.values.dtype)
    if x.size == 0:
        return state
    capacity = state.values.shape[0]
    key, sub = jax.random.split(state.key)
    tags = jax.random.uniform(sub, (x.size,), jnp.float32)
    values, tags = _top_capacity(
        jnp.concatenate([state.values, x]), jnp.concatenate([state.tags, tags]), capacity
    )
    return ReservoirSketch(values, tags, state.count + jnp.asarray(x.size, jnp.int32), key)


def reservoir_merge(a: ReservoirSketch, b: ReservoirSketch) -> ReservoirSketch:
    """Top-``capacity`` of the tag union — exact on the sample; the threaded
    key folds the peer's count in so later updates stay decorrelated."""
    if a.values.shape != b.values.shape:
        raise ValueError(
            f"cannot merge reservoirs of different capacity: {a.values.shape} vs {b.values.shape}"
        )
    capacity = a.values.shape[0]
    values, tags = _top_capacity(
        jnp.concatenate([a.values, b.values]), jnp.concatenate([a.tags, b.tags]), capacity
    )
    return ReservoirSketch(
        values=values,
        tags=tags,
        count=a.count + b.count,
        key=jax.random.fold_in(a.key, b.count),
    )


def reservoir_sample(state: ReservoirSketch) -> Tuple[Array, Array]:
    """``(values, valid)`` — the sample and a boolean mask of live slots
    (the reservoir is only partially filled while ``count < capacity``)."""
    return state.values, jnp.isfinite(state.tags)


register_sketch_state(ReservoirSketch, reservoir_merge)
