# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""KLL-style streaming quantile sketch (Karnin, Lang & Liberty, 2016), the
deterministic-compaction variant.

Fixed-shape, pure-JAX, mergeable: the state is ``(levels, capacity)`` arrays
whose shapes never change, so update and merge trace into a compiled sharded
step like any elementwise state — the bounded-memory replacement for the
``dist_reduce_fx="cat"`` regime (Spearman/Kendall/exact curves) that can
never run under jit.

Structure (classic multi-level compactor):

- level ``l`` holds up to ``capacity`` sorted-on-demand items, each standing
  for ``2**l`` original points;
- inserting a batch builds a throwaway sketch of the (statically-shaped)
  batch and merges it in;
- a level over capacity *compacts*: items are sorted and the odd-position
  half is promoted to level ``l+1`` at double weight, the even half dropped
  (plus one kept leftover when the count is odd).

**Error accounting is exact, not asymptotic**: one compaction at level ``l``
perturbs the rank of ANY query point by at most ``2**l`` (the promoted items
at positions 1,3,5,... of the sorted buffer hit ``floor(m/2)`` of the ``m``
items below the query; doubling their weight misses ``m`` by at most the
parity bit). The state counts compactions per level, so
:func:`kll_error_bound` returns a hard deterministic bound
``sum_l compactions[l] * 2**l`` on the rank error of every query — the
property suite asserts the measured error of a 1e6-point stream stays under
it. Total weight is conserved by compaction (``2w*(n//2) + w*(n%2) == w*n``),
so ``count`` is always exact.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.sketch.registry import register_sketch_state

Array = jax.Array

#: default geometry: ~0.9% worst-case rank error up to ``count = capacity *
#: 2**(levels-1)`` ≈ 1.3e8 points in ~140 KB of state (see kll_geometry)
DEFAULT_CAPACITY = 2048
DEFAULT_LEVELS = 17


class KLLSketch(NamedTuple):
    """Registered pytree state of the quantile sketch (all leaves fixed-shape)."""

    items: Array  #: (levels, capacity) item values; empty slots hold +inf
    sizes: Array  #: (levels,) int32 — number of live items per level
    compactions: Array  #: (levels,) int32 — compactions performed per level
    count: Array  #: () int32 — exact number of points folded in
    minimum: Array  #: () running exact min (+inf when empty)
    maximum: Array  #: () running exact max (-inf when empty)
    overflow: Array  #: () bool — a carry out of the top level was dropped


#: the exact-count ceiling: ``count`` is int32, so a sketch may never be
#: sized to absorb more weight than this before its overflow latch fires —
#: past the latch results are flagged invalid anyway (error bound = +inf)
MAX_STREAM = 2**31 - 1


def kll_levels_for(capacity: int, max_n: float) -> int:
    """Levels needed for a sketch of ``capacity`` to absorb ``max_n`` points
    without overflow (+1 spare level of headroom)."""
    if not 0 < max_n <= MAX_STREAM:
        raise ValueError(f"max_n must be in (0, {MAX_STREAM}] (int32-exact counts), got {max_n}")
    return max(1, int(math.ceil(math.log2(max(max_n / capacity, 1.0)))) + 1) + 1


def kll_geometry(eps: float, max_n: float = 1e8) -> Tuple[int, int]:
    """Smallest power-of-two ``(capacity, levels)`` whose deterministic
    worst-case rank error stays ≤ ``eps * n`` for streams up to ``max_n``.

    Worst case: level ``l`` compacts at most ``n / (capacity * 2**l)`` times
    (each compaction consumes ``capacity`` items of weight ``2**l``), each
    costing ≤ ``2**l`` rank error, so the bound is ``n * L / capacity`` with
    ``L = floor(log2(n / capacity)) + 1`` compacting levels.
    """
    if not 0 < eps < 1:
        raise ValueError(f"eps must be in (0, 1), got {eps}")
    if not 0 < max_n <= MAX_STREAM:
        raise ValueError(f"max_n must be in (0, {MAX_STREAM}] (int32-exact counts), got {max_n}")
    capacity = 32
    while capacity * 2 <= 2**24:
        levels_active = max(1, int(math.floor(math.log2(max(max_n / capacity, 1.0)))) + 1)
        if levels_active / capacity <= eps:
            break
        capacity *= 2
    return capacity, kll_levels_for(capacity, max_n)


def kll_init(
    capacity: int = DEFAULT_CAPACITY,
    levels: int = DEFAULT_LEVELS,
    dtype: Union[jnp.dtype, type] = jnp.float32,
) -> KLLSketch:
    """Empty sketch of the given geometry. ``capacity`` items per level,
    ``levels`` levels: holds up to ``capacity * 2**(levels-1)`` points before
    latching ``overflow``."""
    if capacity < 2 or levels < 1:
        raise ValueError(f"need capacity >= 2 and levels >= 1, got ({capacity}, {levels})")
    if capacity * 2 ** (levels - 1) > MAX_STREAM:
        # count is int32: it must stay exact at least until the overflow
        # latch fires (total weight > capacity * 2**(levels-1)), or long
        # streams would wrap count silently while the sketch still looked
        # healthy
        raise ValueError(
            f"geometry ({capacity}, {levels}) absorbs up to {capacity * 2 ** (levels - 1):.2e} points,"
            f" beyond the int32-exact count ceiling {MAX_STREAM}; lower `levels` (error bounds"
            " past ~2e9 points need a coarser eps anyway)"
        )
    dtype = jnp.dtype(dtype)
    if not jnp.issubdtype(dtype, jnp.floating):
        raise ValueError(f"KLLSketch requires a floating dtype (inf sentinels), got {dtype}")
    return KLLSketch(
        items=jnp.full((levels, capacity), jnp.inf, dtype),
        sizes=jnp.zeros((levels,), jnp.int32),
        compactions=jnp.zeros((levels,), jnp.int32),
        count=jnp.asarray(0, jnp.int32),
        minimum=jnp.asarray(jnp.inf, dtype),
        maximum=jnp.asarray(-jnp.inf, dtype),
        overflow=jnp.asarray(False, jnp.bool_),
    )


def _sketch_of_batch(x: Array, levels: int, capacity: int, dtype) -> KLLSketch:
    """A throwaway sketch of one batch. ``x.size`` is static under trace, so
    the compaction cascade unrolls at trace time — no dynamic control flow."""
    x = jnp.ravel(x).astype(dtype)
    n_in = int(x.size)
    items = jnp.full((levels, capacity), jnp.inf, dtype)
    sizes = jnp.zeros((levels,), jnp.int32)
    compactions = jnp.zeros((levels,), jnp.int32)
    if n_in == 0:
        return KLLSketch(
            items, sizes, compactions,
            jnp.asarray(0, jnp.int32),
            jnp.asarray(jnp.inf, dtype), jnp.asarray(-jnp.inf, dtype),
            jnp.asarray(False, jnp.bool_),
        )
    cur = jnp.sort(x)
    level = 0
    while cur.size > capacity:
        if level >= levels - 1:
            raise ValueError(
                f"a single batch of {n_in} elements cannot fit a ({levels}, {capacity})"
                f" KLLSketch — raise `levels`/`capacity` (or split the batch)"
            )
        n = int(cur.size)
        if n % 2 == 1:  # leftover stays at this level, weight preserved
            items = items.at[level, 0].set(cur[n - 1])
            sizes = sizes.at[level].set(1)
        compactions = compactions.at[level].add(1)
        cur = cur[1 : n - (n % 2) : 2]  # odd positions of the paired prefix
        level += 1
    m = int(cur.size)
    items = items.at[level, :m].set(cur)
    sizes = sizes.at[level].add(m)
    return KLLSketch(
        items=items,
        sizes=sizes,
        compactions=compactions,
        count=jnp.asarray(n_in, jnp.int32),
        minimum=jnp.min(x),
        maximum=jnp.max(x),
        overflow=jnp.asarray(False, jnp.bool_),
    )


def kll_merge(a: KLLSketch, b: KLLSketch) -> KLLSketch:
    """Pairwise merge — pure, jit-safe, shape-preserving.

    Levelwise: combine both level buffers with the carry promoted from below;
    an over-capacity level compacts (odd-position half up one level at double
    weight, even half dropped, odd-count leftover kept). The carry buffer
    holds ≤ ``2*capacity`` items (``n ≤ 4*capacity`` ⇒ promote ≤
    ``2*capacity``), so every intermediate shape is static. A carry out of
    the top level cannot be represented and latches ``overflow``.
    """
    levels, capacity = a.items.shape
    if b.items.shape != (levels, capacity):
        raise ValueError(
            f"cannot merge KLL sketches of different geometry: {a.items.shape} vs {b.items.shape}"
        )
    dtype = a.items.dtype
    inf = jnp.asarray(jnp.inf, dtype)
    items = jnp.full((levels, capacity), jnp.inf, dtype)
    sizes = jnp.zeros((levels,), jnp.int32)
    compactions = a.compactions + b.compactions
    carry_items = jnp.full((2 * capacity,), jnp.inf, dtype)
    carry_n = jnp.asarray(0, jnp.int32)
    slot = jnp.arange(capacity)
    cslot = jnp.arange(2 * capacity)
    for level in range(levels):
        combined = jnp.sort(jnp.concatenate([a.items[level], b.items[level], carry_items]))
        n = a.sizes[level] + b.sizes[level] + carry_n
        too_big = n > capacity
        # fits: first n slots of the sorted 4K buffer are the live items
        kept_small = combined[:capacity]
        # compacts: only the odd-count leftover (the largest paired-out item)
        # stays at this level; everything else promotes or drops
        leftover = combined[jnp.maximum(n - 1, 0)]
        kept_big = jnp.where((slot == 0) & (n % 2 == 1), leftover, inf)
        items = items.at[level].set(jnp.where(too_big, kept_big, kept_small))
        sizes = sizes.at[level].set(jnp.where(too_big, n % 2, n))
        compactions = compactions.at[level].add(too_big.astype(jnp.int32))
        # odd positions 1,3,5,... of the live prefix promote at double weight
        promoted = combined[1::2]
        carry_items = jnp.where(too_big & (2 * cslot + 1 < n), promoted, inf)
        carry_n = jnp.where(too_big, n // 2, 0)
    return KLLSketch(
        items=items,
        sizes=sizes,
        compactions=compactions,
        count=a.count + b.count,
        minimum=jnp.minimum(a.minimum, b.minimum),
        maximum=jnp.maximum(a.maximum, b.maximum),
        overflow=a.overflow | b.overflow | (carry_n > 0),
    )


def kll_update(state: KLLSketch, x: Array) -> KLLSketch:
    """Fold a batch of values into the sketch (jit-safe; batch shape static
    under trace, state shapes unchanged)."""
    x = jnp.asarray(x)
    if x.size == 0:  # static under trace — empty updates are identity
        return state
    levels, capacity = state.items.shape
    return kll_merge(state, _sketch_of_batch(x, levels, capacity, state.items.dtype))


def _weighted_items(state: KLLSketch) -> Tuple[Array, Array]:
    """All live items flattened with their integer weights (dead slots get
    weight 0; their +inf values sort to the end)."""
    levels, capacity = state.items.shape
    values = state.items.reshape(-1)
    level_w = jnp.left_shift(jnp.asarray(1, jnp.int32), jnp.arange(levels, dtype=jnp.int32))
    weights = jnp.broadcast_to(level_w[:, None], (levels, capacity)).reshape(-1)
    live = (jnp.arange(capacity)[None, :] < state.sizes[:, None]).reshape(-1)
    return values, jnp.where(live, weights, 0)


def _sorted_cdf_arrays(state: KLLSketch) -> Tuple[Array, Array]:
    values, weights = _weighted_items(state)
    order = jnp.argsort(values)
    sv = values[order]
    cum = jnp.cumsum(weights[order])
    return sv, cum


def kll_quantile(state: KLLSketch, q: Union[float, Array]) -> Array:
    """Approximate ``q``-quantile(s); scalar or vector ``q``. Exact at the
    endpoints (the sketch tracks true min/max); NaN on an empty sketch."""
    sv, cum = _sorted_cdf_arrays(state)
    q = jnp.asarray(q, sv.dtype)
    count = state.count.astype(sv.dtype)
    target = jnp.clip(jnp.ceil(q * count), 1.0, jnp.maximum(count, 1.0))
    idx = jnp.clip(jnp.searchsorted(cum.astype(sv.dtype), target, side="left"), 0, sv.size - 1)
    out = jnp.clip(sv[idx], state.minimum, state.maximum)
    out = jnp.where(q <= 0.0, state.minimum, jnp.where(q >= 1.0, state.maximum, out))
    return jnp.where(state.count > 0, out, jnp.asarray(jnp.nan, sv.dtype))


def kll_rank(state: KLLSketch, v: Union[float, Array]) -> Array:
    """Approximate number of folded points ``<= v`` (scalar or vector ``v``)."""
    sv, cum = _sorted_cdf_arrays(state)
    pos = jnp.searchsorted(sv, jnp.asarray(v, sv.dtype), side="right")
    padded = jnp.concatenate([jnp.zeros((1,), cum.dtype), cum])
    return padded[pos]


def kll_cdf(state: KLLSketch, v: Union[float, Array]) -> Array:
    """Approximate empirical CDF at ``v`` — ``rank(v) / count`` (0 when empty)."""
    denom = jnp.maximum(state.count, 1)
    return kll_rank(state, v).astype(state.items.dtype) / denom.astype(state.items.dtype)


def kll_error_bound(state: KLLSketch) -> Array:
    """Hard deterministic bound on the rank error of any query:
    ``sum_l compactions[l] * 2**l`` (+inf once ``overflow`` latched — dropped
    items void every guarantee)."""
    levels = state.compactions.shape[0]
    weights = jnp.left_shift(jnp.asarray(1, jnp.int32), jnp.arange(levels, dtype=jnp.int32))
    bound = jnp.sum(state.compactions * weights).astype(jnp.float32)
    return jnp.where(state.overflow, jnp.asarray(jnp.inf, jnp.float32), bound)


def kll_state_bytes(state: KLLSketch) -> int:
    """Total bytes of the (fixed-shape) state — the number that stays flat
    while a ``cat`` state grows with the stream."""
    return sum(leaf.size * leaf.dtype.itemsize for leaf in jax.tree_util.tree_leaves(state))


register_sketch_state(KLLSketch, kll_merge)
