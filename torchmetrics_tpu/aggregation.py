# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Aggregation metrics: turn raw streamed values into metrics.

Capability parity with reference ``src/torchmetrics/aggregation.py`` (727 LoC):
``BaseAggregator`` with NaN strategies, ``MaxMetric``/``MinMetric``/
``SumMetric``/``CatMetric``/``MeanMetric`` (weighted), and windowed
``RunningMean``/``RunningSum`` — plus the bounded-memory ``Quantile``/
``Median`` built on the KLL sketch (``torchmetrics_tpu.sketch``,
ARCHITECTURE.md §11): the streaming answer to ``CatMetric`` +
``jnp.quantile``, in O(1) state.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.sketch import (
    kll_error_bound,
    kll_geometry,
    kll_init,
    kll_levels_for,
    kll_quantile,
    kll_update,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.wrappers.running import Running

Array = jax.Array


class BaseAggregator(Metric):
    """Base for aggregation metrics (reference ``aggregation.py:30``).

    ``nan_strategy``: ``"error"|"warn"|"ignore"|"disable"`` or a float used to
    impute NaNs (reference ``aggregation.py:75-107``). The imputation/masking
    is done with jnp.where so the update stays jit-safe; "error"/"warn" probe
    the value on host and therefore only fire in eager mode.
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore", "disable")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, (int, float)):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)
        self.state_name = state_name

    def _cast_and_nan_check_input(self, x: Union[float, Array], weight: Optional[Union[float, Array]] = None):
        """Cast to float array and handle NaNs per strategy (reference ``aggregation.py:75``)."""
        x = jnp.asarray(x, dtype=jnp.float32) if not isinstance(x, jax.Array) else x.astype(jnp.float32)
        if weight is not None:
            weight = jnp.asarray(weight, dtype=jnp.float32) if not isinstance(weight, jax.Array) else weight.astype(jnp.float32)
            weight = jnp.broadcast_to(weight, x.shape)
        else:
            weight = jnp.ones_like(x)
        if self.nan_strategy == "disable":
            return x, weight
        nan_mask = jnp.isnan(x)
        if self.nan_strategy in ("error", "warn"):
            import numpy as np

            if not isinstance(x, jax.core.Tracer) and bool(np.any(np.asarray(nan_mask))):
                if self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                from torchmetrics_tpu.utilities.prints import rank_zero_warn

                rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                x = x[~np.asarray(nan_mask)]
                weight = weight[~np.asarray(nan_mask)]
            return x, weight
        if self.nan_strategy == "ignore":
            # jit-safe masking: zero weight on NaN entries, replace value by 0
            weight = jnp.where(nan_mask, 0.0, weight)
            x = jnp.where(nan_mask, 0.0, x)
            return x, weight
        # float imputation
        x = jnp.where(nan_mask, jnp.asarray(float(self.nan_strategy), x.dtype), x)
        return x, weight

    def update(self, value: Union[float, Array]) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def compute(self) -> Array:
        return getattr(self, self.state_name)


class MaxMetric(BaseAggregator):
    """Running max (reference ``aggregation.py:114``)."""

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf), nan_strategy, state_name="max_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if self.nan_strategy == "ignore":
            value = jnp.where(jnp.isnan(jnp.asarray(value)), -jnp.inf, value)
        if value.size:
            self.max_value = jnp.maximum(self.max_value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running min (reference ``aggregation.py:219``)."""

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf), nan_strategy, state_name="min_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if self.nan_strategy == "ignore":
            value = jnp.where(jnp.isnan(jnp.asarray(value)), jnp.inf, value)
        if value.size:
            self.min_value = jnp.minimum(self.min_value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum (reference ``aggregation.py:324``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, state_name="sum_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.sum_value = self.sum_value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate all seen values (reference ``aggregation.py:429``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, state_name="value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        return dim_zero_cat(self.value) if isinstance(self.value, list) and self.value else self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean (reference ``aggregation.py:493``)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, state_name="mean_value", **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        value, weight = self._cast_and_nan_check_input(value, weight)
        if value.size == 0:
            return
        self.mean_value = self.mean_value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        return self.mean_value / self.weight


class Quantile(BaseAggregator):
    """Streaming quantile(s) in bounded memory via a KLL sketch.

    The ``dist_reduce_fx="merge"`` counterpart of ``CatMetric`` +
    ``jnp.quantile``: the state is a fixed-shape
    :class:`~torchmetrics_tpu.sketch.KLLSketch` (so it jits, shards, syncs by
    pairwise merge, and checkpoints like any elementwise state) and every
    query's rank error is bounded by ``eps * n`` — the live bound for the
    current stream is :meth:`error_bound`.

    Args:
        q: quantile (or sequence of quantiles) in ``[0, 1]`` to report.
        eps: target worst-case rank-error fraction; the sketch geometry is
            sized from it (ignored when ``capacity``/``levels`` are given).
        max_n: stream length the ``eps`` sizing must hold for.
        capacity/levels: explicit sketch geometry override.
        nan_strategy: as every aggregator (``"error"|"warn"|"ignore"|float``).
    """

    full_state_update = False

    def __init__(
        self,
        q: Union[float, Sequence[float]] = 0.5,
        eps: float = 0.01,
        max_n: float = 1e8,
        capacity: Optional[int] = None,
        levels: Optional[int] = None,
        nan_strategy: Union[str, float] = "warn",
        **kwargs: Any,
    ) -> None:
        q_arr = jnp.asarray(q, jnp.float32)
        if bool(jnp.any((q_arr < 0) | (q_arr > 1))):
            raise ValueError(f"Expected quantile(s) `q` in [0, 1], but got {q}")
        if capacity is None:
            sized_capacity, sized_levels = kll_geometry(eps, max_n)
            capacity = sized_capacity
            levels = sized_levels if levels is None else levels
        elif levels is None:
            # levels must be derived from the GIVEN capacity: a smaller
            # buffer needs MORE levels to absorb the same max_n before the
            # overflow latch voids every guarantee
            levels = kll_levels_for(capacity, max_n)
        super().__init__("merge", kll_init(capacity=capacity, levels=levels), nan_strategy, state_name="sketch", **kwargs)
        self.q = q_arr
        self.eps = eps

    def update(self, value: Union[float, Array]) -> None:
        if self.nan_strategy == "ignore":
            # the other aggregators mask NaNs to zero WEIGHT, but a sketch
            # point has no weight channel — truly dropping them needs a
            # data-dependent size, which only the eager (host) path can do
            value = jnp.asarray(value, dtype=jnp.float32).ravel()
            if isinstance(value, jax.core.Tracer):
                raise ValueError(
                    "Quantile(nan_strategy='ignore') cannot run inside a traced update (dropping"
                    " NaNs is data-dependent-shape); use a float imputation strategy or pre-filter"
                )
            value = value[~jnp.isnan(value)]
        else:
            value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.sketch = kll_update(self.sketch, value)

    def compute(self) -> Array:
        """The ``q``-quantile(s) of everything streamed so far."""
        return kll_quantile(self.sketch, self.q)

    def error_bound(self) -> Array:
        """Hard deterministic bound on the rank error of :meth:`compute`
        (``sum_l compactions[l] * 2**l``; divide by ``n`` for the fraction)."""
        return kll_error_bound(self.sketch)


class Median(Quantile):
    """Streaming median in bounded memory — ``Quantile(q=0.5)``."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(q=0.5, nan_strategy=nan_strategy, **kwargs)


class RunningMean(Running):
    """Mean over the last ``window`` updates (reference ``aggregation.py:616``)."""

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=MeanMetric(nan_strategy=nan_strategy, **kwargs), window=window)


class RunningSum(Running):
    """Sum over the last ``window`` updates (reference ``aggregation.py:673``)."""

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=SumMetric(nan_strategy=nan_strategy, **kwargs), window=window)
