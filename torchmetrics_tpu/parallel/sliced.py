# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Sliced evaluation plane: thousands of cohort cells in ONE compiled dispatch.

A serving-scale eval plane answers "accuracy per country, per model-version"
— which naively means one ``Metric`` instance per cohort and one Python
``update()`` dispatch per cohort per batch: exactly the per-member host cost
the fused plane (``parallel/fused.py``) just eliminated for the single-cohort
case, multiplied by thousands. :class:`SlicedPlan` is the fixed-shape
successor of the reference's one-wrapper-per-cohort pattern:

- **slice table** — a fixed-capacity open-addressed hash table maps cohort
  keys (integer arrays, one value or tuple per batch row) to cell indices
  *inside the compiled step*: murmur-style mixing, linear probing via a
  ``lax.while_loop`` (each round resolves claims with a deterministic
  lowest-row-wins scatter, so insertion is order-independent and replayable),
  no deletions — a key's cell is stable for the plan's lifetime. Rows whose
  key finds no cell after a full sweep are DROPPED and latched into a spill
  counter (``slice.table.spills``) — overflow never corrupts resident cells.
- **cell-carried state** — every registered state of every compute-group
  leader carries a leading ``[num_cells]`` axis in one donated, scan-able
  carry (the PR-9 machinery). A batch updates ALL cells in one dispatch:
  the member's own ``update`` is traced per row (``vmap`` over the batch
  axis) and the per-row fresh states are segment-scattered into their cells
  — ``segment_sum``/``max``/``min`` for elementwise states, an offset
  scatter into per-cell :class:`CatBuffer`\\ s for list ("cat") states, and a
  pairwise sketch ``merge`` fold for ``dist_reduce_fx="merge"`` states.
  Queries (:meth:`compute_all`) lift the member's ``compute`` over the cell
  axis with ``vmap`` — N-thousand cohort values in one dispatch too.

**Exactness contract.** Splitting a batch by cohort is the SAME contract
in-step sharding already relies on: ``update(A ∪ B) == reduce(update(A),
update(B))`` under the state's declared ``dist_reduce_fx``. Any metric that
is ``sharded_update``-exact at row granularity is sliced-exact:
``sliced(k=N)`` equals N independent per-cohort metrics bitwise for integer
elementwise states, cat states (row order within a cell is preserved), and
add-style sketch states (``HistogramSketch``/``MomentsSketch`` counts);
float sums agree up to summation order. Array states declaring ``mean``,
``None`` or callable reductions are refused at build — their fold is either
ambiguous at row granularity (``mean`` weights) or grows the carry
(stacking), same refusal as the fused sharded plane.

**Memory.** The per-row decomposition materializes ``[batch, *state]``
intermediates before the segment reduce; with very large per-metric states
(big confusion curves) size batches accordingly. The carry itself is
``num_cells ×`` the member's state — the whole point: thousands of cohorts
at a fixed, known footprint.

**Sharded variant** (``mesh=``): batch rows shard over the mesh axis; the
slice-table assignment runs replicated on the full key vector (every device
agrees on the table), per-device row states segment-reduce locally and
mesh-reduce with the same collectives as ``sharded_update``
(``psum``/``pmax``/``pmin``); cat rows and sketch row-states ``all_gather``
(device-ordered, like the cat reduction of ``mesh_reduce_tree``) and fold
replicated — so sliced-sharded == sliced-local bitwise on the same batch.

Durability: :meth:`save_checkpoint`/:meth:`load_checkpoint` round-trip the
whole carry (table included) as plain numpy dicts through
``CheckpointStore`` — kill-and-resume == uninterrupted, pinned in
``tests/unittests/bases/test_sliced.py``.
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from torchmetrics_tpu.obs import attribution as _obs_attr
from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.obs import xla as _obs_xla
from torchmetrics_tpu.parallel.cat_buffer import CatBuffer, cat_buffer_values
from torchmetrics_tpu.parallel.fused import _MemberInfo, _resolve_members, fusion_ineligibility
from torchmetrics_tpu.parallel.sharded import (
    _batch_update_state,
    _fingerprint_digest,
    _walk_fingerprint,
    plan_cache_lookup,
    plan_cache_store,
    shard_map,
)
from torchmetrics_tpu.sketch.registry import is_sketch_state, merge_states, sketch_state_class
from torchmetrics_tpu.utilities.exceptions import StateRestoreError

Array = jax.Array

__all__ = [
    "SlicedPlan",
    "SliceTable",
    "sliced_ineligibility",
    "slice_key_reason",
    "slice_table_size_reason",
]

#: payload layout version of :meth:`SlicedPlan.save_checkpoint`
SLICED_FORMAT_VERSION = 1

#: reductions whose per-cell fold is exact at row granularity (see module
#: docstring); ``cat`` covers list states, ``merge`` sketch states
_SLICEABLE_REDUCTIONS = ("sum", "max", "min", "cat", "merge")


# -------------------------------------------------------------- eligibility


def slice_table_size_reason(num_cells: Any) -> Optional[str]:
    """Why ``num_cells`` cannot size a slice table, or ``None``.

    The SAME predicate metriclint's ML008 applies statically: the table is a
    compiled-in shape, so its size must be a static positive python int —
    float expressions (``cells / 2``) and trace-dependent values (``jnp``
    results) are dynamic-shape sizing and are refused.
    """
    if isinstance(num_cells, bool) or not isinstance(num_cells, int):
        return (
            f"num_cells must be a static positive python int (a compiled-in shape), got"
            f" {type(num_cells).__name__} — float or traced sizing is dynamic-shape"
        )
    if num_cells < 1:
        return f"num_cells must be >= 1, got {num_cells}"
    return None


def slice_key_reason(dtype: Any) -> Optional[str]:
    """Why a cohort-key dtype cannot enter the slice table, or ``None``.

    The SAME predicate metriclint's ML008 applies statically: keys are hashed
    and compared for exact equality inside the compiled step, so they must be
    integer (or bool) arrays — float keys are unhashable cohorts (1.0000001
    is a new cohort every batch).
    """
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer) or dtype == jnp.bool_:
        return None
    return (
        f"cohort keys must be integer (hashable) arrays, got dtype {dtype} — bucket or"
        " hash float features to ints on the producer side"
    )


def sliced_ineligibility(metric: Any) -> Optional[str]:
    """Why ``metric`` cannot enter a sliced plan, or ``None`` when it can.

    Everything fusion requires (traceable positional update, no host state)
    plus the row-granular fold contract: every array state must declare a
    named reduction from ``{sum, max, min, merge}`` (list states are ``cat``).
    """
    reason = fusion_ineligibility(metric)
    if reason:
        return reason
    for name, red in metric._reductions.items():
        default = metric._defaults[name]
        if isinstance(default, list):
            if red not in ("cat", None):
                return (
                    f"list state {name!r} declares dist_reduce_fx={red!r}; sliced list"
                    " states append per cell (cat semantics)"
                )
            continue
        if red == "mean":
            return (
                f"state {name!r} declares dist_reduce_fx='mean': the per-cell fold weight"
                " (rows vs update events) is ambiguous at row granularity — restructure as"
                " sum + count states (like MeanMetric) to slice exactly"
            )
        if red not in _SLICEABLE_REDUCTIONS:
            return (
                f"state {name!r} declares dist_reduce_fx={red!r}, whose stacking fold grows"
                " the state per step — a fixed-shape cell carry needs a named reduction"
                " (sum/max/min/merge)"
            )
    return None


# --------------------------------------------------------------- slice table


class SliceTable(NamedTuple):
    """The cohort-key → cell-index map, carried inside the compiled step."""

    keys: Array  # (num_cells, key_width) int32; rows meaningful only where occupied
    occupied: Array  # (num_cells,) bool
    spills: Array  # () int32: rows dropped because a full probe sweep found no cell


def _rotl32(x: Array, r: int) -> Array:
    return (x << r) | (x >> (32 - r))


def _hash_rows(kmat: Array) -> Array:
    """Murmur3-style mix of the key columns → ``[B]`` uint32 (wrapping
    uint32 arithmetic; column count is static so the mix unrolls)."""
    h = jnp.full((kmat.shape[0],), 0x811C9DC5, jnp.uint32)
    for i in range(kmat.shape[1]):
        k = kmat[:, i].astype(jnp.uint32) * jnp.uint32(0xCC9E2D51)
        k = _rotl32(k, 15) * jnp.uint32(0x1B873593)
        h = _rotl32(h ^ k, 13) * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def _assign_cells(table: SliceTable, kmat: Array) -> Tuple[SliceTable, Array]:
    """Place every batch row's key in the table (linear probing, inserting
    new keys) and return ``(new_table, cell_ids)`` with ``-1`` for spilled
    rows. Deterministic under SPMD: contested empty slots go to the lowest
    row index, and since the table never deletes, a key's probe chain can
    never pass an empty slot before its resident cell.
    """
    num_cells = table.keys.shape[0]
    batch = kmat.shape[0]
    rows = jnp.arange(batch, dtype=jnp.int32)
    h0 = (_hash_rows(kmat) % jnp.uint32(num_cells)).astype(jnp.int32)

    # fast path: a one-shot associative lookup resolves every RESIDENT key
    # (keys are unique in the table, so equality finds the open-addressed
    # slot directly). The probe loop below then only spins for batches that
    # actually INSERT new cohorts — in steady state (every cohort resident)
    # its condition is false on entry and the per-batch cost is this single
    # [batch, num_cells] compare, not max-displacement × scatter rounds.
    resident = jnp.all(table.keys[None, :, :] == kmat[:, None, :], axis=-1) & table.occupied[None, :]
    cells0 = jnp.where(
        resident.any(axis=1), resident.argmax(axis=1).astype(jnp.int32), jnp.int32(-1)
    )

    def cond(carry):
        j, cells, _tkeys, _occ = carry
        return jnp.logical_and(j < num_cells, jnp.any(cells < 0))

    def body(carry):
        j, cells, tkeys, occ = carry
        slot = (h0 + j) % num_cells
        match = occ[slot] & jnp.all(tkeys[slot] == kmat, axis=1)
        cells = jnp.where((cells < 0) & match, slot, cells)
        cand = (cells < 0) & ~occ[slot]
        # deterministic claim: lowest contending row index wins the slot
        winner = (
            jnp.full((num_cells,), batch, jnp.int32)
            .at[jnp.where(cand, slot, num_cells)]
            .min(rows, mode="drop")
        )
        is_winner = cand & (winner[slot] == rows)
        tkeys = tkeys.at[jnp.where(is_winner, slot, num_cells)].set(kmat, mode="drop")
        occ = occ.at[jnp.where(is_winner, slot, num_cells)].set(True, mode="drop")
        # losers with the winner's key still land here; other losers reprobe
        match2 = occ[slot] & jnp.all(tkeys[slot] == kmat, axis=1)
        cells = jnp.where(cand & match2, slot, cells)
        return j + 1, cells, tkeys, occ

    init = (jnp.asarray(0, jnp.int32), cells0, table.keys, table.occupied)
    _, cells, tkeys, occ = jax.lax.while_loop(cond, body, init)
    spilled = (cells < 0).sum().astype(jnp.int32)
    return SliceTable(keys=tkeys, occupied=occ, spills=table.spills + spilled), cells


def _within_cell_rank(cells: Array) -> Array:
    """Per row: how many earlier batch rows share its cell — the cat-scatter
    offset that preserves row order within a cell."""
    batch = cells.shape[0]
    idx = jnp.arange(batch, dtype=jnp.int32)
    order = jnp.argsort(cells, stable=True)
    sorted_cells = cells[order]
    starts = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_cells[1:] != sorted_cells[:-1]]
    )
    seg_start = jax.lax.associative_scan(jnp.maximum, jnp.where(starts, idx, 0))
    return jnp.zeros((batch,), jnp.int32).at[order].set(idx - seg_start)


# ----------------------------------------------------------- per-row updates


def _row_states(info: _MemberInfo, batch: Tuple[Any, ...]) -> Tuple[Dict[str, Any], int]:
    """One fresh update per batch row, vmapped: every leaf gains a leading
    ``[batch]`` axis (list states: each appended chunk gains it). The row is
    presented as a size-1 batch so the member's ``update`` sees its ordinary
    batched shapes."""
    arrays = [jnp.asarray(a) for a in batch]
    lead = [a.shape[0] for a in arrays if a.ndim >= 1]
    if not lead:
        raise ValueError("sliced update needs at least one batched (ndim >= 1) input")
    batch_rows = lead[0]
    in_axes = tuple(0 if a.ndim >= 1 else None for a in arrays)
    staged = tuple(
        a.reshape((batch_rows, 1) + a.shape[1:]) if ax == 0 else a
        for a, ax in zip(arrays, in_axes)
    )

    def one(*row: Any) -> Dict[str, Any]:
        return _batch_update_state(info.metric, row, {})

    return jax.vmap(one, in_axes=in_axes)(*staged), batch_rows


def _segment_reduce(red: str, rows: Array, seg: Array, num_cells: int) -> Array:
    """Reduce per-row state leaves into cells; spilled rows carry segment id
    ``num_cells`` and fall off the ``[:num_cells]`` slice."""
    if red == "sum":
        return jax.ops.segment_sum(rows, seg, num_segments=num_cells + 1)[:num_cells]
    if red == "max":
        return jax.ops.segment_max(rows, seg, num_segments=num_cells + 1)[:num_cells]
    if red == "min":
        return jax.ops.segment_min(rows, seg, num_segments=num_cells + 1)[:num_cells]
    raise ValueError(f"unexpected sliced array reduction {red!r}")


def _merge_cells(red: str, carry: Array, fresh: Array, recv: Array) -> Array:
    """Fold a batch's per-cell fresh states into the carry; cells that
    received no rows keep their carry bitwise (segment identities never
    leak in)."""
    if red == "sum":
        merged = carry + fresh
    elif red == "max":
        merged = jnp.maximum(carry, fresh)
    elif red == "min":
        merged = jnp.minimum(carry, fresh)
    else:  # pragma: no cover - guarded by sliced_ineligibility
        raise ValueError(f"unexpected sliced array reduction {red!r}")
    mask = recv.reshape(recv.shape + (1,) * (merged.ndim - 1))
    return jnp.where(mask, merged, carry)


def _scatter_cat(buf: CatBuffer, appended: Sequence[Array], cells: Array, seg: Array) -> CatBuffer:
    """Scatter each row's appended cat rows into its cell's buffer at offset
    ``count[cell] + within_cell_rank * rows_per_update`` — row order within a
    cell is preserved, overflow drops + latches per cell, spilled rows drop.
    """
    rows2 = jnp.concatenate([a for a in appended], axis=1)  # [B, R, *elem]
    batch, per_update = rows2.shape[0], rows2.shape[1]
    num_cells, cap = buf.data.shape[0], buf.data.shape[1]
    ranks = _within_cell_rank(cells)
    base = jnp.where(cells >= 0, buf.count[jnp.clip(cells, 0)], 0)
    pos = base[:, None] + ranks[:, None] * per_update + jnp.arange(per_update, dtype=jnp.int32)[None, :]
    cell_idx = jnp.broadcast_to(
        jnp.where(cells >= 0, cells, num_cells)[:, None], (batch, per_update)
    )
    data = buf.data.at[cell_idx.reshape(-1), pos.reshape(-1)].set(
        rows2.reshape((batch * per_update,) + rows2.shape[2:]).astype(buf.data.dtype),
        mode="drop",
    )
    added = jax.ops.segment_sum(
        jnp.full((batch,), per_update, jnp.int32), seg, num_segments=num_cells + 1
    )[:num_cells]
    new_total = buf.count + added
    return CatBuffer(
        data=data,
        count=jnp.minimum(new_total, cap).astype(jnp.int32),
        overflowed=buf.overflowed | (new_total > cap),
    )


def _fold_sketch(cell_states: Any, row_states: Any, cells: Array, batch: int) -> Any:
    """Pairwise-merge each row's fresh sketch into its cell (serial over the
    batch — sketch merges are arbitrary functions, not segment reductions).
    Spilled rows write back the untouched cell state."""

    def body(i, acc):
        c = cells[i]
        safe = jnp.maximum(c, 0)
        cur = jax.tree_util.tree_map(lambda x: x[safe], acc)
        row = jax.tree_util.tree_map(lambda x: x[i], row_states)
        merged = merge_states(cur, row)

        def write(x, m, old):
            return x.at[safe].set(jnp.where(c >= 0, m, old))

        return jax.tree_util.tree_map(write, acc, merged, cur)

    return jax.lax.fori_loop(0, batch, body, cell_states)


# ---------------------------------------------------------------- the plan


class SlicedPlan:
    """Fan a metric (or ``MetricCollection``) out over cohort cells — one
    compiled dispatch per batch for ALL cells.

    ::

        acc = MulticlassAccuracy(num_classes=10, validate_args=False)
        plan = acc.sliced(num_cells=1024)
        for country, preds, target in stream:
            plan.update(country, preds, target)   # one dispatch, 1024 cohorts
        per_cohort = plan.results()               # {(country,): accuracy}

    Args:
        target: a ``Metric`` or ``MetricCollection`` used as the per-cell
            TEMPLATE — it must be pristine (``reset()``); its accumulated
            state never enters the cells.
        num_cells: slice-table capacity — a static python int (the compiled
            shape); metriclint ML008 flags dynamic/float sizing statically.
        key_width: number of integer key components per row (a tuple of K
            arrays or a ``[B, K]`` array at ``update``); default 1.
        example_keys: optional example of the cohort keys ``update`` will
            receive — validated eagerly (integer dtype, the ML008-shared
            predicate) and used to infer ``key_width``; passing BOTH with
            disagreeing widths raises at construction.
        cat_capacity: max rows PER CELL for list ("cat") states.
        example_batch: example positional batch (sizes CatBuffer row shapes).
        donate: donate the carry (default True) — hold no refs to
            ``plan.state`` across updates.
        mesh/axis_name: build the sharded variant (batch rows shard over the
            mesh axis; the table assignment replicates).
    """

    def __init__(
        self,
        target: Any,
        *,
        num_cells: int,
        key_width: Optional[int] = None,
        example_keys: Optional[Any] = None,
        cat_capacity: Optional[int] = None,
        example_batch: Optional[Tuple[Any, ...]] = None,
        donate: bool = True,
        mesh: Optional[Mesh] = None,
        axis_name: str = "data",
    ) -> None:
        from torchmetrics_tpu.collections import MetricCollection

        reason = slice_table_size_reason(num_cells)
        if reason:
            raise ValueError(f"cannot build a slice table: {reason}")
        if key_width is not None and (not isinstance(key_width, int) or key_width < 1):
            raise ValueError(f"key_width must be a positive int, got {key_width!r}")
        if example_keys is not None:
            cols = (
                [jnp.asarray(k) for k in example_keys]
                if isinstance(example_keys, (tuple, list))
                else [jnp.asarray(example_keys)]
            )
            if len(cols) == 1 and cols[0].ndim == 2:
                cols = [cols[0][:, i] for i in range(cols[0].shape[1])]
            for col in cols:
                key_issue = slice_key_reason(col.dtype)
                if key_issue:
                    raise ValueError(f"bad example_keys: {key_issue}")
            if key_width is not None and key_width != len(cols):
                raise ValueError(
                    f"key_width={key_width} disagrees with example_keys"
                    f" ({len(cols)} component(s)) — drop one or make them match"
                )
            key_width = len(cols)
        key_width = 1 if key_width is None else key_width
        members, groups = _resolve_members(target)
        report = {k: sliced_ineligibility(m) for k, m in members.items()}
        bad = {k: r for k, r in report.items() if r}
        if bad:
            detail = "; ".join(f"{k}: {r}" for k, r in sorted(bad.items()))
            raise ValueError(f"cannot slice {type(target).__name__}: {detail}")
        dirty = sorted(k for k, m in members.items() if m._update_count > 0)
        if dirty:
            raise ValueError(
                f"sliced plans start from a pristine per-cell template; member(s) {dirty}"
                " hold accumulated state — reset() the target first (restore progress via"
                " plan.load_checkpoint instead)"
            )
        self.members = members
        self.groups = groups
        self._collection = target if isinstance(target, MetricCollection) else None
        self._target = target
        self._target_cls = type(target).__name__
        self._template = deepcopy(target)
        self.num_cells = num_cells
        self.key_width = key_width
        self._cat_capacity = cat_capacity
        self._donate = bool(donate)
        self._mesh = mesh
        self._axis = axis_name
        self._infos = [
            _MemberInfo(cg[0], members[cg[0]], cat_capacity, example_batch) for cg in groups
        ]
        if _obs_trace.ENABLED:
            with _obs_trace.span(
                "sliced.build",
                metric=self._target_cls,
                cells=num_cells,
                leaders=len(self._infos),
                sharded=mesh is not None,
            ):
                self._build_steps()
        else:
            self._build_steps()
        self._state = self._initial_state()
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_attr.note_instances(type(self).__name__, list(self.members))

    # ------------------------------------------------------------------ build
    def _fingerprint(self) -> str:
        return _fingerprint_digest(
            "sliced",
            self._target_cls,
            tuple(
                (info.key, type(info.metric).__name__, _walk_fingerprint(info.metric), tuple(info.list_keys))
                for info in self._infos
            ),
            tuple(tuple(cg) for cg in self.groups),
            self.num_cells,
            self.key_width,
            self._cat_capacity,
            self._donate,
            self._axis if self._mesh is not None else None,
        )

    def stable_fingerprint(self) -> str:
        """Process-independent identity for checkpoint validation: the
        members' registry fingerprints plus the table geometry."""
        from torchmetrics_tpu.robustness.checkpoint import checkpoint_fingerprint

        return _fingerprint_digest(
            "sliced-ckpt",
            self._target_cls,
            tuple(sorted((k, checkpoint_fingerprint(m)) for k, m in self.members.items())),
            self.num_cells,
            self.key_width,
            self._cat_capacity,
        )

    def _build_steps(self) -> None:
        raw = self._build_sharded_raw_step() if self._mesh is not None else self._build_local_raw_step()
        jit_kwargs = {"donate_argnums": 0} if self._donate else {}
        key = self._fingerprint()
        cache_key, cached = plan_cache_lookup("sliced", self._target, self._mesh, self._axis, key)
        if cached is not None:
            self._step, self._scan_step = cached
            return

        def step_fn(state, kmat, *batch):
            return raw(state, kmat, batch)

        def chunk_fn(state, stacked):
            def body(s, kb):
                return raw(s, kb[0], kb[1:]), None

            return jax.lax.scan(body, state, stacked)[0]

        self._step = _obs_xla.instrument_jit(
            jax.jit(step_fn, **jit_kwargs),
            key=key, metric=self._target_cls, kind="sliced", span_prefix="sliced.update",
        )
        self._scan_step = _obs_xla.instrument_jit(
            jax.jit(chunk_fn, **jit_kwargs),
            key=f"{key}:scan", metric=self._target_cls, kind="sliced_scan", span_prefix="sliced.scan",
        )
        plan_cache_store(
            "sliced", cache_key, self._target, self._mesh, (self._step, self._scan_step)
        )

    def _fold_member(self, info: _MemberInfo, mstate, row_states, cells, batch):
        num_cells = self.num_cells
        seg = jnp.where(cells >= 0, cells, num_cells)
        recv = jnp.zeros((num_cells,), bool).at[seg].set(True, mode="drop")
        out: Dict[str, Any] = {}
        for name in info.metric._defaults:
            red = info.reductions[name]
            if name in info.list_keys:
                out[name] = _scatter_cat(mstate[name], row_states[name], cells, seg)
            elif red == "merge":
                out[name] = _fold_sketch(mstate[name], row_states[name], cells, batch)
            else:
                fresh = _segment_reduce(red, row_states[name], seg, num_cells)
                out[name] = _merge_cells(red, mstate[name], fresh, recv)
        out["_update_count"] = mstate["_update_count"] + recv.astype(jnp.int32)
        return out

    def _build_local_raw_step(self):
        infos = self._infos

        def raw_step(state, kmat, batch):
            table, cells = _assign_cells(state["table"], kmat)
            out_members = {}
            for info in infos:
                row_states, batch_rows = _row_states(info, batch)
                out_members[info.key] = self._fold_member(
                    info, state["members"][info.key], row_states, cells, batch_rows
                )
            return {
                "members": out_members,
                "table": table,
                "_update_count": state["_update_count"] + 1,
            }

        return raw_step

    def _build_sharded_raw_step(self):
        infos, axis, mesh = self._infos, self._axis, self._mesh
        num_cells = self.num_cells

        def raw_step(state, kmat, batch):
            # table assignment replicates over the FULL key vector so every
            # device agrees on the cohort → cell map
            table, cells = _assign_cells(state["table"], kmat)
            seg = jnp.where(cells >= 0, cells, num_cells)

            def per_device(cells_shard, seg_shard, *batch_shard):
                out: Dict[str, Any] = {}
                for info in infos:
                    row_states, _ = _row_states(info, batch_shard)
                    member_out: Dict[str, Any] = {}
                    for name in info.metric._defaults:
                        red = info.reductions[name]
                        if name in info.list_keys or red == "merge":
                            # gather device-ordered rows; the fold runs
                            # replicated outside with the global cell ids
                            member_out[name] = jax.tree_util.tree_map(
                                lambda v: jax.lax.all_gather(v, axis).reshape(
                                    (-1,) + tuple(v.shape[1:])
                                ),
                                row_states[name],
                            )
                        else:
                            partial = _segment_reduce(red, row_states[name], seg_shard, num_cells)
                            if red == "sum":
                                member_out[name] = jax.lax.psum(partial, axis)
                            elif red == "max":
                                member_out[name] = jax.lax.pmax(partial, axis)
                            else:
                                member_out[name] = jax.lax.pmin(partial, axis)
                    out[info.key] = member_out
                return out

            specs = (P(axis), P(axis)) + tuple(
                P(axis) if getattr(jnp.asarray(a), "ndim", 0) >= 1 else P() for a in batch
            )
            fresh = shard_map(
                per_device, mesh=mesh, in_specs=specs, out_specs=P(), check_rep=False
            )(cells, seg, *batch)
            recv = jnp.zeros((num_cells,), bool).at[seg].set(True, mode="drop")
            batch_rows = cells.shape[0]
            out_members = {}
            for info in infos:
                mstate = state["members"][info.key]
                member_out: Dict[str, Any] = {}
                for name in info.metric._defaults:
                    red = info.reductions[name]
                    f = fresh[info.key][name]
                    if name in info.list_keys:
                        member_out[name] = _scatter_cat(mstate[name], f, cells, seg)
                    elif red == "merge":
                        member_out[name] = _fold_sketch(mstate[name], f, cells, batch_rows)
                    else:
                        member_out[name] = _merge_cells(red, mstate[name], f, recv)
                member_out["_update_count"] = mstate["_update_count"] + recv.astype(jnp.int32)
                out_members[info.key] = member_out
            return {
                "members": out_members,
                "table": table,
                "_update_count": state["_update_count"] + 1,
            }

        return raw_step

    def _initial_state(self) -> Dict[str, Any]:
        num_cells = self.num_cells
        members: Dict[str, Any] = {}
        for info in self._infos:
            metric = info.metric
            slice_: Dict[str, Any] = {}
            for name, default in metric._defaults.items():
                if name in info.list_keys:
                    elem, dtype = info.layout[name]
                    slice_[name] = CatBuffer(
                        data=jnp.zeros((num_cells, self._cat_capacity, *elem), dtype),
                        count=jnp.zeros((num_cells,), jnp.int32),
                        overflowed=jnp.zeros((num_cells,), bool),
                    )
                elif is_sketch_state(default):
                    slice_[name] = jax.tree_util.tree_map(
                        lambda x: jnp.repeat(jnp.asarray(x)[None], num_cells, axis=0), default
                    )
                else:
                    slice_[name] = jnp.repeat(jnp.asarray(default)[None], num_cells, axis=0)
            slice_["_update_count"] = jnp.zeros((num_cells,), jnp.int32)
            members[info.key] = slice_
        return {
            "members": members,
            "table": SliceTable(
                keys=jnp.zeros((num_cells, self.key_width), jnp.int32),
                occupied=jnp.zeros((num_cells,), bool),
                spills=jnp.asarray(0, jnp.int32),
            ),
            "_update_count": jnp.asarray(0, jnp.int32),
        }

    # ------------------------------------------------------------------ drive
    @property
    def state(self) -> Dict[str, Any]:
        """The current carry. With ``donate=True`` (default) the next
        ``update``/``run_scan`` consumes these buffers — read, don't hold."""
        return self._state

    @property
    def updates_applied(self) -> int:
        """Batches applied since the plan was built (host sync)."""
        return int(self._state["_update_count"])

    def key_matrix(self, keys: Any) -> Array:
        """Normalize cohort keys (one int array, a tuple of arrays, or a
        ``[B, K]`` matrix) to the ``[B, key_width]`` int32 the step consumes.
        Refuses float keys (the ML008-shared predicate) and guards the
        table's int32 columns: host-side 64-bit inputs are bounds-checked
        (values past int32 would silently ALIAS cohorts mod 2^32 — split
        wide ids into two components via ``key_width`` instead); 64-bit
        device arrays are refused outright (checking them would force a
        per-batch host sync)."""
        if isinstance(keys, (tuple, list)):
            raw_cols = list(keys)
        elif getattr(keys, "ndim", None) == 2:
            raw_cols = [keys[:, i] for i in range(keys.shape[1])]
        else:
            raw_cols = [keys]
        cols = []
        for raw in raw_cols:
            is_device = isinstance(raw, jax.Array)
            host = raw if is_device else np.asarray(raw)
            reason = slice_key_reason(host.dtype)
            if reason:
                raise ValueError(f"bad cohort key: {reason}")
            if host.ndim != 1:
                raise ValueError(f"cohort key components must be 1-D per row, got shape {host.shape}")
            if jnp.dtype(host.dtype).itemsize > 4:
                if is_device:
                    raise ValueError(
                        "bad cohort key: 64-bit device arrays cannot be bounds-checked without a"
                        " per-batch host sync and would silently alias cohorts mod 2^32 when"
                        " truncated — cast to int32, or split wide ids into two int32"
                        " components (key_width)"
                    )
                if host.size and (host.max() > np.iinfo(np.int32).max or host.min() < np.iinfo(np.int32).min):
                    raise ValueError(
                        "bad cohort key: values exceed int32 — truncating would silently alias"
                        " distinct cohorts mod 2^32; split wide ids into two int32 components"
                        " (key_width), e.g. (ids >> 32, ids & 0xFFFFFFFF)"
                    )
            cols.append(jnp.asarray(host).astype(jnp.int32))
        if len(cols) != self.key_width:
            raise ValueError(
                f"expected {self.key_width} cohort key component(s) (key_width), got {len(cols)}"
            )
        return jnp.stack(cols, axis=1)

    def update(self, keys: Any, *batch: Any) -> None:
        """Fold one batch into its cohort cells: ONE compiled call for ALL
        cells. ``keys`` is one int array ``[B]``, a tuple of them, or a
        ``[B, key_width]`` matrix — row ``i``'s cohort for ``batch[...][i]``."""
        self._state = self._step(self._state, self.key_matrix(keys), *batch)

    def run_scan(self, keys_seq: Any, batches: Any) -> None:
        """Scan a pre-staged chunk: ``keys_seq`` is a sequence (or stacked
        ``[N, B]``/``[N, B, K]`` array) of per-batch keys, ``batches`` a
        sequence of positional batch tuples or already-stacked arrays whose
        leading axis is the scan axis. Zero per-batch Python."""
        from torchmetrics_tpu.parallel.fused import FusedCollectionPlan

        # every per-batch key vector routes through key_matrix, so a scan
        # gets the SAME validation update() gives (key_width, float refusal,
        # int32 bounds) — a stacked array cannot bypass it
        if isinstance(keys_seq, (list, tuple)):
            per_batch = list(keys_seq)
        else:
            arr = keys_seq if hasattr(keys_seq, "ndim") else np.asarray(keys_seq)
            per_batch = [arr[i] for i in range(arr.shape[0])]
        kstack = jnp.stack([self.key_matrix(k) for k in per_batch])
        staged = FusedCollectionPlan.stage(batches)
        self._state = self._scan_step(self._state, (kstack,) + staged)

    # ---------------------------------------------------------------- queries
    def _table_host(self) -> Tuple[np.ndarray, np.ndarray, int]:
        table = self._state["table"]
        return np.asarray(table.keys), np.asarray(table.occupied), int(np.asarray(table.spills))

    @property
    def spills(self) -> int:
        """Rows dropped because the table was full (host sync)."""
        return self._table_host()[2]

    @property
    def occupancy(self) -> float:
        """Fraction of cells holding a cohort (host sync)."""
        _, occupied, _ = self._table_host()
        return float(occupied.sum()) / float(self.num_cells)

    def occupied_cells(self) -> Dict[Tuple[int, ...], int]:
        """``{cohort key tuple: cell index}`` for every resident cohort."""
        keys, occupied, _ = self._table_host()
        return {tuple(int(v) for v in keys[i]): int(i) for i in np.nonzero(occupied)[0]}

    def lookup(self, key: Any) -> Optional[int]:
        """Cell index of one cohort key (int or tuple), or ``None``."""
        if not isinstance(key, (tuple, list)):
            key = (key,)
        return self.occupied_cells().get(tuple(int(k) for k in key))

    def cell_state_tree(self, member: str, cell: int) -> Dict[str, Any]:
        """One cell's state for one member, in ``load_state_tree`` form
        (CatBuffers fold to list states, raising on that cell's overflow;
        ``"_update_count"`` rides the reserved key)."""
        group = next((cg for cg in self.groups if member in cg), None)
        if group is None:
            raise KeyError(f"unknown member {member!r}; members: {sorted(self.members)}")
        info = next(i for i in self._infos if i.key == group[0])  # the group leader's carry
        mstate = self._state["members"][info.key]
        tree: Dict[str, Any] = {}
        for name in info.metric._defaults:
            value = mstate[name]
            if name in info.list_keys:
                buf = CatBuffer(
                    data=value.data[cell], count=value.count[cell], overflowed=value.overflowed[cell]
                )
                rows = cat_buffer_values(buf)  # raises on per-cell overflow
                tree[name] = [rows] if int(buf.count) else []
            elif is_sketch_state(info.metric._defaults[name]):
                tree[name] = jax.tree_util.tree_map(lambda x: x[cell], value)
            else:
                tree[name] = value[cell]
        tree["_update_count"] = int(mstate["_update_count"][cell])
        return tree

    def export_cell(self, key: Any) -> Any:
        """A fresh copy of the target holding one cohort's state — compute,
        checkpoint or inspect it like any ordinary metric. ``key`` is the
        cohort key (int or tuple) or a cell index via ``lookup``."""
        cell = self.lookup(key)
        if cell is None:
            raise KeyError(f"cohort key {key!r} holds no cell (spilled or never seen)")
        return self._export_cell_index(cell)

    def _export_cell_index(self, cell: int) -> Any:
        clone = deepcopy(self._template)
        exported_members, _ = _resolve_members(clone, propagate_state=False)
        for cg in self.groups:
            leader_tree = self.cell_state_tree(cg[0], cell)
            for member_key in cg:
                exported_members[member_key].load_state_tree(dict(leader_tree))
                exported_members[member_key]._computed = None
        if self._collection is not None:
            clone._state_is_copy = False
        return clone

    def results(self) -> Dict[Tuple[int, ...], Any]:
        """``{cohort key tuple: compute() value}`` over every resident cell
        (host loop — evaluation-end cost, not per-batch). The table is read
        back ONCE; per-cohort exports index straight into the carry."""
        self.publish_gauges()
        return {
            key: self._export_cell_index(cell).compute()
            for key, cell in self.occupied_cells().items()
        }

    def compute_all(self) -> Dict[str, Any]:
        """Every member's ``compute`` lifted over the cell axis with
        ``vmap`` — one dispatch returns per-cell values ``[num_cells, ...]``
        per member key. Unoccupied cells compute on default state (typically
        NaN/0) — mask with :meth:`occupied_cells`. Refuses cat-state members
        (per-cell valid counts are dynamic)."""
        values: Dict[str, Any] = {}
        for info in self._infos:
            if info.list_keys:
                raise ValueError(
                    f"member {info.key!r} holds list ('cat') states {info.list_keys}: per-cell"
                    " valid row counts are dynamic — use results()/export_cell instead"
                )
            leader_state = self._state["members"][info.key]
            # compute-group members SHARE the leader's state but each has its
            # own compute — vmap every member's own compute over the carry
            for member_key in next(cg for cg in self.groups if cg[0] == info.key):
                member = self.members[member_key]

                def one_cell(mstate, _metric=member):
                    saved = _metric._copy_state_dict()
                    saved_count, saved_computed = _metric._update_count, _metric._computed
                    try:
                        _metric._install_state_tree(
                            {k: v for k, v in mstate.items() if k != "_update_count"}
                        )
                        _metric._computed = None
                        return type(_metric).compute(_metric)  # raw compute: no sync detour
                    finally:
                        _metric._install_state_tree(saved)
                        _metric._update_count = saved_count
                        _metric._computed = saved_computed

                values[member_key] = jax.vmap(one_cell)(leader_state)
        self.publish_gauges()
        return values

    # ----------------------------------------------------------- durability
    def save_checkpoint(self) -> Dict[str, Any]:
        """The whole carry (slice table included) as one plain numpy dict —
        store it through ``CheckpointStore`` like any metric checkpoint."""
        state = self._state
        members: Dict[str, Any] = {}
        for info in self._infos:
            mstate = state["members"][info.key]
            encoded: Dict[str, Any] = {}
            for name in info.metric._defaults:
                value = mstate[name]
                if name in info.list_keys:
                    encoded[name] = {
                        "__catbuffer__": True,
                        "data": np.asarray(value.data),
                        "count": np.asarray(value.count),
                        "overflowed": np.asarray(value.overflowed),
                    }
                elif is_sketch_state(info.metric._defaults[name]):
                    # field-keyed leaves, the checkpoint layer's sketch wire
                    # format — resilient to NamedTuple field reordering
                    encoded[name] = {
                        "__sketch__": type(info.metric._defaults[name]).__name__,
                        "leaves": {
                            field: np.asarray(leaf)
                            for field, leaf in zip(type(info.metric._defaults[name])._fields, value)
                        },
                    }
                else:
                    encoded[name] = np.asarray(value)
            encoded["_update_count"] = np.asarray(mstate["_update_count"])
            members[info.key] = encoded
        table = state["table"]
        payload = {
            "sliced_format": SLICED_FORMAT_VERSION,
            "fingerprint": self.stable_fingerprint(),
            "num_cells": self.num_cells,
            "key_width": self.key_width,
            "update_count": int(state["_update_count"]),
            "table": {
                "keys": np.asarray(table.keys),
                "occupied": np.asarray(table.occupied),
                "spills": np.asarray(table.spills),
            },
            "members": members,
        }
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            self.publish_gauges()
        return payload

    def load_checkpoint(self, payload: Dict[str, Any]) -> None:
        """Validate-ALL-then-apply restore of :meth:`save_checkpoint`: any
        mismatch (format, fingerprint, geometry, leaf shape/dtype) raises
        :class:`StateRestoreError` and the live carry is untouched."""
        version = payload.get("sliced_format")
        if not isinstance(version, int) or version < 1 or version > SLICED_FORMAT_VERSION:
            raise StateRestoreError(
                f"sliced checkpoint format {version!r} is not supported"
                f" (this build reads <= {SLICED_FORMAT_VERSION})"
            )
        want_fp = self.stable_fingerprint()
        if payload.get("fingerprint") != want_fp:
            raise StateRestoreError(
                f"sliced checkpoint fingerprint {payload.get('fingerprint')!r} does not match"
                f" this plan's {want_fp!r} — different members or table geometry"
            )
        if payload.get("num_cells") != self.num_cells or payload.get("key_width") != self.key_width:
            raise StateRestoreError(
                "sliced checkpoint table geometry"
                f" ({payload.get('num_cells')}x{payload.get('key_width')}) does not match the"
                f" plan ({self.num_cells}x{self.key_width})"
            )
        reference = self._initial_state()

        def check(name: str, got: np.ndarray, want: Array) -> Array:
            got = np.asarray(got)
            if tuple(got.shape) != tuple(want.shape) or jnp.dtype(got.dtype) != jnp.dtype(want.dtype):
                raise StateRestoreError(
                    f"sliced checkpoint leaf {name!r} has shape {got.shape}/{got.dtype},"
                    f" expected {tuple(want.shape)}/{want.dtype}"
                )
            # jnp.array, not asarray: on CPU asarray can ALIAS the numpy
            # buffer zero-copy, and the next donated step would overwrite
            # memory jax does not own while replica broadcasts still read it
            return jnp.array(got)

        fresh = {"members": {}, "table": None, "_update_count": None}
        try:
            table_p = payload["table"]
            fresh["table"] = SliceTable(
                keys=check("table.keys", table_p["keys"], reference["table"].keys),
                occupied=check("table.occupied", table_p["occupied"], reference["table"].occupied),
                spills=check("table.spills", table_p["spills"], reference["table"].spills),
            )
            fresh["_update_count"] = jnp.asarray(int(payload["update_count"]), jnp.int32)
            for info in self._infos:
                encoded = payload["members"][info.key]
                ref_m = reference["members"][info.key]
                decoded: Dict[str, Any] = {}
                for name in info.metric._defaults:
                    value = encoded[name]
                    prefix = f"{info.key}.{name}"
                    if name in info.list_keys:
                        ref_buf = ref_m[name]
                        decoded[name] = CatBuffer(
                            data=check(f"{prefix}.data", value["data"], ref_buf.data),
                            count=check(f"{prefix}.count", value["count"], ref_buf.count),
                            overflowed=check(
                                f"{prefix}.overflowed", value["overflowed"], ref_buf.overflowed
                            ),
                        )
                    elif is_sketch_state(info.metric._defaults[name]):
                        cls = sketch_state_class(value["__sketch__"])
                        fields = type(info.metric._defaults[name])._fields
                        leaves_in = value["leaves"]
                        if cls is not type(info.metric._defaults[name]) or not isinstance(
                            leaves_in, dict
                        ) or sorted(leaves_in) != sorted(fields):
                            raise StateRestoreError(
                                f"sliced checkpoint sketch state {prefix!r} does not match the"
                                " registered sketch class/fields"
                            )
                        decoded[name] = cls(
                            *[
                                check(f"{prefix}.{field}", leaves_in[field], getattr(ref_m[name], field))
                                for field in fields
                            ]
                        )
                    else:
                        decoded[name] = check(prefix, value, ref_m[name])
                decoded["_update_count"] = check(
                    f"{info.key}._update_count", encoded["_update_count"], ref_m["_update_count"]
                )
                fresh["members"][info.key] = decoded
        except (KeyError, TypeError, ValueError) as err:
            if isinstance(err, StateRestoreError):
                raise
            raise StateRestoreError(f"sliced checkpoint is malformed: {err}") from err
        self._state = fresh  # validate-all passed: apply atomically

    # -------------------------------------------------------------- obs plane
    def state_byte_sizes(self) -> Dict[str, int]:
        """Per-state byte footprint of the whole carry (array metadata — no
        device sync), keyed ``<member>.<state>`` plus the ``table``."""
        sizes: Dict[str, int] = {}
        for info in self._infos:
            mstate = self._state["members"][info.key]
            for name in info.metric._defaults:
                sizes[f"{info.key}.{name}"] = int(
                    sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(mstate[name]))
                )
        table = self._state["table"]
        sizes["table"] = int(table.keys.nbytes + table.occupied.nbytes)
        return sizes

    def publish_gauges(self) -> None:
        """Publish ``slice.table.occupancy``/``.spills``/``.cells`` gauges
        plus the per-table ``state_bytes`` attribution row. One flag check
        when obs is off — call freely at host boundaries (results,
        checkpoints, runner snapshots); never per batch (it syncs the
        table)."""
        if not (_obs_trace.ENABLED or _obs_live.ENABLED):
            return
        _, occupied, spills = self._table_host()
        occupancy = float(occupied.sum()) / self.num_cells
        # the bare names feed the fleet dashboard column (last-writer-wins
        # when a process drives several plans); the target-class-namespaced
        # copies disambiguate multi-table processes, like metric.<Class>.*
        for prefix in ("slice.table", f"slice.table.{self._target_cls}"):
            _obs_counters.set_gauge(f"{prefix}.occupancy", occupancy)
            _obs_counters.set_gauge(f"{prefix}.cells", self.num_cells)
            _obs_counters.set_gauge(f"{prefix}.spills", spills)
        _obs_attr.note_instances(type(self).__name__, list(self.members))
        leaves = {
            f"{info.key}.{name}": jax.tree_util.tree_leaves(self._state["members"][info.key][name])
            for info in self._infos
            for name in info.metric._defaults
        }
        leaves["table"] = [self._state["table"].keys, self._state["table"].occupied]
        _obs_attr.note_state_bytes(
            self, self.state_byte_sizes(), updates=self.updates_applied, leaves=leaves
        )

    def live_probe(self) -> Dict[str, float]:
        """Probe payload for the PR-7 live publisher (register with
        ``obs.live.register_probe``): table occupancy/spills at the publish
        cadence without a per-batch host sync."""
        _, occupied, spills = self._table_host()
        occupancy = float(occupied.sum()) / self.num_cells
        return {
            "slice.table.occupancy": occupancy,
            "slice.table.spills": float(spills),
            f"slice.table.{self._target_cls}.occupancy": occupancy,
            f"slice.table.{self._target_cls}.spills": float(spills),
        }
