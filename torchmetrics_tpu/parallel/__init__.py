# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""TPU-native distribution: sharded metric updates over a ``jax.sharding.Mesh``.

This subsystem replaces the reference's process-group model (NCCL/Gloo
``gather_all_tensors``, reference ``src/torchmetrics/utilities/distributed.py:97-147``
+ ``Metric._sync_dist``, ``metric.py:435-474``) with JAX's in-step sharding:

- :func:`sharded_update` runs a metric's ``update`` **inside** ``shard_map``
  over a device mesh: each device folds its local shard of the batch into a
  per-device partial state, then the states are merged with XLA collectives
  (``psum``/``pmax``/``pmin``/``all_gather``) over ICI — keyed by each state's
  declared ``dist_reduce_fx``, exactly like the reference's reduction map but
  without any host round-trip.
- :func:`metric_merge` / :func:`tree_merge` are the pure pairwise-merge
  functions (the generalization of the reference ``_reduce_states``,
  ``metric.py:401-433``) — usable directly inside user ``pjit`` eval steps.
- :class:`ShardedMetric` wraps any :class:`~torchmetrics_tpu.Metric` so its
  ``update`` transparently executes sharded over a mesh axis.
- :mod:`~torchmetrics_tpu.parallel.cat_buffer` gives list ("cat") states a
  fixed-capacity, jit/scan-safe representation (:class:`CatBuffer`) so exact
  curves, rank statistics, and retrieval run inside compiled streaming loops
  and under ``shard_map`` (round 3; the reference's list states are host-only).

Multi-host (DCN) sync of replicated states stays in
``torchmetrics_tpu.utilities.distributed`` — the two regimes compose.
"""
from torchmetrics_tpu.parallel.cat_buffer import (
    CatBuffer,
    cat_buffer_all_gather,
    cat_buffer_append,
    cat_buffer_init,
    cat_buffer_merge,
    cat_buffer_values,
)
from torchmetrics_tpu.parallel.feed import DeviceFeed
from torchmetrics_tpu.parallel.fused import (
    FusedCollectionPlan,
    fusion_ineligibility,
    fusion_report,
)
from torchmetrics_tpu.parallel.sliced import (
    SlicedPlan,
    SliceTable,
    slice_key_reason,
    slice_table_size_reason,
    sliced_ineligibility,
)
from torchmetrics_tpu.parallel.windowing import WindowRing
from torchmetrics_tpu.parallel.sharded import (
    ShardedMetric,
    deep_reductions,
    deep_state_tree,
    fold_jit_state,
    make_jit_update,
    make_sharded_update,
    metric_merge,
    mesh_reduce_tree,
    sharded_update,
    tree_merge,
)

__all__ = [
    "CatBuffer",
    "DeviceFeed",
    "FusedCollectionPlan",
    "ShardedMetric",
    "SliceTable",
    "SlicedPlan",
    "WindowRing",
    "cat_buffer_all_gather",
    "cat_buffer_append",
    "cat_buffer_init",
    "cat_buffer_merge",
    "cat_buffer_values",
    "deep_reductions",
    "deep_state_tree",
    "fold_jit_state",
    "fusion_ineligibility",
    "fusion_report",
    "make_jit_update",
    "make_sharded_update",
    "metric_merge",
    "mesh_reduce_tree",
    "sharded_update",
    "slice_key_reason",
    "slice_table_size_reason",
    "sliced_ineligibility",
    "tree_merge",
]
