# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Windowed aggregation: tumbling/sliding windows as a ring of mergeable
state snapshots.

A serving eval plane answers "AUROC over the last 15 minutes", not "AUROC
since boot". The reference's answer — ``Running`` — re-instantiates one state
copy per update event and caps the window at a handful of updates; its
fixed-shape successor is a **ring of closed windows**:

- the wrapped metric (or ``MetricCollection``) accumulates the OPEN window
  exactly as it always does — zero change to the hot path, fused/jitted
  drives included;
- on a rotation trigger (every N batches and/or every T seconds, driven by
  :class:`~torchmetrics_tpu.robustness.runner.StreamingEvaluator` or called
  directly) the open window CLOSES: its state trees snapshot into the ring
  and the metric resets. The ring holds the last ``slots`` closed windows;
  older windows expire by falling off the ring;
- a query is a **fold**: the newest ``k`` ring entries pairwise-merge under
  each state's declared ``dist_reduce_fx`` (the ``_REDUCTION_MAP`` contract
  — elementwise sums/maxes, cat list concatenation, sketch ``merge`` — the
  same merge sync and sharding already trust), optionally including the open
  window, and the merged state computes on a scratch copy. A tumbling
  window is ``query(last=1)``; a sliding window of ``W = k × rotation
  period`` is ``query(last=k)`` — one state plane, both shapes.

**Parity contract** (``tests/unittests/bases/test_windowing.py``): a query
over ``k`` windows equals recomputing the metric from scratch over exactly
those windows' batches — bitwise for exact-merge state kinds (integer
elementwise, cat, add-style sketches), within merge tolerance otherwise —
and a tumbling ring with ``every_n=1`` matches ``Running(metric, window=N)``
on the overlap (the wrapper this plane supersedes at serving scale).

**Durability**: :meth:`payload`/:meth:`restore` round-trip the ring as plain
numpy dicts; ``StreamingEvaluator`` embeds them in its snapshots, so
kill-and-resume restores the closed windows alongside the open state and the
exactly-once cursor.

**Observability**: every rotation publishes ``window.<Class>.*`` gauges
(``slots_live``, ``closed_batches``, rotation counter) and :meth:`probe`
feeds the PR-7 live publisher the real-time ``window.<Class>.age_s`` — all
behind the usual one-flag check, zero overhead when off.
"""
from __future__ import annotations

import time
from copy import deepcopy
from typing import Any, Dict, List, Optional

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.utilities.exceptions import StateRestoreError

__all__ = ["WindowRing"]

#: payload layout version of :meth:`WindowRing.payload`
WINDOW_PAYLOAD_VERSION = 1


class WindowRing:
    """Ring of closed, mergeable windows over a metric or collection.

    ::

        auroc = MulticlassAUROC(num_classes=10, thresholds=64, validate_args=False)
        ring = WindowRing(auroc, slots=15, every_s=60.0)     # 15 one-minute windows
        StreamingEvaluator(auroc, store=store, window_ring=ring).run(stream)
        ring.query(last=15)          # AUROC over the last 15 minutes
        ring.query(last=1)           # the newest closed minute (tumbling)

    Args:
        target: the ``Metric`` or ``MetricCollection`` accumulating the open
            window — the SAME object the evaluator drives.
        slots: closed windows the ring retains; older windows expire.
        every_n: close the open window after this many observed batches.
        every_s: close the open window when it has been open this long
            (checked per observed batch; OR-combined with ``every_n``).
            Both ``None`` = rotation only via explicit :meth:`rotate` calls.
    """

    def __init__(
        self,
        target: Any,
        *,
        slots: int,
        every_n: Optional[int] = None,
        every_s: Optional[float] = None,
    ) -> None:
        from torchmetrics_tpu.collections import MetricCollection
        from torchmetrics_tpu.metric import Metric

        if not isinstance(target, (Metric, MetricCollection)):
            raise ValueError(
                f"WindowRing wraps a Metric or MetricCollection, got {type(target).__name__}"
            )
        if not (isinstance(slots, int) and not isinstance(slots, bool) and slots >= 1):
            raise ValueError(f"slots must be a positive int, got {slots!r}")
        if every_n is not None and every_n < 1:
            raise ValueError(f"every_n must be >= 1, got {every_n}")
        if every_s is not None and every_s <= 0:
            raise ValueError(f"every_s must be > 0, got {every_s}")
        self.target = target
        self.slots = slots
        self.every_n = every_n
        self.every_s = every_s
        self._is_collection = isinstance(target, MetricCollection)
        self._template = deepcopy(target)
        #: closed windows, oldest → newest; each entry is
        #: {"cursor", "batches", "members": {key: state tree incl _update_count}}
        self._ring: List[Dict[str, Any]] = []
        self._open_batches = 0
        self._opened_t = time.monotonic()
        self._rotations = 0
        # payload() encoding of the closed ring, invalidated on rotation —
        # closed windows are immutable between rotations, so a per-batch
        # stall-capture payload must not re-encode the whole ring every batch
        self._encoded_ring: Optional[List[Dict[str, Any]]] = None

    # ------------------------------------------------------------------ state
    def _members(self, target: Optional[Any] = None) -> Dict[str, Any]:
        target = self.target if target is None else target
        if self._is_collection:
            return dict(target.items(keep_base=True, copy_state=True))
        return {type(target).__name__: target}

    @staticmethod
    def _snapshot_tree(metric: Any) -> Dict[str, Any]:
        tree = metric._copy_state_dict()
        tree["_update_count"] = metric._update_count
        return tree

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def open_batches(self) -> int:
        """Batches observed in the (not yet closed) open window."""
        return self._open_batches

    @property
    def open_age_s(self) -> float:
        """Seconds the current open window has been accumulating."""
        return time.monotonic() - self._opened_t

    # --------------------------------------------------------------- rotation
    def due(self) -> bool:
        """Whether a trigger asks the open window to close now."""
        if self.every_n is not None and self._open_batches >= self.every_n:
            return True
        if self.every_s is not None and self.open_age_s >= self.every_s:
            return True
        return False

    def observe(self, cursor: int) -> bool:
        """Per-batch driver hook (``StreamingEvaluator`` calls it after each
        applied batch): count the batch into the open window and rotate when
        a trigger fires. Returns whether a rotation happened."""
        self._open_batches += 1
        if self.due():
            self.rotate(cursor)
            return True
        return False

    def rotate(self, cursor: int = -1) -> None:
        """Close the open window: snapshot every member's state tree into the
        ring (the oldest entry expires past ``slots``) and reset the target.
        A window that saw no batches still closes — an empty window is real
        serving information ("no traffic this minute")."""
        entry = {
            "cursor": int(cursor),
            "batches": self._open_batches,
            "members": {key: self._snapshot_tree(m) for key, m in self._members().items()},
        }
        self._ring.append(entry)
        if len(self._ring) > self.slots:
            del self._ring[0]
        self.target.reset()
        self._open_batches = 0
        self._opened_t = time.monotonic()
        self._rotations += 1
        self._encoded_ring = None  # the closed set changed: re-encode lazily
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            cls = type(self.target).__name__
            _obs_counters.inc(f"window.{cls}.rotations")
            _obs_counters.set_gauge(f"window.{cls}.slots_live", len(self._ring))
            _obs_counters.set_gauge(f"window.{cls}.closed_batches", entry["batches"])
            _obs_counters.set_gauge(f"window.{cls}.age_s", 0.0)

    # ------------------------------------------------------------------ query
    def _merge_trees(self, metric: Any, a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        """Pairwise window merge under the declared reductions — the window
        close IS a ``metric_merge`` fold, so every state kind the sync/shard
        planes can reduce is windowable."""
        from torchmetrics_tpu.parallel.sharded import tree_merge

        count_a, count_b = int(a["_update_count"]), int(b["_update_count"])
        # TRUE update counts as merge weights: an EMPTY closed window (count
        # 0) must not dilute "mean" states with default-valued state —
        # (0*default + n*v)/n == v keeps the recompute parity exact. Only the
        # all-empty fold (0+0 would divide by zero) falls back to equal
        # weights, where every operand is the default anyway.
        weight_a, weight_b = (count_a, count_b) if count_a + count_b else (1, 1)
        merged = tree_merge(
            metric._reductions,
            {k: a[k] for k in metric._defaults},
            {k: b[k] for k in metric._defaults},
            weight_a=weight_a,
            weight_b=weight_b,
        )
        merged["_update_count"] = count_a + count_b
        return merged

    def query(self, last: Optional[int] = None, include_open: bool = False) -> Any:
        """Compute over the newest ``last`` closed windows (default: every
        live ring entry), oldest-first fold; ``include_open=True`` also
        merges the open window's live state (a "current sliding window
        including right now" read). The target itself is untouched — the
        fold installs into a scratch copy."""
        entries = self._ring if last is None else self._ring[max(0, len(self._ring) - last):]
        member_trees: List[Dict[str, Dict[str, Any]]] = [e["members"] for e in entries]
        if include_open:
            member_trees = member_trees + [
                {key: self._snapshot_tree(m) for key, m in self._members().items()}
            ]
        if not member_trees:
            raise ValueError("no closed windows to query (and include_open=False)")
        scratch = deepcopy(self._template)
        scratch_members = self._members(scratch)
        for key, member in scratch_members.items():
            folded = member_trees[0][key]
            for tree in member_trees[1:]:
                folded = self._merge_trees(member, folded, tree[key])
            member.load_state_tree(dict(folded))
            member._computed = None
        if self._is_collection:
            scratch._state_is_copy = False
        return scratch.compute()

    # -------------------------------------------------------------- live plane
    def probe(self) -> Dict[str, float]:
        """PR-7 live-publisher probe: the open window's age and the ring
        occupancy, sampled at the publish cadence (``StreamingEvaluator``
        registers it when a ring is attached and publishing is on)."""
        cls = type(self.target).__name__
        return {
            f"window.{cls}.age_s": self.open_age_s,
            f"window.{cls}.slots_live": float(len(self._ring)),
            f"window.{cls}.open_batches": float(self._open_batches),
        }

    # ------------------------------------------------------------- durability
    @staticmethod
    def _encode_value(value: Any) -> Any:
        """One state leaf as plain host data — the SAME wire format the PR-2
        checkpoint layer writes (list -> list of ndarrays, sketch -> the
        field-keyed ``{"__sketch__", "leaves"}`` dict ``load_state_tree``
        validates and decodes), so the sketch serialization exists ONCE."""
        from torchmetrics_tpu.robustness.checkpoint import _serialize_state

        return _serialize_state(value)

    def payload(self) -> Dict[str, Any]:
        """The ring (closed windows + open-window counters) as one plain
        numpy dict — ``StreamingEvaluator`` embeds it in its snapshots.
        Closed windows are immutable between rotations, so their encoding is
        cached: the per-batch stall-capture path pays the device→host
        round-trips once per ROTATION, not once per batch."""
        if self._encoded_ring is None:
            self._encoded_ring = [
                {
                    "cursor": e["cursor"],
                    "batches": e["batches"],
                    "members": {
                        key: {name: self._encode_value(v) for name, v in tree.items() if name != "_update_count"}
                        | {"_update_count": int(tree["_update_count"])}
                        for key, tree in e["members"].items()
                    },
                }
                for e in self._ring
            ]
        return {
            "window_payload_version": WINDOW_PAYLOAD_VERSION,
            "slots": self.slots,
            "open_batches": self._open_batches,
            "rotations": self._rotations,
            "ring": list(self._encoded_ring),
        }

    def restore(self, payload: Dict[str, Any]) -> None:
        """Validate-ALL-then-apply restore of :meth:`payload`: every entry's
        every member tree is decoded and validated against the member's state
        registry (on a scratch copy) before the live ring is touched.
        Callers coordinating with OTHER restores (the runner restores the
        metric checkpoint too) can validate first and apply later via
        :meth:`validated_parts`/:meth:`apply_parts`."""
        self.apply_parts(self.validated_parts(payload))

    def validated_parts(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Decode + validate a :meth:`payload` WITHOUT touching the live
        ring; raises :class:`StateRestoreError` on any mismatch. The result
        feeds :meth:`apply_parts`."""
        version = payload.get("window_payload_version")
        if not isinstance(version, int) or version < 1 or version > WINDOW_PAYLOAD_VERSION:
            raise StateRestoreError(
                f"window ring payload version {version!r} is not supported"
                f" (this build reads <= {WINDOW_PAYLOAD_VERSION})"
            )
        if payload.get("slots") != self.slots:
            raise StateRestoreError(
                f"window ring payload was written for slots={payload.get('slots')!r},"
                f" this ring has slots={self.slots}"
            )
        entries = payload.get("ring", [])
        if len(entries) > self.slots:
            raise StateRestoreError(
                f"window ring payload holds {len(entries)} closed windows but the ring"
                f" retains at most slots={self.slots} — corrupt/foreign payload"
            )
        scratch_members = self._members(deepcopy(self._template))
        want_keys = set(scratch_members)
        fresh_ring: List[Dict[str, Any]] = []
        try:
            for i, entry in enumerate(entries):
                members = entry["members"]
                if set(members) != want_keys:
                    raise StateRestoreError(
                        f"window ring entry {i} members {sorted(members)} do not match the"
                        f" target's {sorted(want_keys)}"
                    )
                decoded_members: Dict[str, Dict[str, Any]] = {}
                for key, tree in members.items():
                    # registry validation AND decode in one step: the scratch
                    # member's load_state_tree validates shape/dtype/kind and
                    # converts the checkpoint-format sketch dicts back to
                    # their NamedTuples — the decoded tree is read back from
                    # the scratch; a failure leaves the live ring untouched
                    scratch_members[key].load_state_tree(dict(tree))
                    decoded_members[key] = self._snapshot_tree(scratch_members[key])
                fresh_ring.append(
                    {"cursor": int(entry["cursor"]), "batches": int(entry["batches"]), "members": decoded_members}
                )
        except (KeyError, TypeError, ValueError) as err:
            if isinstance(err, StateRestoreError):
                raise
            raise StateRestoreError(f"window ring payload is malformed: {err}") from err
        return {
            "ring": fresh_ring,
            "open_batches": int(payload.get("open_batches", 0)),
            "rotations": int(payload.get("rotations", len(fresh_ring))),
        }

    def apply_parts(self, parts: Dict[str, Any]) -> None:
        """Install :meth:`validated_parts` output into the live ring."""
        self._ring = parts["ring"]
        self._open_batches = parts["open_batches"]
        self._rotations = parts["rotations"]
        self._opened_t = time.monotonic()
        self._encoded_ring = None
