# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Fixed-capacity append buffers: list ("cat") metric states under jit/shard_map.

The reference accumulates ``cat`` states as Python lists of tensors
(reference ``metric.py:260-271``) and concatenates per-rank lists at sync
time. Lists of per-batch tensors are inherently dynamic-shape — they cannot
live inside a compiled XLA program, which is why round-2's sharded regime
rejected them. The TPU-native answer (SURVEY.md §7 "static shapes first") is
a capacity-bounded buffer::

    CatBuffer(data=(capacity, *elem), count=int32, overflowed=bool)

- ``append`` writes batch rows at offset ``count`` with an out-of-bounds-
  dropping scatter — static shapes, jit/scan/vmap-safe.
- ``merge`` splices another buffer's valid rows in (pairwise reduction).
- ``all_gather_compact`` is the cross-device merge: inside ``shard_map`` it
  gathers every device's buffer and compacts the valid rows into one
  ``(n_devices * capacity,)`` buffer ordered by device index — the collective
  analogue of the reference's gather-then-``dim_zero_cat``.
- Overflow never corrupts data: rows past capacity are dropped and the
  ``overflowed`` flag latches; ``values()`` raises on the host so callers can
  re-run with a larger capacity or fall back to host accumulation.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


class CatBuffer(NamedTuple):
    """A fixed-capacity append buffer (a pytree of three arrays)."""

    data: Array  # (capacity, *elem)
    count: Array  # int32 scalar: valid rows
    overflowed: Array  # bool scalar: an append ran past capacity


def cat_buffer_init(capacity: int, elem_shape: Sequence[int] = (), dtype: Any = jnp.float32) -> CatBuffer:
    """An empty buffer holding up to ``capacity`` rows of shape ``elem_shape``."""
    return CatBuffer(
        data=jnp.zeros((capacity, *elem_shape), dtype),
        count=jnp.asarray(0, jnp.int32),
        overflowed=jnp.asarray(False),
    )


def cat_buffer_append(buf: CatBuffer, rows: Array) -> CatBuffer:
    """Append ``rows`` (shape ``(B, *elem)``) at the current offset.

    Rows that would land past capacity are dropped (scatter ``mode="drop"``)
    and ``overflowed`` latches — no clamped-index overwrite of earlier rows.
    """
    rows = jnp.asarray(rows)
    if rows.ndim == buf.data.ndim - 1:  # single row convenience
        rows = rows[None]
    n = rows.shape[0]
    idx = buf.count + jnp.arange(n)
    data = buf.data.at[idx].set(rows.astype(buf.data.dtype), mode="drop")
    new_total = buf.count + n
    return CatBuffer(
        data=data,
        count=jnp.minimum(new_total, buf.data.shape[0]).astype(jnp.int32),
        overflowed=buf.overflowed | (new_total > buf.data.shape[0]),
    )


def cat_buffer_merge(a: CatBuffer, b: CatBuffer) -> CatBuffer:
    """Splice ``b``'s valid rows after ``a``'s (pairwise cat reduction)."""
    cap_a = a.data.shape[0]
    rb = jnp.arange(b.data.shape[0])
    # invalid source rows route to index cap_a: out of bounds, dropped
    idx = jnp.where(rb < b.count, a.count + rb, cap_a)
    data = a.data.at[idx].set(b.data.astype(a.data.dtype), mode="drop")
    new_total = a.count + b.count
    return CatBuffer(
        data=data,
        count=jnp.minimum(new_total, cap_a).astype(jnp.int32),
        overflowed=a.overflowed | b.overflowed | (new_total > cap_a),
    )


def cat_buffer_all_gather(buf: CatBuffer, axis_name: str) -> CatBuffer:
    """Cross-device merge: compact every device's valid rows into one buffer.

    Must run inside ``shard_map``/``pmap`` with ``axis_name`` bound. Returns a
    replicated ``CatBuffer`` of capacity ``n_devices * capacity`` whose rows
    are ordered by device index (the reference's rank-ordered gather,
    ``metric.py:459-474``) — deterministic, so downstream sort-based metrics
    (Spearman, exact curves) see identical inputs on every device.
    """
    cap = buf.data.shape[0]
    data = jax.lax.all_gather(buf.data, axis_name)  # (n_dev, cap, *elem)
    counts = jax.lax.all_gather(buf.count, axis_name)  # (n_dev,)
    over = jax.lax.all_gather(buf.overflowed, axis_name).any()
    n_dev = data.shape[0]
    offsets = jnp.cumsum(counts) - counts  # exclusive prefix sum
    rows = jnp.arange(cap)
    # per (device, row) destination; invalid rows route out of bounds
    dest = jnp.where(rows[None, :] < counts[:, None], offsets[:, None] + rows[None, :], n_dev * cap)
    flat_dest = dest.reshape(-1)
    flat_data = data.reshape((n_dev * cap, *data.shape[2:]))
    out = jnp.zeros_like(flat_data).at[flat_dest].set(flat_data, mode="drop")
    return CatBuffer(data=out, count=counts.sum().astype(jnp.int32), overflowed=over)


def cat_buffer_values(buf: CatBuffer) -> Array:
    """The valid rows, host-side. Raises if the buffer ever overflowed."""
    if bool(buf.overflowed):
        raise RuntimeError(
            f"CatBuffer overflowed its capacity of {buf.data.shape[0]} rows; rows were dropped."
            " Re-run with a larger capacity, or fall back to host (list-state) accumulation."
        )
    return buf.data[: int(buf.count)]


def infer_cat_layout(metric: Any, example_batch: Tuple[Any, ...]) -> dict:
    """Per-list-state ``(elem_shape, dtype)`` via abstract eval.

    Runs the metric's ``update`` under ``jax.eval_shape`` (no FLOPs, no
    device) on the example batch to learn what each list state appends.
    """
    def probe(*batch):
        saved = metric._copy_state_dict()
        saved_count, saved_computed = metric._update_count, metric._computed
        try:
            metric.reset()
            metric.update(*batch)
            tree = metric.state_tree()
            return {k: [jnp.atleast_1d(x) for x in v] for k, v in tree.items() if isinstance(v, list)}
        finally:
            metric._install_state_tree(saved)  # self-snapshot: trusted
            metric._update_count = saved_count
            metric._computed = saved_computed

    shapes = jax.eval_shape(probe, *example_batch)
    layout = {}
    for key, appended in shapes.items():
        if not appended:
            raise ValueError(f"list state {key!r} received no append for the example batch")
        elem = appended[0].shape[1:]
        if any(a.shape[1:] != elem or a.dtype != appended[0].dtype for a in appended):
            raise ValueError(f"list state {key!r} appends inconsistent shapes/dtypes per update")
        layout[key] = (elem, appended[0].dtype)
    return layout
