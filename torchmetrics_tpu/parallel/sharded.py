# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Sharded metric execution over a device mesh.

Design (SURVEY.md §7): a metric is four pure functions —
``init() -> State``, ``update(State, batch) -> State``,
``compute(State) -> value``, ``merge(State, State) -> State`` — and the OO
:class:`~torchmetrics_tpu.Metric` is a shell over them. This module exploits
that: the OO metric's traced ``update`` runs per-device under ``shard_map``
on the local batch shard, and per-device partial states are merged with the
XLA collective matching each state's declared reduction:

==============  =======================================
dist_reduce_fx  collective over the mesh axis
==============  =======================================
``"sum"``       ``jax.lax.psum``
``"mean"``      ``jax.lax.pmean``
``"max"``       ``jax.lax.pmax``
``"min"``       ``jax.lax.pmin``
``"cat"``       ``jax.lax.all_gather`` + flatten
``None``        ``jax.lax.all_gather`` (stacked raw)
custom fn       ``all_gather`` + fn on the stacked axis
==============  =======================================

This is the TPU-native analogue of the reference's gather-then-reduce protocol
(``metric.py:459-474``): same semantics, but fused into the compiled step and
riding ICI instead of NCCL.
"""
from __future__ import annotations

import hashlib
import inspect
import weakref
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import device as _obs_device
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.obs import xla as _obs_xla
from torchmetrics_tpu.parallel.cat_buffer import (
    CatBuffer,
    cat_buffer_append,
    cat_buffer_init,
    cat_buffer_merge,
    cat_buffer_values,
    infer_cat_layout,
)
from torchmetrics_tpu.sketch.registry import is_sketch_state, merge_states

try:  # jax >= 0.7 top-level export; the experimental path is deprecated
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_rep)

except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

Array = jax.Array

# compiled sharded-update steps keyed by (id(metric), id(mesh), axis,
# walk-fingerprint); weakrefs validate against id reuse after gc, the
# fingerprint invalidates on child-metric swaps / flag flips
_SHARDED_FN_CACHE: Dict[Tuple, Tuple] = {}


def plan_cache_lookup(kind: str, target: Any, mesh: Optional[Mesh], axis: str, key: str) -> Tuple[Tuple, Optional[Any]]:
    """Shared compiled-step cache lookup for the plan planes (fused/sliced):
    returns ``(cache_key, steps-or-None)`` and bumps ``<kind>.cache.hit/miss``.
    Keys lead with the ``kind`` marker so each plane's key space stays
    disjoint from ``sharded_update``'s ``(id, id, axis, ...)`` keys."""
    cache_key = (kind, id(target), id(mesh) if mesh is not None else None, axis, key)
    entry = _SHARDED_FN_CACHE.get(cache_key)
    if entry is not None and entry[0]() is target and (mesh is None or entry[1]() is mesh):
        if _obs_trace.ENABLED:
            _obs_counters.inc(f"{kind}.cache.hit")
        return cache_key, entry[2]
    if _obs_trace.ENABLED:
        _obs_counters.inc(f"{kind}.cache.miss")
    return cache_key, None


def plan_cache_store(kind: str, cache_key: Tuple, target: Any, mesh: Optional[Mesh], steps: Any) -> None:
    """Store a plan's compiled steps, evicting superseded fingerprints of the
    same (target, mesh, axis) and entries whose target/mesh was garbage-
    collected — fresh-plan-per-target is advertised usage, and dead entries
    would otherwise pin metrics + compiled steps via the closure forever."""

    def _dead(k: Tuple) -> bool:
        e = _SHARDED_FN_CACHE[k]
        return e[0]() is None or (e[1] is not None and e[1]() is None)

    stale = [
        k for k in _SHARDED_FN_CACHE
        if isinstance(k, tuple) and k[:1] == (kind,) and k != cache_key
        and (k[1:4] == cache_key[1:4] or _dead(k))
    ]
    for old in stale:
        del _SHARDED_FN_CACHE[old]
    if stale and _obs_trace.ENABLED:
        _obs_counters.inc(f"{kind}.cache.evict", len(stale))
    _SHARDED_FN_CACHE[cache_key] = (
        weakref.ref(target),
        weakref.ref(mesh) if mesh is not None else None,
        steps,
    )


# ------------------------------------------------------------------ pure merge


def metric_merge(
    reduction: Optional[str | Callable], a: Any, b: Any, weight_a: Any = 1.0, weight_b: Any = 1.0
) -> Any:
    """Pairwise-merge two state values under a declared reduction.

    The pure generalization of reference ``Metric._reduce_states``
    (``metric.py:401-433``); jit-safe for array states. ``weight_a``/``weight_b``
    are the update counts behind each part, used to merge ``"mean"`` states as
    a correctly weighted average (the reference's ``metric.py:317`` running-avg
    semantics) — with the defaults, a pair of equal-weight parts averages to
    ``(a + b) / 2``.
    """
    if reduction == "sum":
        return a + b
    if reduction == "mean":
        return (weight_a * a + weight_b * b) / (weight_a + weight_b)
    if reduction == "max":
        return jnp.maximum(a, b)
    if reduction == "min":
        return jnp.minimum(a, b)
    if reduction == "merge":
        # sketch states carry their own exact pairwise merge — weights are
        # irrelevant (the sketch tracks its own counts)
        return merge_states(a, b)
    if reduction == "cat":
        if isinstance(a, CatBuffer):
            return cat_buffer_merge(a, b)
        if isinstance(a, list):
            return list(a) + list(b)
        return jnp.concatenate([jnp.atleast_1d(a), jnp.atleast_1d(b)])
    if reduction is None:
        if isinstance(a, list):
            # list states under a None reduction extend across parts (the
            # reference's rank-extend, metric.py:356)
            return list(a) + list(b)
        return jnp.stack([a, b])
    if callable(reduction):
        return reduction(jnp.stack([a, b]))
    raise ValueError(f"Unknown reduction {reduction!r}")


def tree_merge(
    reductions: Dict[str, Any],
    state_a: Dict[str, Any],
    state_b: Dict[str, Any],
    weight_a: Any = 1.0,
    weight_b: Any = 1.0,
) -> Dict[str, Any]:
    """Merge two state pytrees keyed by per-state reductions.

    ``weight_a``/``weight_b`` are the update counts behind each pytree; they
    only affect ``"mean"`` states (weighted running average).
    """
    return {k: metric_merge(reductions[k], state_a[k], state_b[k], weight_a, weight_b) for k in state_a}


def mesh_reduce_tree(reductions: Dict[str, Any], state: Dict[str, Any], axis_name: str) -> Dict[str, Any]:
    """Reduce a per-device partial-state pytree across a mesh axis.

    Must be called inside ``shard_map``/``pmap`` with ``axis_name`` bound.

    List ("cat") states require UNIFORM appends: under SPMD every device
    traces the same program, so each device's list must hold the same number
    of same-shaped tensors — that is what makes the per-append ``all_gather``
    below well-defined. Calling this from a non-SPMD context where devices
    appended different counts/shapes would silently miscombine; pad to a
    common shape (see ``CatBuffer``) before reducing.
    """
    def gather_flat(v: Array) -> Array:
        return jax.lax.all_gather(v, axis_name).reshape((-1,) + tuple(v.shape[1:]))

    out: Dict[str, Any] = {}
    for key, value in state.items():
        reduction = reductions[key]
        if isinstance(value, list) and reduction in ("cat", None):
            # rank-extend semantics (reference metric.py:356): each appended
            # tensor gathers across devices and flattens, so the host list
            # receives one device-ordered tensor per append
            out[key] = [gather_flat(v) for v in value]
        elif reduction == "sum":
            out[key] = jax.lax.psum(value, axis_name)
        elif reduction == "mean":
            out[key] = jax.lax.pmean(value, axis_name)
        elif reduction == "max":
            out[key] = jax.lax.pmax(value, axis_name)
        elif reduction == "min":
            out[key] = jax.lax.pmin(value, axis_name)
        elif reduction == "merge":
            # per-device partial sketches: all_gather every leaf, then fold
            # the device axis by pairwise merge (device count is static at
            # trace time, so the fold unrolls into the compiled program)
            gathered = jax.tree_util.tree_map(lambda v: jax.lax.all_gather(v, axis_name), value)
            n_dev = int(jax.tree_util.tree_leaves(gathered)[0].shape[0])
            merged = jax.tree_util.tree_map(lambda v: v[0], gathered)
            for d in range(1, n_dev):
                merged = merge_states(merged, jax.tree_util.tree_map(lambda v, _d=d: v[_d], gathered))
            out[key] = merged
        elif reduction == "cat":
            out[key] = gather_flat(value)
        elif reduction is None:
            out[key] = jax.lax.all_gather(value, axis_name)
        elif callable(reduction):
            out[key] = reduction(jax.lax.all_gather(value, axis_name))
        else:
            raise ValueError(f"Unknown reduction {reduction!r} for state {key!r}")
    return out


# --------------------------------------------------------------- jitted update


def make_jit_update(
    metric: "Any",
    cat_capacity: Optional[int] = None,
    example_batch: Optional[Tuple[Any, ...]] = None,
    donate: bool = False,
) -> Tuple[Callable[..., Dict[str, Any]], Dict[str, Any]]:
    """Build ``(step, init_state)`` where ``step(state, *batch) -> state`` is jitted.

    The entire update — validation-free kernel plus merge into the running
    state — compiles to one XLA program, so a metric-evaluation loop runs at
    device speed with no per-op dispatch.

    List ("cat") states — exact curves, Spearman/Kendall, retrieval — are
    dynamic-shape and cannot live in a compiled program directly; pass
    ``cat_capacity`` (max TOTAL rows to retain) plus an ``example_batch``
    (used under ``jax.eval_shape``, no compute, to learn each state's row
    shape) and they become fixed-capacity :class:`CatBuffer` states: append
    under jit/scan, overflow latched, never corrupting. Fold the final state
    back with :func:`fold_jit_state`, which converts buffers to the metric's
    list states (raising on overflow so callers can enlarge the capacity or
    fall back to host accumulation).

    The state pytree carries the update count under the reserved key
    ``"_update_count"`` so ``"mean"`` states merge as a correctly weighted
    running average (reference ``metric.py:317``) instead of decaying
    pairwise means.

    With device telemetry enabled at build time
    (``torchmetrics_tpu.obs.device``), the state additionally carries a
    fixed-shape ``"_telemetry"`` health accumulator (per-input NaN/Inf
    counts, min/max/absmax, optional histogram) updated INSIDE the compiled
    step; :func:`fold_jit_state` moves it to the metric, and the next
    ``compute()`` drains it into ``device.<Metric>.*`` gauges. Disabled
    (the default) the traced program is byte-identical to this docstring's
    plain contract — zero extra HLO ops.

    ``donate=True`` donates the state carry (``donate_argnums=0``): XLA may
    reuse the input state's buffers for the output, so a streaming loop
    updates in place instead of allocating a fresh state per batch — the
    regime the fused collection plane (``parallel/fused.py``) runs in. The
    caller's OLD state reference is consumed (reading it afterwards raises);
    that is a property of the ``donate`` flag ALONE — enabling/disabling
    device telemetry never changes the caller-visible buffer semantics
    (pinned by ``test_make_jit_update_donate_semantics_telemetry_invariant``).
    Default off: the lone-metric path keeps the append-only, caller-holds-
    the-state contract unchanged.
    """
    if _obs_trace.ENABLED:
        with _obs_trace.span("parallel.jit_build", metric=type(metric).__name__):
            return _make_jit_update(metric, cat_capacity, example_batch, donate)
    return _make_jit_update(metric, cat_capacity, example_batch, donate)


def _fingerprint_digest(*parts: Any) -> str:
    """Short stable digest of build-identity parts — the key xla compile
    records are filed under (ISSUE 6: cost capture keyed by cache fingerprint)."""
    return hashlib.sha1(repr(parts).encode()).hexdigest()[:12]


def _update_arity(metric: "Any") -> int:
    """Number of positional batch inputs ``metric.update`` declares — sizes
    the per-input telemetry arrays when no ``example_batch`` is given. Calls
    may legally pass fewer (optional args: extra slots stay zero) or more
    (``*args`` signatures: overflow inputs collapse into the last slot, so
    telemetry TOTALS stay exact even when attribution cannot)."""
    params = [
        p
        for name, p in inspect.signature(type(metric).update).parameters.items()
        if name != "self" and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return max(1, len(params))


def _make_jit_update(
    metric: "Any",
    cat_capacity: Optional[int] = None,
    example_batch: Optional[Tuple[Any, ...]] = None,
    donate: bool = False,
) -> Tuple[Callable[..., Dict[str, Any]], Dict[str, Any]]:
    base_step, init_state = _build_update_step(metric, cat_capacity, example_batch)
    # donation is the CALLER's choice, applied identically whether telemetry
    # is on or off — an observability flag must never change caller-visible
    # buffer semantics (with donate=True the telemetry carry is donated too:
    # it is part of the state the caller handed over)
    jit_kwargs = {"donate_argnums": 0} if donate else {}
    if donate:
        # the raw init state aliases the metric's _defaults arrays; a donated
        # first step consuming THOSE buffers would break every later reset().
        # Fresh copies make the handed-out state safely consumable.
        init_state = jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), init_state)
    telemetry_on, histogram = _obs_device.config_token()
    if telemetry_on:
        # the in-graph telemetry carry (obs/device.py): decided at BUILD time
        # so the disabled path's traced program is byte-identical to a
        # never-instrumented build (zero extra HLO ops, pinned by test)
        n_inputs = len(example_batch) if example_batch is not None else _update_arity(metric)
        init_state = dict(init_state)
        init_state["_telemetry"] = _obs_device.telemetry_init(n_inputs, histogram)

        def step(state: Dict[str, Any], *batch: Any) -> Dict[str, Any]:
            state = dict(state)
            telemetry = state.pop("_telemetry")
            out = base_step(state, *batch)
            out["_telemetry"] = _obs_device.telemetry_update(telemetry, batch)
            return out

        # NOT donated by default: an observability flag must never change
        # buffer semantics the caller sees (donation would delete state a
        # caller still holds). With ``donate=True`` the caller opted in and
        # the telemetry carry rides the same aliasing.
        jitted = jax.jit(step, **jit_kwargs)
    else:
        jitted = jax.jit(base_step, **jit_kwargs)
    key = _fingerprint_digest(
        "jit_update", type(metric).__name__, _walk_fingerprint(metric), telemetry_on, donate
    )
    return (
        _obs_xla.instrument_jit(
            jitted, key=key, metric=type(metric).__name__, kind="jit_update", span_prefix="parallel.jit_update"
        ),
        init_state,
    )


def _build_update_step(
    metric: "Any",
    cat_capacity: Optional[int] = None,
    example_batch: Optional[Tuple[Any, ...]] = None,
) -> Tuple[Callable[..., Dict[str, Any]], Dict[str, Any]]:
    """The raw (unjitted, never-instrumented) update step + init state —
    the program :func:`make_jit_update` jits; kept separate so the
    zero-HLO-when-disabled parity test has an uninstrumented reference."""
    walk = _walk_metrics(metric)
    for path, m in walk:
        reason = getattr(m, "_sharded_update_unsupported", None)
        if reason:
            where = f" (at {path!r})" if path else ""
            raise ValueError(f"{type(m).__name__} does not support a traced update step{where}: {reason}")
    if len(walk) > 1:
        raise ValueError(
            f"{type(metric).__name__} wraps child metrics; make_jit_update's state pytree covers only the"
            " root registry, so the children would mistrace. Use sharded_update/make_sharded_update"
            " (deep state walk) for wrapper metrics."
        )
    reductions = dict(metric._reductions)
    list_state_keys = [k for k, v in metric._defaults.items() if isinstance(v, list)]
    if list_state_keys and cat_capacity is None:
        raise ValueError(
            f"Metric {type(metric).__name__} has list ('cat') states {list_state_keys}; jitted"
            " accumulation needs a fixed capacity — pass cat_capacity (max total rows) and an"
            " example_batch."
        )
    init_state = {
        k: v if is_sketch_state(v) else jnp.asarray(v)
        for k, v in metric._defaults.items()
        if k not in list_state_keys
    }
    if list_state_keys:
        if example_batch is None:
            raise ValueError("cat_capacity requires example_batch to infer per-state row shapes")
        layout = infer_cat_layout(metric, example_batch)
        for k in list_state_keys:
            elem, dtype = layout[k]
            init_state[k] = cat_buffer_init(cat_capacity, elem, dtype)
    init_state["_update_count"] = jnp.asarray(0, jnp.int32)

    def step(state: Dict[str, Any], *batch: Any) -> Dict[str, Any]:
        state = dict(state)
        count = state.pop("_update_count")
        fresh = _batch_update_state(metric, batch, {})
        for k in list_state_keys:
            rows = jnp.concatenate([jnp.atleast_1d(x) for x in fresh.pop(k)])
            state[k] = cat_buffer_append(state[k], rows)
        array_keys = [k for k in fresh]
        # mean states: weighted running average; count==0 degenerates to the
        # fresh state exactly ((0*a + 1*b)/1 == b), so no special first step
        merged = tree_merge(
            {k: reductions[k] for k in array_keys},
            {k: state[k] for k in array_keys},
            fresh,
            weight_a=count,
            weight_b=1,
        )
        for k in list_state_keys:
            merged[k] = state[k]
        merged["_update_count"] = count + 1
        return merged

    return step, init_state


def fold_jit_state(metric: "Any", state: Dict[str, Any]) -> None:
    """Load a :func:`make_jit_update` final state back into the metric.

    Converts :class:`CatBuffer` states to the metric's host-side list states
    (raising if any buffer overflowed) and restores the update count. A
    ``"_telemetry"`` carry (device telemetry was enabled at build) moves to
    the metric's pending accumulator, drained into ``device.*`` gauges at the
    next ``compute()``/``sync()`` boundary.
    """
    state = dict(state)
    telemetry = state.pop("_telemetry", None)
    if telemetry is not None:
        # fold is a host boundary already: deriving the histogram config from
        # the state's edge vector (a tiny materialization) is fine here
        _obs_device.accumulate(metric, telemetry, _obs_device.state_histogram_config(telemetry))
    tree = {}
    for k, v in state.items():
        if isinstance(v, CatBuffer):
            tree[k] = [cat_buffer_values(v)]
        else:
            tree[k] = v
    # "_update_count" rides the tree's reserved key symmetrically with
    # state_tree(include_count=True) — load_state_tree restores the counter
    metric.load_state_tree(tree)
    metric._computed = None


# ------------------------------------------------------------- sharded update


def _walk_metrics(metric: "Any") -> list:
    """Depth-first ``[(path, metric), ...]`` over the metric and every Metric
    reachable through its attributes — wrapper children held directly or
    inside ARBITRARILY NESTED list/tuple/dict values (list-of-list,
    dict-of-list, ...: ``MultioutputWrapper.metrics``, ``MetricTracker``,
    user grids). The root's path is ``""``; child paths are
    ``attr``/``attr[i]``/``attr[key]`` segments joined with ``/``.

    A Metric reachable ONLY through an UNORDERED container (set/frozenset)
    raises: its state would be silently excluded from the deep
    snapshot/reset/restore and a traced update would later die with an
    opaque ``UnexpectedTracerError``. A metric that merely ALSO sits in a
    set (e.g. an auxiliary dedup index over a list attribute) is fine — the
    check runs after the whole walk, against everything the supported paths
    reached. metriclint rule ML005 flags the construction statically."""
    from torchmetrics_tpu.metric import Metric

    set_hits: list = []

    def find(seg: str, val: Any, found: list, visiting: set) -> None:
        if isinstance(val, Metric):
            found.append((seg, val))
        elif isinstance(val, (list, tuple, dict)):
            if id(val) in visiting:  # self-referential container
                return
            visiting.add(id(val))
            items = val.items() if isinstance(val, dict) else enumerate(val)
            for k, v in items:
                find(f"{seg}[{k}]", v, found, visiting)
        elif isinstance(val, (set, frozenset)):
            collect_set_hits(seg, val)

    def collect_set_hits(seg: str, val: Any) -> None:
        # anything at any depth under a set/frozenset (members may be
        # tuples/frozensets) is unreachable for the ordered state walk
        if isinstance(val, Metric):
            set_hits.append((seg, val))
        elif isinstance(val, (set, frozenset, tuple, list)):
            for v in val:
                collect_set_hits(seg, v)
        elif isinstance(val, dict):
            for v in val.values():
                collect_set_hits(seg, v)

    seen = {id(metric)}
    out = [("", metric)]
    stack = [("", metric)]
    while stack:
        path, m = stack.pop()
        for attr, val in vars(m).items():
            found: list = []
            find(attr, val, found, set())
            for seg, child in found:
                if id(child) in seen:
                    continue
                seen.add(id(child))
                child_path = f"{path}/{seg}" if path else seg
                out.append((child_path, child))
                stack.append((child_path, child))
    orphaned = sorted({seg for seg, m in set_hits if id(m) not in seen})
    if orphaned:
        raise ValueError(
            f"cannot shard: metric reachable only via unsupported container(s) {orphaned}"
            " (set/frozenset have no stable order for the state walk) — use a list,"
            " tuple, or dict"
        )
    return out


def _walk_fingerprint(metric: "Any") -> Tuple:
    """Structural fingerprint of the metric walk for cache invalidation:
    ``(path, id(child), unsupported-reason)`` per reachable metric. Swapping
    a wrapper's child (``tracker.base_metric = other``) or flipping an
    instance flag changes the fingerprint, so a cached compiled step keyed on
    it can never silently fold the OLD children (ADVICE.md round-5)."""
    return tuple(
        (path, id(m), getattr(m, "_sharded_update_unsupported", None), getattr(m, "_sharded_fold_children", True))
        for path, m in _walk_metrics(metric)
    )


def _fold_targets(metric: "Any") -> list:
    """The ``_walk_metrics`` entries whose states the sharded fold must merge.

    A wrapper that consumes its children's state per update event and resets
    them (``Running``: child state is transient, the replicated path leaves it
    pristine) declares ``_sharded_fold_children = False``; its descendants are
    traced and snapshotted but NOT folded — folding them would bump their
    update counts and mean-state weights away from the replicated path."""
    walk = _walk_metrics(metric)
    no_fold_prefixes = [
        f"{path}/" if path else "" for path, m in walk if not getattr(m, "_sharded_fold_children", True)
    ]

    def skipped(path: str) -> bool:
        return any(path != pref.rstrip("/") and path.startswith(pref) for pref in no_fold_prefixes)

    return [(path, m) for path, m in walk if not skipped(path)]


def _deep_key(path: str, name: str) -> str:
    """Flat pytree key for a state: plain ``name`` on the root (preserving the
    childless-metric key format everywhere), ``path:name`` on children
    (attribute names cannot contain ``:``)."""
    return f"{path}:{name}" if path else name


def deep_reductions(metric: "Any") -> Dict[str, Any]:
    """``dist_reduce_fx`` registry over the metric AND its wrapper children."""
    return {_deep_key(p, n): r for p, m in _walk_metrics(metric) for n, r in m._reductions.items()}


def deep_state_tree(metric: "Any") -> Dict[str, Any]:
    """``state_tree`` over the metric and its wrapper children (flat keys)."""
    return {_deep_key(p, n): v for p, m in _walk_metrics(metric) for n, v in m.state_tree().items()}


def _deep_snapshot(metric: "Any") -> list:
    return [
        (m, m._copy_state_dict(), m._update_count, m._computed,
         {a: getattr(m, a) for a in getattr(m, "_host_counters", ())},
         getattr(m, "_device_telemetry", None))
        for _, m in _walk_metrics(metric)
    ]


def _deep_restore(snapshot: list) -> None:
    for m, state, count, computed, counters, telemetry in snapshot:
        m._install_state_tree(state)  # self-snapshot: trusted, no validation
        m._update_count = count
        m._computed = computed
        for attr, val in counters.items():
            setattr(m, attr, val)
        # pending device telemetry survives trace-time resets and forward's
        # batch-local detour (which would otherwise double-count the batch)
        m._device_telemetry = telemetry


def _deep_batch_update_state(metric: "Any", args: Tuple, kwargs: Dict[str, Any]) -> Dict[str, Any]:
    """Run ``metric.update`` on a fresh state and return the deep state pytree.

    Pure w.r.t. traced inputs: the metric object AND every reachable child
    metric are reset/restored around the traced update so no tracer leaks
    into any host-side object (wrappers delegate ``update`` to children)."""
    snapshot = _deep_snapshot(metric)
    try:
        for _, m in _walk_metrics(metric):  # wrapper reset may not cascade; per-metric reset is idempotent
            m.reset()
        metric.update(*args, **kwargs)
        return deep_state_tree(metric)
    finally:
        _deep_restore(snapshot)


def _batch_update_state(metric: "Any", args: Tuple, kwargs: Dict[str, Any]) -> Dict[str, Any]:
    """Run ``metric.update`` on a fresh state and return the resulting pytree.

    Pure w.r.t. traced inputs: the metric object is reset/restored around the
    traced update so no tracer leaks into the host-side object.
    """
    saved = metric._copy_state_dict()
    saved_count = metric._update_count
    saved_computed = metric._computed
    saved_telemetry = getattr(metric, "_device_telemetry", None)
    try:
        metric.reset()
        metric.update(*args, **kwargs)
        return metric.state_tree()
    finally:
        metric._install_state_tree(saved)  # self-snapshot: trusted
        metric._update_count = saved_count
        metric._computed = saved_computed
        metric._device_telemetry = saved_telemetry  # trace-time reset must not drop pending telemetry


def make_sharded_update(
    metric: "Any",
    mesh: Mesh,
    axis_name: str = "data",
    in_specs: Optional[Any] = None,
) -> Callable[..., Dict[str, Any]]:
    """Build a jitted function ``(batch...) -> merged state pytree``.

    The returned function shards its array arguments along ``axis_name`` over
    ``mesh``, runs the metric's ``update`` per device on the local shard, and
    reduces the per-device partial states with the collectives of
    :func:`mesh_reduce_tree`. The result is a fully-replicated state pytree
    ready to be merged into the host-side metric with
    :meth:`Metric.load_state_tree` / :func:`tree_merge`.

    List ("cat"/None) states work too: within one update step the per-shard
    appended rows have static shapes, so each append ``all_gather``s and
    flattens device-ordered — exact curves, Spearman/Kendall, and retrieval
    metrics run in this regime with no capacity bound (the buffer-capacity
    machinery of :func:`make_jit_update` is only needed when the whole
    streaming loop lives inside one compiled program).

    Wrapper metrics (MinMax, Classwise, Multioutput, Running, ...) shard too:
    the traced update walks every reachable child metric, so the merged pytree
    carries the children's states under ``path:name`` keys (root states keep
    their plain names — childless metrics see the same tree as before).
    Metrics whose update cannot be traced (``BootStrapper``'s per-update host
    resampling) declare ``_sharded_update_unsupported`` and are refused here.
    """
    for path, m in _walk_metrics(metric):
        reason = getattr(m, "_sharded_update_unsupported", None)
        if reason:
            where = f" (at {path!r})" if path else ""
            raise ValueError(f"{type(m).__name__} does not support sharded_update{where}: {reason}")
    reductions = deep_reductions(metric)
    # device telemetry is a BUILD-time decision (obs/device.py): with the flag
    # off the traced program below is byte-identical to a never-instrumented
    # build; sharded_update keys its cache on the config so a flip rebuilds
    telemetry_on, histogram = _obs_device.config_token()

    def per_device(*args: Any, **kwargs: Any) -> Dict[str, Any]:
        partial_state = _deep_batch_update_state(metric, args, kwargs)
        out = mesh_reduce_tree(reductions, partial_state, axis_name)
        if telemetry_on:
            telemetry = _obs_device.telemetry_update(
                _obs_device.telemetry_init(max(1, len(args)), histogram), args
            )
            out["_telemetry"] = _obs_device.telemetry_mesh_reduce(telemetry, axis_name)
        return out

    def build_specs(args: Sequence[Any]) -> Tuple:
        # batch args shard along axis_name; scalars/0-d args are replicated
        return tuple(P(axis_name) if getattr(jnp.asarray(a), "ndim", 0) >= 1 else P() for a in args)

    key_base = _fingerprint_digest(
        "sharded", type(metric).__name__, axis_name, _walk_fingerprint(metric), telemetry_on
    )
    fn_cache: Dict[Tuple, Callable] = {}

    def sharded(*args: Any) -> Dict[str, Any]:
        specs = in_specs if in_specs is not None else build_specs(args)
        key = tuple(specs)
        fn = fn_cache.get(key)
        if fn is None:
            jitted = jax.jit(
                shard_map(
                    per_device,
                    mesh=mesh,
                    in_specs=specs,
                    out_specs=P(),  # merged state is replicated
                    check_rep=False,
                )
            )
            # jax.jit is lazy — trace, XLA compile and the first execution
            # all hide inside the first call. The instrumented wrapper
            # splits them under tracing: ``sharded.lower`` / ``sharded.compile``
            # (tagged with the backend's flops/bytes cost analysis, keyed by
            # the cache fingerprint) / ``sharded.first_step`` — so compile
            # time is no longer conflated with first-step execution.
            fn = _obs_xla.instrument_jit(
                jitted,
                key=f"{key_base}:{_fingerprint_digest(key)}",
                metric=type(metric).__name__,
                kind="sharded",
                span_prefix="sharded",
            )
            fn_cache[key] = fn
        out = fn(*args)
        if telemetry_on:
            # strip the carry HERE so the public contract is unchanged: the
            # returned pytree stays load_state_tree/tree_merge-ready whether
            # telemetry is on or off; the pending accumulator on the metric
            # (device-side merge, no host sync) is the telemetry's only exit
            out = dict(out)
            telemetry = out.pop("_telemetry", None)
            if telemetry is not None:
                _obs_device.accumulate(metric, telemetry, histogram)
        return out

    sharded._fn_cache = fn_cache  # per-spec instrumented jits (tests lower through this)
    return sharded


def sharded_update(
    metric: "Any",
    mesh: Mesh,
    *args: Any,
    axis_name: str = "data",
) -> None:
    """Execute one sharded update step and fold the result into ``metric``.

    The user-facing one-liner::

        mesh = jax.make_mesh((8,), ("data",))
        sharded_update(acc, mesh, preds, target)   # preds/target sharded 8-way

    Equivalent to ``metric.update`` on the full batch, but each device only
    touches its shard — the reference's DDP regime without processes. The
    compiled step is cached on the metric per (mesh, axis), so repeated calls
    dispatch the same XLA program.
    """
    # the walk fingerprint is part of the key: swapping a wrapper's child or
    # flipping an instance-level flag after the first call must invalidate the
    # cached compiled step, or it would silently fold the OLD children
    # (ADVICE.md round-5). The fingerprint walk re-runs per call but is a
    # cheap host-side attribute scan; the expensive parts (trace + compile +
    # fold-target resolution) stay cached. The device-telemetry config rides
    # the key too: telemetry is baked into the traced program at build, so a
    # flag flip must rebuild, never serve the wrong instrumentation state.
    key = (id(metric), id(mesh), axis_name, _walk_fingerprint(metric), _obs_device.config_token())
    entry = _SHARDED_FN_CACHE.get(key)
    cold = entry is None or entry[0]() is not metric or entry[1]() is not mesh
    if cold:
        if _obs_trace.ENABLED:
            # a live-looking entry whose weakrefs went stale is an id-reuse
            # invalidation, not a plain miss — count them apart
            _obs_counters.inc("sharded.cache.miss" if entry is None else "sharded.cache.invalidated")
            with _obs_trace.span("sharded.jit_build", metric=type(metric).__name__, axis=axis_name):
                built = make_sharded_update(metric, mesh, axis_name=axis_name)
        else:
            built = make_sharded_update(metric, mesh, axis_name=axis_name)
        ref_m, ref_mesh = weakref.ref(metric), weakref.ref(mesh)
        entry = (ref_m, ref_mesh, built, _fold_targets(metric))
        # evict superseded fingerprints of the same (metric, mesh, axis) so
        # repeated child swaps do not grow the cache without bound
        stale = [k for k in _SHARDED_FN_CACHE if k[:3] == key[:3] and k != key]
        for old in stale:
            del _SHARDED_FN_CACHE[old]
        if stale and _obs_trace.ENABLED:
            _obs_counters.inc("sharded.cache.evict", len(stale))
            _obs_trace.instant("sharded.cache.evict", metric=type(metric).__name__, evicted=len(stale))
        _SHARDED_FN_CACHE[key] = entry
    elif _obs_trace.ENABLED:
        _obs_counters.inc("sharded.cache.hit")
    update_fn, walk = entry[2], entry[3]
    if _obs_trace.ENABLED:
        with _obs_trace.span("sharded.update_step", metric=type(metric).__name__, cold=cold):
            merged = update_fn(*args)
    else:
        merged = update_fn(*args)
    # telemetry (if enabled at build) was already stripped and accumulated by
    # the make_sharded_update closure — `merged` is a clean state pytree here
    for path, m in walk:
        prev_count = m._update_count
        m._computed = None
        m._update_count += 1
        part = {n: merged[_deep_key(path, n)] for n in m._defaults}
        # default fold: reduction-keyed merge, "mean" states weighted by the
        # running update count (reference metric.py:317); event-indexed
        # wrappers (Running) override the hook with their rotation
        m._fold_sharded_state(part, prev_count)


class ShardedMetric:
    """Wrap a metric so ``update``/``forward`` run sharded over a mesh axis.

    Drop-in shell: all other attribute access proxies to the wrapped metric.
    """

    def __init__(self, metric: "Any", mesh: Mesh, axis_name: str = "data") -> None:
        object.__setattr__(self, "_metric", metric)
        object.__setattr__(self, "_mesh", mesh)
        object.__setattr__(self, "_axis_name", axis_name)

    def update(self, *args: Any) -> None:
        sharded_update(self._metric, self._mesh, *args, axis_name=self._axis_name)

    def forward(self, *args: Any) -> Any:
        """Sharded accumulate + batch-local value (reference ``metric.py:283`` dual return).

        For ``full_state_update`` wrappers (MinMax) this PRESERVES the wrapped
        metric's global accumulation: the fold is a real state merge, and the
        batch-local detour deep-snapshots every reachable child. The
        reference's double-update trick instead resets children whose states
        its shallow cache never captured (``metric.py:336-346`` +
        ``minmax.py:106``), so upstream a ``forward`` stream leaves the base
        metric holding only the last batch.
        """
        prev_count = self._metric._update_count
        self.update(*args)
        if prev_count > 0:
            # batch-local value needs a fresh state: run the (cached) sharded
            # step once more on a reset metric, compute, then restore (deep:
            # wrapper children snapshot/restore too)
            snapshot = _deep_snapshot(self._metric)
            for _, m in _walk_metrics(self._metric):
                m.reset()
            sharded_update(self._metric, self._mesh, *args, axis_name=self._axis_name)
            # the detour re-measured the SAME batch the real update already
            # accumulated telemetry for: discard the duplicate so the detour
            # compute() cannot drain batch-local numbers over the cumulative
            # device.* gauges (the snapshot restores the true pending state)
            self._metric._device_telemetry = None
            self._metric._to_sync = False
            batch_val = self._metric.compute()
            self._metric._to_sync = self._metric.sync_on_compute
            _deep_restore(snapshot)
            self._metric._computed = None
            return batch_val
        self._metric._to_sync = False
        val = self._metric.compute()
        self._metric._to_sync = self._metric.sync_on_compute
        self._metric._computed = None
        return val

    def __call__(self, *args: Any) -> Any:
        return self.forward(*args)

    def compute(self) -> Any:
        return self._metric.compute()

    def reset(self) -> None:
        self._metric.reset()

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_metric"), name)
