# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""One-dispatch fused evaluation plane: a whole ``MetricCollection`` as ONE
compiled, donated, scan-able step.

The unfused streaming loop pays, per batch and per metric, a Python
``update()`` dispatch, a transactional state snapshot, and an obs-flag check
— PR 8's cost ledger (``metricscope top --by host_self_ms``) shows that host
self-time dominating the compiled device work for multi-metric collections.
:class:`FusedCollectionPlan` removes it:

- every member's registered state is flattened (via the same ``add_state``
  registry ``make_jit_update`` consumes) into ONE state pytree
  ``{"members": {key: {state...}}, "_update_count": i32}``, compute-group
  dedup preserved — only group LEADERS are traced, so shared states compile
  once and members keep riding the collection's state-ref propagation;
- the entire collection update compiles into a single jitted step with
  ``donate_argnums=0`` on the state carry: XLA updates the state in place,
  so a streaming loop allocates nothing per batch;
- :meth:`FusedCollectionPlan.run_scan` pushes a whole pre-staged chunk of
  batches through the step under ``lax.scan`` — zero per-batch Python;
  :meth:`FusedCollectionPlan.run_stream` adds the async double-buffered
  host→device feed (:mod:`torchmetrics_tpu.parallel.feed`) so staging batch
  k+1 overlaps the compiled step on batch k;
- :meth:`FusedCollectionPlan.fold_back` installs the carried totals back
  into the member metrics (CatBuffers become list states, the update count
  restores, group members resync), so ``compute()``/``sync``/checkpointing
  are completely unchanged — fold-back happens at snapshot/compute
  boundaries, never per batch.

**Parity contract.** The local (unsharded) step TRACES each leader's own
``update`` against the carried state — the computation is literally the
eager one, so fused == unfused is bitwise for every state kind (elementwise,
cat/CatBuffer, sketch "merge"); pinned by
``tests/unittests/bases/test_fused.py`` under plain jit, ``lax.scan``, and
kill-and-resume. The sharded step mirrors ``sharded_update`` exactly
(per-device fresh update, ``mesh_reduce_tree``, count-weighted fold), so
fused-sharded == unfused-sharded bitwise on the same mesh.

**Eligibility.** Fusion requires a traceable positional update: metrics with
kwargs-only update signatures, host-state updates
(``_sharded_update_unsupported``), host-side counters, or wrapper children
are refused with a per-member report (:func:`fusion_report`) — metriclint
rule ML007 flags the same constructions statically.

With device telemetry enabled at build (:mod:`torchmetrics_tpu.obs.device`)
the fused state additionally carries ONE ``TelemetryState`` for the whole
collection (members see the same batch, so per-member carries would be
copies); fold-back accumulates it into every leader's pending slot. Cold
builds ride the AOT compile capture (``obs/xla.py``), recorded under the
collection class with per-member ``instances`` so ``metricscope top`` still
attributes the fused step's flops/compile cost.
"""
from __future__ import annotations

import inspect
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from torchmetrics_tpu.obs import attribution as _obs_attr
from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import device as _obs_device
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.obs import xla as _obs_xla
from torchmetrics_tpu.parallel.cat_buffer import (
    cat_buffer_append,
    cat_buffer_init,
    cat_buffer_values,
    infer_cat_layout,
)
from torchmetrics_tpu.parallel.sharded import (
    _batch_update_state,
    _fingerprint_digest,
    _update_arity,
    _walk_fingerprint,
    _walk_metrics,
    mesh_reduce_tree,
    plan_cache_lookup,
    plan_cache_store,
    shard_map,
    tree_merge,
)

__all__ = ["FusedCollectionPlan", "fusion_ineligibility", "fusion_report"]

_POSITIONAL_KINDS = (
    inspect.Parameter.POSITIONAL_ONLY,
    inspect.Parameter.POSITIONAL_OR_KEYWORD,
    inspect.Parameter.VAR_POSITIONAL,
)


# ---------------------------------------------------------------- eligibility


def fusion_ineligibility(metric: Any) -> Optional[str]:
    """Why ``metric`` cannot enter a fused plan, or ``None`` when it can.

    The SAME predicate metriclint's ML007 applies statically: kwargs-only
    update signatures and host-state metrics are fusion-ineligible; the
    runtime additionally refuses wrapper children and host-side counters
    (things the AST cannot always prove).
    """
    reason = getattr(metric, "_sharded_update_unsupported", None)
    if reason:
        return f"host-state update ({reason})"
    counters = getattr(metric, "_host_counters", ())
    if counters:
        return f"host-side counters {sorted(counters)} cannot ride the device state carry"
    if not getattr(metric, "_defaults", None):
        return "declares no registered states"
    if len(_walk_metrics(metric)) > 1:
        return "wraps child metrics; the fused state pytree covers only the root registry"
    params = [
        p for name, p in inspect.signature(type(metric).update).parameters.items() if name != "self"
    ]
    if not any(p.kind in _POSITIONAL_KINDS for p in params):
        return "update() accepts no positional batch arguments (kwargs-only signature)"
    return None


def fusion_report(target: Any) -> Dict[str, Optional[str]]:
    """Per-member eligibility report for a Metric or MetricCollection:
    ``{member: None}`` when fusable, ``{member: reason}`` otherwise. The
    plan's build raises with exactly these reasons; ML007 flags the same
    members statically. Read-only: unlike the plan build, asking for a
    report never touches the collection's state-ref propagation."""
    members, _ = _resolve_members(target, propagate_state=False)
    return {key: fusion_ineligibility(m) for key, m in members.items()}


def _resolve_members(target: Any, propagate_state: bool = True) -> Tuple[Dict[str, Any], List[List[str]]]:
    """``(members, groups)``: base-keyed member dict plus compute groups
    (leader first). A bare Metric is a one-member collectionette. With
    ``propagate_state`` (the plan build) a copy-state collection first
    re-propagates leader state into members — the same entry protocol as
    ``MetricCollection.update``; eligibility queries skip it."""
    from torchmetrics_tpu.collections import MetricCollection
    from torchmetrics_tpu.metric import Metric

    if isinstance(target, MetricCollection):
        if propagate_state and target._state_is_copy:
            # mirror MetricCollection.update's entry: members must hold real
            # (non-copy) state before we snapshot it into the carry
            target._compute_groups_create_state_ref(copy=False)
            target._state_is_copy = False
        keys = sorted(dict.keys(target))
        members = {k: dict.__getitem__(target, k) for k in keys}
        if target._enable_compute_groups and target._groups_checked:
            groups = [list(cg) for cg in target._groups.values()]
        else:
            # groups not (yet) established: every member leads itself. Run two
            # eager updates (or pass explicit compute_groups) before fusing to
            # let the dedup discovery fire — the plan freezes the assignment.
            groups = [[k] for k in keys]
        return members, groups
    if isinstance(target, Metric):
        name = type(target).__name__
        return {name: target}, [[name]]
    raise TypeError(f"cannot fuse a {type(target).__name__}; expected Metric or MetricCollection")


# --------------------------------------------------------------------- helpers


def _copy_tree(tree: Any) -> Any:
    """Deep device copy of a state pytree: decouples the live metric (or a
    fold-back target) from buffers the donated step will consume."""
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), tree)


def _concat_rows(appended: Sequence[Any]) -> Any:
    return jnp.concatenate([jnp.atleast_1d(x) for x in appended])


class _MemberInfo:
    """Static per-leader build record (not a pytree)."""

    __slots__ = ("key", "metric", "reductions", "list_keys", "layout")

    def __init__(self, key: str, metric: Any, cat_capacity: Optional[int], example_batch) -> None:
        self.key = key
        self.metric = metric
        self.reductions = dict(metric._reductions)
        self.list_keys = [k for k, v in metric._defaults.items() if isinstance(v, list)]
        if self.list_keys and (cat_capacity is None or example_batch is None):
            raise ValueError(
                f"member {key!r} ({type(metric).__name__}) has list ('cat') states"
                f" {self.list_keys}; the fused plan needs cat_capacity (max total rows)"
                " and an example_batch to give them fixed-capacity CatBuffer carries"
            )
        self.layout = infer_cat_layout(metric, tuple(example_batch)) if self.list_keys else {}


def _traced_member_update(info: _MemberInfo, mstate: Dict[str, Any], batch: Tuple[Any, ...]) -> Dict[str, Any]:
    """One leader's update traced AGAINST the carried state.

    Installing the carry and running the metric's own (wrapped) ``update``
    makes the traced program literally the eager computation — the basis of
    the fused==unfused bitwise guarantee. List ("cat") states are installed
    empty; the freshly appended rows append into the CatBuffer carry. The
    host-side metric object is snapshot/restored around the trace so no
    tracer leaks out (same discipline as ``_batch_update_state``).
    """
    metric = info.metric
    saved = metric._copy_state_dict()
    saved_count, saved_computed = metric._update_count, metric._computed
    saved_telemetry = getattr(metric, "_device_telemetry", None)
    try:
        install = {
            k: v for k, v in mstate.items() if k not in info.list_keys and k != "_update_count"
        }
        for k in info.list_keys:
            install[k] = []
        metric._install_state_tree(install)
        metric._computed = None
        metric.update(*batch)
        tree = metric.state_tree()
    finally:
        metric._install_state_tree(saved)  # self-snapshot: trusted
        metric._update_count = saved_count
        metric._computed = saved_computed
        metric._device_telemetry = saved_telemetry
    out = {k: v for k, v in tree.items() if k not in info.list_keys}
    for k in info.list_keys:
        appended = tree[k]
        out[k] = mstate[k] if not appended else cat_buffer_append(mstate[k], _concat_rows(appended))
    # the member's running update count rides ITS slice of the carry (seeded
    # from the live metric at build), so the traced program never bakes in
    # prior progress — a rebuilt plan over a resumed metric reuses the cache
    out["_update_count"] = mstate["_update_count"] + 1
    return out


# ------------------------------------------------------------------- the plan


class FusedCollectionPlan:
    """Compile a whole collection's update into one donated step.

    ::

        suite = MetricCollection({"acc": ..., "f1": ..., "auroc": ...})
        suite.update(p0, t0); suite.update(p1, t1)   # let compute groups form
        plan = suite.fused()                          # ONE compiled step
        for preds, target in stream:
            plan.update(preds, target)               # one dispatch, N metrics
        plan.run_scan(chunk)                          # or: zero per-batch Python
        plan.fold_back()                              # states back in the metrics
        suite.compute()                               # unchanged from here on

    The carry is seeded from the members' CURRENT states (fusing mid-stream
    or after a checkpoint restore just works) and donated on every step —
    hold no references to ``plan.state`` across updates.

    Args:
        target: a ``MetricCollection`` (or bare ``Metric``).
        cat_capacity: max TOTAL rows per list ("cat") state; required (with
            ``example_batch``) when any member has list states.
        example_batch: example positional batch, used only under
            ``jax.eval_shape`` to size CatBuffer carries.
        donate: donate the state carry (default True — the fused plane's
            raison d'être); pass False to keep old states readable.
        mesh/axis_name: build the SHARDED variant instead — the per-batch
            step runs every leader's update under ``shard_map`` over the
            mesh axis and mesh-reduces, exactly like ``sharded_update``.
    """

    def __init__(
        self,
        target: Any,
        *,
        cat_capacity: Optional[int] = None,
        example_batch: Optional[Tuple[Any, ...]] = None,
        donate: bool = True,
        mesh: Optional[Mesh] = None,
        axis_name: str = "data",
    ) -> None:
        from torchmetrics_tpu.collections import MetricCollection

        members, groups = _resolve_members(target)
        report = {k: fusion_ineligibility(m) for k, m in members.items()}
        bad = {k: r for k, r in report.items() if r}
        if bad:
            detail = "; ".join(f"{k}: {r}" for k, r in sorted(bad.items()))
            raise ValueError(f"cannot fuse {type(target).__name__}: {detail}")
        self.members = members
        self.groups = groups
        self._collection = target if isinstance(target, MetricCollection) else None
        self._target_cls = type(target).__name__
        self._donate = bool(donate)
        self._mesh = mesh
        self._axis = axis_name
        self._cat_capacity = cat_capacity
        self._telemetry_on, self._histogram = _obs_device.config_token()
        self._infos = [
            _MemberInfo(cg[0], members[cg[0]], cat_capacity, example_batch) for cg in groups
        ]
        if mesh is not None:
            # the sharded carry folds fresh events with tree_merge: a None or
            # custom-callable reduction on an ARRAY state stacks (shape grows
            # per step), which cannot live in a fixed-shape compiled carry
            for info in self._infos:
                for name, red in info.reductions.items():
                    if name not in info.list_keys and not isinstance(red, str):
                        raise ValueError(
                            f"cannot fuse {info.key!r} ({type(info.metric).__name__}) over a mesh:"
                            f" array state {name!r} declares dist_reduce_fx={red!r}, whose stacking"
                            " fold grows the state per step — fixed-shape carries need a named"
                            " reduction (sum/mean/max/min/merge)"
                        )
        self._arity = (
            len(example_batch)
            if example_batch is not None
            else max(_update_arity(info.metric) for info in self._infos)
        )
        if _obs_trace.ENABLED:
            with _obs_trace.span(
                "fused.build",
                metric=self._target_cls,
                members=len(members),
                leaders=len(self._infos),
                sharded=mesh is not None,
            ):
                self._build_steps()
        else:
            self._build_steps()
        self._state = self._initial_state()
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            self._note_attribution()

    # ------------------------------------------------------------------ build
    def _fingerprint(self) -> str:
        """Build identity: group structure, per-leader walk fingerprints and
        init counts (both appear in the traced program), cat config, donation
        and the telemetry token — the key fused compile records and the
        sharded-step cache file under."""
        return _fingerprint_digest(
            "fused",
            self._target_cls,
            tuple(
                (info.key, type(info.metric).__name__, _walk_fingerprint(info.metric),
                 tuple(info.list_keys))
                for info in self._infos
            ),
            tuple(tuple(cg) for cg in self.groups),
            self._cat_capacity,
            self._donate,
            self._axis if self._mesh is not None else None,
            _obs_device.config_token(),
        )

    def _build_steps(self) -> None:
        raw = self._build_sharded_raw_step() if self._mesh is not None else self._build_local_raw_step()
        self._raw_step = raw
        jit_kwargs = {"donate_argnums": 0} if self._donate else {}
        key = self._fingerprint()

        # fused steps (local AND sharded) ride _SHARDED_FN_CACHE: rebuilding
        # a plan over the same target — a resumed evaluator, a fresh plan per
        # epoch — reuses the compiled steps instead of paying trace+compile
        # again (the carry-riding update counts exist precisely so rebuilt
        # programs are cache-identical).
        cache_key, cached = plan_cache_lookup("fused", self._ref_target(), self._mesh, self._axis, key)
        if cached is not None:
            self._step, self._scan_step = cached
            return

        def step_fn(state, *batch):
            return raw(state, batch)

        def chunk_fn(state, stacked):
            def body(s, b):
                return raw(s, b), None

            return jax.lax.scan(body, state, stacked)[0]

        self._step = _obs_xla.instrument_jit(
            jax.jit(step_fn, **jit_kwargs),
            key=key, metric=self._target_cls, kind="fused", span_prefix="fused.update",
        )
        self._scan_step = _obs_xla.instrument_jit(
            jax.jit(chunk_fn, **jit_kwargs),
            key=f"{key}:scan", metric=self._target_cls, kind="fused_scan", span_prefix="fused.scan",
        )
        plan_cache_store(
            "fused", cache_key, self._ref_target(), self._mesh, (self._step, self._scan_step)
        )

    def _ref_target(self) -> Any:
        return self._collection if self._collection is not None else self._infos[0].metric

    def _build_local_raw_step(self):
        infos, telemetry_on = self._infos, self._telemetry_on

        def raw_step(state, batch):
            members = state["members"]
            out_members = {info.key: _traced_member_update(info, members[info.key], batch) for info in infos}
            out = {"members": out_members, "_update_count": state["_update_count"] + 1}
            if telemetry_on:
                out["_telemetry"] = _obs_device.telemetry_update(state["_telemetry"], batch)
            return out

        return raw_step

    def _build_sharded_raw_step(self):
        infos, axis, mesh = self._infos, self._axis, self._mesh
        telemetry_on, histogram = self._telemetry_on, self._histogram

        def per_device(*batch):
            out = {}
            for info in infos:
                partial = _batch_update_state(info.metric, batch, {})
                out[info.key] = mesh_reduce_tree(info.reductions, partial, axis)
            if telemetry_on:
                fresh = _obs_device.telemetry_update(
                    _obs_device.telemetry_init(max(1, len(batch)), histogram), batch
                )
                out["_telemetry"] = _obs_device.telemetry_mesh_reduce(fresh, axis)
            return out

        def raw_step(state, batch):
            # batch shapes are static under trace, so the specs (and the
            # shard_map they parameterize) resolve at trace time
            specs = tuple(P(axis) if getattr(jnp.asarray(a), "ndim", 0) >= 1 else P() for a in batch)
            fresh = shard_map(per_device, mesh=mesh, in_specs=specs, out_specs=P(), check_rep=False)(*batch)
            out_members = {}
            for info in infos:
                carry, f = state["members"][info.key], fresh[info.key]
                prev = carry["_update_count"]
                arr = {k: v for k, v in f.items() if k not in info.list_keys}
                merged = tree_merge(
                    {k: info.reductions[k] for k in arr},
                    {k: carry[k] for k in arr},
                    arr,
                    weight_a=prev,
                    weight_b=1,
                )
                # sharded_update LOADS the first-ever event's merged state
                # instead of folding it into the defaults — select the same
                # behavior so step one stays bitwise (sketch merges against
                # an empty default are not identity). prev rides the carry,
                # so the program is independent of prior progress.
                merged = {
                    k: jax.tree_util.tree_map(
                        lambda mv, fv: jnp.where(prev == 0, fv, mv), merged[k], arr[k]
                    )
                    for k in merged
                }
                for k in info.list_keys:
                    merged[k] = cat_buffer_append(carry[k], _concat_rows(f[k]))
                merged["_update_count"] = prev + 1
                out_members[info.key] = merged
            out = {"members": out_members, "_update_count": state["_update_count"] + 1}
            if telemetry_on:
                out["_telemetry"] = _obs_device.telemetry_merge(state["_telemetry"], fresh["_telemetry"])
            return out

        return raw_step

    def _initial_state(self) -> Dict[str, Any]:
        members: Dict[str, Any] = {}
        for info in self._infos:
            metric = info.metric
            slice_: Dict[str, Any] = {}
            for name in metric._defaults:
                value = getattr(metric, name)
                if name in info.list_keys:
                    elem, dtype = info.layout[name]
                    buf = cat_buffer_init(self._cat_capacity, elem, dtype)
                    if value:  # fusing mid-stream: existing rows seed the buffer
                        buf = cat_buffer_append(buf, _concat_rows(value))
                    slice_[name] = buf
                else:
                    # copies decouple the carry from the live metric state:
                    # the first donated step must not delete buffers the
                    # metric (or a checkpoint in flight) still references
                    slice_[name] = _copy_tree(value)
            slice_["_update_count"] = jnp.asarray(metric._update_count, jnp.int32)
            members[info.key] = slice_
        state: Dict[str, Any] = {"members": members, "_update_count": jnp.asarray(0, jnp.int32)}
        if self._telemetry_on:
            state["_telemetry"] = _obs_device.telemetry_init(self._arity, self._histogram)
        return state

    def _note_attribution(self) -> None:
        """Record the fused plan's join keys in the cost-attribution registry:
        member names under the COLLECTION row (where the fused XLA records
        land) and under each member's own class row."""
        _obs_attr.note_instances(self._target_cls, list(self.members))
        for key, metric in self.members.items():
            _obs_attr.note_instance(type(metric).__name__, key)

    # ------------------------------------------------------------------ drive
    @property
    def state(self) -> Dict[str, Any]:
        """The current state carry. With ``donate=True`` (the default) the
        next ``update``/``run_scan`` consumes these buffers — read, don't
        hold."""
        return self._state

    @property
    def updates_applied(self) -> int:
        """Fused steps applied since the plan was built (host sync)."""
        return int(self._state["_update_count"])

    def update(self, *batch: Any) -> None:
        """Apply one batch: ONE compiled call for the whole collection."""
        self._state = self._step(self._state, *batch)

    def run_scan(self, batches: Any) -> None:
        """Scan a pre-staged chunk of batches through the step — zero
        per-batch Python. ``batches`` is either a sequence of positional
        batch tuples (staged/stacked here, one host→device transfer) or an
        already-stacked tuple of arrays whose leading axis is the scan axis.
        """
        self._state = self._scan_step(self._state, self.stage(batches))

    def run_stream(self, batches: Iterable[Any], prefetch: int = 2) -> None:
        """Drive an iterable of batches through the double-buffered device
        feed: ``device_put`` of batch k+1 is dispatched while the compiled
        step runs on batch k (see :mod:`torchmetrics_tpu.parallel.feed`)."""
        from torchmetrics_tpu.parallel.feed import DeviceFeed

        for batch in DeviceFeed(batches, depth=prefetch):
            if isinstance(batch, tuple):
                self.update(*batch)
            else:
                self.update(batch)

    @staticmethod
    def stage(batches: Any) -> Tuple[Any, ...]:
        """Stack a sequence of batch tuples into scan-ready arrays."""
        if isinstance(batches, tuple):
            return tuple(jnp.asarray(b) for b in batches)
        seq = list(batches)
        if not seq:
            raise ValueError("run_scan needs at least one batch")
        if not isinstance(seq[0], tuple):
            return (jnp.stack([jnp.asarray(b) for b in seq]),)
        arity = len(seq[0])
        return tuple(jnp.stack([jnp.asarray(b[i]) for b in seq]) for i in range(arity))

    # -------------------------------------------------------------- fold-back
    def fold_back(self) -> None:
        """Install the carried totals back into the member metrics.

        Call at snapshot/compute boundaries (the :class:`StreamingEvaluator`
        fused drive does) — never per batch. Leaders get their exact state
        tree (CatBuffers fold to list states, raising on overflow; the update
        count restores as ``init + fused steps``); compute-group members
        resync counts and ride the collection's ordinary state-ref
        propagation at the next ``compute()``. Idempotent: folding twice
        installs the same totals. The carry stays valid — keep updating and
        fold again at the next boundary. Installed values are device COPIES,
        so the next donated step cannot delete state the metrics now hold.
        """
        state = self._state
        count = int(state["_update_count"])  # host sync: the fold IS a host boundary
        for info in self._infos:
            metric = info.metric
            mstate = _copy_tree(state["members"][info.key])
            tree: Dict[str, Any] = {}
            for name in metric._defaults:
                if name in info.list_keys:
                    tree[name] = [cat_buffer_values(mstate[name])]  # raises on overflow
                else:
                    tree[name] = mstate[name]
            tree["_update_count"] = int(mstate["_update_count"])
            metric.load_state_tree(tree)
            metric._computed = None
        telemetry = state.get("_telemetry")
        if telemetry is not None and count > 0:
            # one carry for the whole collection (members saw the same
            # batches): every leader's pending slot accumulates it, exactly
            # what per-member make_jit_update carries would have measured
            t_copy = _copy_tree(telemetry)
            for info in self._infos:
                _obs_device.accumulate(info.metric, t_copy, self._histogram)
            fresh = dict(state)
            fresh["_telemetry"] = _obs_device.telemetry_init(self._arity, self._histogram)
            self._state = fresh
        for cg in self.groups:
            leader = self.members[cg[0]]
            for key in cg[1:]:
                member = self.members[key]
                member._update_count = leader._update_count
                member._computed = None
        if self._collection is not None:
            # members hold (or will lazily receive) leader state — the same
            # post-update invariant MetricCollection.update leaves behind
            self._collection._state_is_copy = False
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            self._note_attribution()
            for info in self._infos:
                _obs_attr.metric_boundary(info.metric)
