# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Async double-buffered host→device batch feed.

A streaming evaluation that calls ``step(state, *batch)`` on host-resident
batches serializes three things that could overlap: producing batch k+1
(decode/augment/host copy), its host→device transfer, and the compiled step
on batch k. :class:`DeviceFeed` overlaps all three with a background staging
thread and a depth-bounded queue (the classic double-buffer at ``depth=2``,
the default)::

    plan = suite.fused()
    for batch in DeviceFeed(batches):      # producer + transfer overlap step k
        plan.update(*batch)

``depth`` bounds device memory: at most ``depth`` staged batches sit in the
queue (plus the one being staged). Tuples/lists/dicts of arrays transfer as
one pytree; numpy inputs upload, device-resident arrays pass through (a
no-op ``device_put``).

**Failure contract.** A producer exception — the batch iterable raising, or
the ``device_put`` staging itself failing — is captured by the staging
thread and re-raised to the CONSUMER on its next ``get()``/iteration step,
at the position where the batch would have appeared. Before this contract
the consumer would block on a queue that was never going to fill until the
runner's watchdog fired (a stall disguised as a slow device); now the drive
loop dies promptly with the real error. The ``feed.stage`` fault-injection
point (``robustness/faults.py``) rehearses exactly that path, and
``tests/unittests/bases/test_fused.py`` pins it.

Abandoning the iterator early (``break`` in the consumer loop) stops the
producer thread promptly — it never blocks forever on a full queue.

This is the host-side half of the fused evaluation plane's feed path
(ISSUE 9); :meth:`FusedCollectionPlan.run_stream` wires it in.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

import jax

from torchmetrics_tpu.robustness import faults

__all__ = ["DeviceFeed"]

_DONE = object()  # producer sentinel: the batch iterable is exhausted


class _ProducerError:
    """Envelope for an exception captured on the staging thread — re-raised
    on the consumer at the position where the failed batch would have
    appeared."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException) -> None:
        self.error = error


class DeviceFeed:
    """Iterate ``batches`` with a background thread staging up to ``depth``
    device transfers ahead of the consumer.

    Args:
        batches: any iterable of batches (pytrees of arrays — tuples of
            ``(preds, target)`` in the common case). Consumed on the staging
            thread: its ``__next__`` must not require the consumer's thread.
        device: target device; ``None`` uses the default device.
        depth: how many staged batches to keep queued ahead of the consumer
            (``2`` = classic double buffering; ``1`` degenerates to one
            batch ahead).
    """

    def __init__(self, batches: Iterable[Any], device: Optional[Any] = None, depth: int = 2) -> None:
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._batches = batches
        self._device = device
        self._depth = depth

    @staticmethod
    def _stage(batch: Any, device: Optional[Any]) -> Any:
        # device_put on a pytree dispatches every leaf's transfer
        # asynchronously and returns immediately
        if faults._ACTIVE:  # staging-fault drill: a poisoned batch/transfer
            faults.fire("feed.stage")
        return jax.device_put(batch, device)

    def __iter__(self) -> Iterator[Any]:
        staged: queue.Queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        # resolve the target device on the CONSUMER's thread: a
        # `with jax.default_device(...)` scope is thread-local, and the
        # staging thread would otherwise silently fall back to the global
        # default — batches must land where the consumer's context says
        device = self._device if self._device is not None else jax.config.jax_default_device

        def put_until_stopped(item: Any) -> bool:
            """Blocking put that yields to the stop flag (an abandoned
            consumer must never leave the producer wedged on a full queue);
            True when the item landed."""
            while not stop.is_set():
                try:
                    staged.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce() -> None:
            payload: Any = _DONE
            try:
                for batch in self._batches:
                    if not put_until_stopped(self._stage(batch, device)):
                        return
            except BaseException as err:  # noqa: BLE001 - surfaced to the consumer
                payload = _ProducerError(err)
            # terminal marker (end-of-stream or the captured error): the
            # consumer is guaranteed to unblock on its next get()
            put_until_stopped(payload)

        worker = threading.Thread(target=produce, daemon=True, name="tm-tpu-device-feed")
        worker.start()
        try:
            while True:
                item = staged.get()
                if item is _DONE:
                    return
                if isinstance(item, _ProducerError):
                    raise item.error
                yield item
        finally:
            stop.set()  # consumer done/abandoned: unblock a put-blocked producer
