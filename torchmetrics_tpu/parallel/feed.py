# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Async double-buffered host→device batch feed.

A streaming evaluation that calls ``step(state, *batch)`` on host-resident
batches serializes two things that could overlap: the host→device transfer
of batch k+1 and the compiled step on batch k. JAX dispatch is asynchronous,
so overlap needs no threads — it needs the ``device_put`` of the NEXT batch
to be *issued* before the current batch is consumed. :class:`DeviceFeed`
does exactly that with a depth-bounded buffer (the classic double-buffer at
``depth=2``, the default):

::

    plan = suite.fused()
    for batch in DeviceFeed(batches):      # transfer k+1 overlaps step k
        plan.update(*batch)

``depth`` bounds device memory: at most ``depth`` staged batches are alive
at once. Tuples/lists/dicts of arrays transfer as one pytree; numpy inputs
upload, device-resident arrays pass through (a no-op ``device_put``).

This is the host-side half of the fused evaluation plane's feed path
(ISSUE 9); :meth:`FusedCollectionPlan.run_stream` wires it in.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Iterable, Iterator, Optional

import jax

__all__ = ["DeviceFeed"]


class DeviceFeed:
    """Iterate ``batches`` with up to ``depth`` device transfers in flight.

    Args:
        batches: any iterable of batches (pytrees of arrays — tuples of
            ``(preds, target)`` in the common case).
        device: target device; ``None`` uses the default device.
        depth: how many batches to keep staged ahead of the consumer
            (``2`` = classic double buffering; ``1`` degenerates to eager
            per-batch transfer).
    """

    def __init__(self, batches: Iterable[Any], device: Optional[Any] = None, depth: int = 2) -> None:
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._batches = batches
        self._device = device
        self._depth = depth

    def _put(self, batch: Any) -> Any:
        # device_put on a pytree dispatches every leaf's transfer
        # asynchronously and returns immediately
        return jax.device_put(batch, self._device)

    def __iter__(self) -> Iterator[Any]:
        staged: deque = deque()
        for batch in self._batches:
            staged.append(self._put(batch))
            if len(staged) >= self._depth:
                yield staged.popleft()
        while staged:
            yield staged.popleft()
