# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""The ``Metric`` base runtime (layer L3).

Capability parity with reference ``src/torchmetrics/metric.py`` (the 1245-line
``Metric`` base), re-designed TPU-first:

- **States are immutable jnp arrays** (or lists of arrays for ``cat`` states),
  registered declaratively via :meth:`add_state` with a distributed reduction
  (reference ``metric.py:197-280``). Because arrays are immutable values, the
  reference's cache/restore dance for ``forward`` and ``sync``/``unsync``
  (``metric.py:316-399, 507-608``) collapses to keeping plain references.
- **Every kernel is pure & jit-safe.** ``update``/``compute`` on subclasses
  only do jnp ops + attribute assignment, so an entire update step can be
  traced: see :meth:`state_tree` / :meth:`load_state_tree` and
  ``torchmetrics_tpu.parallel`` for running updates under ``shard_map`` on a
  device mesh with collective reductions over ICI.
- **Distribution regimes**: in-step sharding (primary) needs no ``sync()`` at
  all; the multi-host replica regime reproduces the reference's
  ``sync``/``unsync``/``sync_context`` protocol over DCN.

The arithmetic-composition operator overloads (reference ``metric.py:972-1245``)
are provided by :class:`CompositionalMetric` at the bottom of this file.
"""
from __future__ import annotations

import functools
import inspect
import time
from copy import deepcopy
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.data import (
    _flatten,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from torchmetrics_tpu._reduction_names import VALID_REDUCTION_NAMES
from torchmetrics_tpu.obs import attribution as _obs_attr
from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import device as _obs_device
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.obs import trace as _obs_trace
from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.sketch.registry import is_sketch_state, merge_states, reduce_merge_states
from torchmetrics_tpu.robustness.sync_config import DEFAULT_SYNC_CONFIG, SyncConfig
from torchmetrics_tpu.utilities.distributed import distributed_available as _dist_available
from torchmetrics_tpu.utilities.distributed import gather_all_arrays
from torchmetrics_tpu.utilities.exceptions import SyncError, SyncWarning, TorchMetricsUserError
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def jit_distributed_available() -> bool:
    """Probe used as default ``distributed_available_fn`` (reference ``metric.py:46-48``)."""
    return _dist_available()


import contextlib as _contextlib
import os as _os

# read once: profiling is an operator decision made before the process starts
_PROFILE_ENABLED = _os.environ.get("TM_TPU_PROFILE", "0") == "1"
_NULL_CONTEXT = _contextlib.nullcontext()


def _trace_annotation(obj: Any, phase: str):
    """``jax.profiler`` trace annotation around update/compute (SURVEY §5.1:
    the reference has no in-repo tracing; profiler hooks are the TPU-native
    observability analogue). Enabled with ``TM_TPU_PROFILE=1`` **set before
    the library is imported** (read once at import; a per-call env lookup on
    every update would tax the hot path) — free when off.
    """
    if not _PROFILE_ENABLED:
        return _NULL_CONTEXT
    return jax.profiler.TraceAnnotation(f"{type(obj).__name__}.{phase}")


_REDUCTION_MAP: Dict[str, Optional[Callable]] = {
    "sum": dim_zero_sum,
    "mean": dim_zero_mean,
    "cat": dim_zero_cat,
    "min": dim_zero_min,
    "max": dim_zero_max,
    # sketch states: reduce a per-rank/per-device sequence by pairwise merge
    "merge": reduce_merge_states,
}
# the canonical name list (shared with metriclint's ML003) and the map must
# agree — a reduction added to one without the other fails here at import
assert tuple(_REDUCTION_MAP) == VALID_REDUCTION_NAMES, (
    f"_REDUCTION_MAP keys {tuple(_REDUCTION_MAP)} drifted from"
    f" _reduction_names.VALID_REDUCTION_NAMES {VALID_REDUCTION_NAMES}"
)


class Metric:
    """Base class for all metrics (reference ``metric.py:51``).

    Subclasses implement ``update(self, ...)`` and ``compute(self)`` using
    states declared with :meth:`add_state`; everything else — accumulation
    bookkeeping, ``forward`` dual-return, reset, distributed sync, state-dict
    serialization, arithmetic composition — is generic code driven by the
    state registry.
    """

    __jit_unused_properties__: List[str] = ["is_differentiable"]
    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False

    #: extra host-side (non-array) attributes the sharded regime must
    #: snapshot/restore around traced updates (e.g. ``Running._num_vals_seen``)
    _host_counters: Tuple[str, ...] = ()

    #: set to an explanatory string on metrics whose ``update`` cannot run
    #: under a traced ``parallel.sharded_update`` step (e.g. per-update host
    #: randomness); the sharded regime raises it instead of mistracing
    _sharded_update_unsupported: Optional[str] = None

    #: False on wrappers that consume child-metric state per update event and
    #: reset the child (``Running``): the sharded fold then leaves the
    #: children untouched, exactly like the replicated path does
    _sharded_fold_children: bool = True

    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None

    def __init__(self, **kwargs: Any) -> None:
        # config kwargs (reference ``metric.py:115-150``), strict unknown-kwarg error
        self.compute_on_cpu = kwargs.pop("compute_on_cpu", False)
        if not isinstance(self.compute_on_cpu, bool):
            raise ValueError(f"Expected keyword argument `compute_on_cpu` to be a `bool` but got {self.compute_on_cpu}")
        self.dist_sync_on_step = kwargs.pop("dist_sync_on_step", False)
        if not isinstance(self.dist_sync_on_step, bool):
            raise ValueError(f"Expected keyword argument `dist_sync_on_step` to be a `bool` but got {self.dist_sync_on_step}")
        self.process_group = kwargs.pop("process_group", None)
        self.dist_sync_fn = kwargs.pop("dist_sync_fn", None)
        if self.dist_sync_fn is not None and not callable(self.dist_sync_fn):
            raise ValueError(f"Expected keyword argument `dist_sync_fn` to be an callable function but got {self.dist_sync_fn}")
        self.distributed_available_fn = kwargs.pop("distributed_available_fn", None) or jit_distributed_available
        self.sync_on_compute = kwargs.pop("sync_on_compute", True)
        if not isinstance(self.sync_on_compute, bool):
            raise ValueError(f"Expected keyword argument `sync_on_compute` to be a `bool` but got {self.sync_on_compute}")
        self.compute_with_cache = kwargs.pop("compute_with_cache", True)
        if not isinstance(self.compute_with_cache, bool):
            raise ValueError(f"Expected keyword argument `compute_with_cache` to be a `bool` but got {self.compute_with_cache}")
        self.sync_config = kwargs.pop("sync_config", None)
        if self.sync_config is not None and not isinstance(self.sync_config, SyncConfig):
            raise ValueError(f"Expected keyword argument `sync_config` to be a `SyncConfig` but got {self.sync_config}")
        if kwargs:
            kwargs_ = [f"`{a}`" for a in sorted(kwargs)]
            raise ValueError(f"Unexpected keyword arguments: {', '.join(kwargs_)}")

        self._device = None  # lazily resolved jax.Device
        self._dtype = jnp.float32

        # state registry (reference ``metric.py:165-167``)
        self._defaults: Dict[str, Union[Array, List]] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Union[str, Callable, None]] = {}

        self._update_count = 0
        self._computed: Any = None
        self._to_sync = self.sync_on_compute
        self._should_unsync = True
        self._enable_grad = False

        # pending in-graph telemetry (obs/device.py): accumulated as device
        # arrays by the compiled update paths, drained into device.* gauges
        # only at compute()/sync() boundaries — never per batch
        self._device_telemetry: Optional[Any] = None

        # sync bookkeeping
        self._is_synced = False
        self._cache: Optional[Dict[str, Any]] = None

        # wrap user update/compute with bookkeeping (reference ``metric.py:476, 610``)
        self._rewrap()

    # ------------------------------------------------------------------ wrap
    def _rewrap(self) -> None:
        if getattr(self, "_guard_policy", None) is not None:
            # StateGuard-enabled metric (robustness/guard.py): the guarded
            # closure replaces the raw update INSIDE the transactional wrapper,
            # so pickle/__setstate__ round-trips re-install the guard
            from torchmetrics_tpu.robustness.guard import _guard_wrap_update

            self.update: Callable[..., None] = self._wrap_update(_guard_wrap_update(self))  # type: ignore[method-assign]
        else:
            self.update = self._wrap_update(self.__class__.update.__get__(self))  # type: ignore[method-assign]
        self.compute: Callable[..., Any] = self._wrap_compute(self.__class__.compute.__get__(self))  # type: ignore[method-assign]

    def domain_contract(self) -> Optional[Any]:
        """Input-domain contract for the StateGuard plane, or ``None``.

        Families whose ``update`` consumes float predictions override this to
        return a :class:`~torchmetrics_tpu.robustness.guard.DomainContract`
        describing per-argument validity (finite, probs in [0, 1], labels <
        num_classes) — compiled into the update step by
        :func:`~torchmetrics_tpu.robustness.guard.enable_guard`. Metriclint
        ML013 flags float-prediction metrics that leave this unimplemented.
        """
        return None

    def __getstate__(self) -> Dict[str, Any]:
        """Drop wrapped closures for pickling (reference ``metric.py:713``)."""
        return {k: v for k, v in self.__dict__.items() if k not in ("update", "compute")}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self._rewrap()

    # ----------------------------------------------------------------- state
    def add_state(
        self,
        name: str,
        default: Union[Array, list, float, int],
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
    ) -> None:
        """Register a metric state (reference ``metric.py:197-280``).

        ``default`` must be an array (fixed-shape accumulator), an empty
        list (append/``cat`` state), or — with ``dist_reduce_fx="merge"`` — a
        registered mergeable sketch state (``torchmetrics_tpu.sketch``).
        ``dist_reduce_fx`` is one of the names in ``_REDUCTION_MAP``, a custom
        callable, or ``None``.
        """
        if dist_reduce_fx == "merge":
            if not is_sketch_state(default):
                raise ValueError(
                    f"dist_reduce_fx='merge' requires the default of state {name!r} to be a registered"
                    " mergeable sketch state (see torchmetrics_tpu.sketch.register_sketch_state),"
                    f" got {type(default).__name__}"
                )
        elif is_sketch_state(default):
            raise ValueError(
                f"state {name!r} holds a {type(default).__name__} sketch state — it must be registered"
                " with dist_reduce_fx='merge' (any other reduction would mangle the pytree)"
            )
        elif not isinstance(default, list) or default:
            if isinstance(default, (int, float)):
                default = jnp.array(default, dtype=self._dtype if isinstance(default, float) else None)
            if not isinstance(default, (jnp.ndarray, np.ndarray, jax.Array)):
                raise ValueError("state variable must be an array or any empty list (where you can append arrays)")
            # `jnp.array` (not `asarray`): a zero-copy view of a caller-owned
            # numpy buffer registered as a state default would be overwritten
            # in place if that state is ever donated — copy at the trust
            # boundary (ML009)
            default = jnp.array(default)
            if getattr(default, "weak_type", False):
                # Strengthen the dtype: a weak-typed f32 accumulator (e.g.
                # `jnp.asarray(0.0)`) silently DEGRADES to bf16 on its first
                # `state + bf16_value` update (weak types defer to the other
                # operand), and every later batch then accumulates in ~3
                # decimal digits. A committed dtype makes f32 accumulation a
                # hard boundary for low-precision inputs.
                default = jnp.array(default, dtype=default.dtype)
        if dist_reduce_fx is not None and not (dist_reduce_fx in _REDUCTION_MAP or callable(dist_reduce_fx)):
            # generated from the live map so the message can never drift from
            # what the runtime actually accepts (it did once, pre-"merge")
            valid = ", ".join(repr(name_) for name_ in _REDUCTION_MAP)
            raise ValueError(f"`dist_reduce_fx` must be callable or one of [{valid}, None]")
        if name in ("update", "compute", "forward", "reset"):
            raise ValueError(f"The name `{name}` is reserved and cannot be used for a metric state")

        self._defaults[name] = deepcopy(default) if isinstance(default, list) else default
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx
        setattr(self, name, [] if isinstance(default, list) else default)

    @property
    def metric_state(self) -> Dict[str, Union[Array, List[Array]]]:
        """Current values of all registered states."""
        return {attr: getattr(self, attr) for attr in self._defaults}

    def state_tree(self, include_count: bool = False) -> Dict[str, Any]:
        """The state registry as a pytree — the bridge into jitted code.

        With ``include_count=True`` the tree also carries the update counter
        under the reserved key ``"_update_count"``, symmetrically with what
        :meth:`load_state_tree` accepts — so checkpoint/fold call sites never
        reach into the private counter by hand.
        """
        tree = {attr: getattr(self, attr) for attr in self._defaults}
        if include_count:
            tree["_update_count"] = self._update_count
        return tree

    def state_spec(self) -> Dict[str, Any]:
        """Declared schema of every state plus a stable registry fingerprint.

        Returns ``{"states": {name: StateSpec}, "fingerprint": str,
        "_update_count": int}`` — the contract :meth:`load_state_tree`
        validates restores against and :meth:`save_checkpoint` embeds so
        orbax/msgpack round-trips are self-validating.
        """
        from torchmetrics_tpu.robustness.spec import build_state_specs, spec_fingerprint

        return {
            "states": build_state_specs(self),
            "fingerprint": spec_fingerprint(self),
            "_update_count": self._update_count,
        }

    def load_state_tree(self, tree: Dict[str, Any], strict: bool = True) -> None:
        """Validate and install a pytree of (possibly traced) values as the
        current state.

        Every leaf is checked against the :meth:`add_state` registry — key
        set, list-vs-array kind, dtype, shape compatibility — and a violation
        raises :class:`~torchmetrics_tpu.utilities.exceptions.StateRestoreError`
        naming the state and expected-vs-got, *before* any state is touched.
        ``strict=False`` tolerates missing/unknown keys and coerces safe
        dtype widenings only. The reserved key ``"_update_count"`` (threaded
        by ``parallel.make_jit_update`` so ``"mean"`` states merge as a
        weighted running average) restores the update counter instead of a
        state.
        """
        from torchmetrics_tpu.robustness.spec import validate_state_tree

        tree = dict(tree)
        count = tree.pop("_update_count", None)
        validated = validate_state_tree(self, tree, strict=strict)
        for attr, value in validated.items():
            setattr(self, attr, value)
        if count is not None:
            self._update_count = int(count)

    def _install_state_tree(self, tree: Dict[str, Any]) -> None:
        """Install a tree WITHOUT validation — only for trees this metric
        produced itself (forward/unsync snapshots, sync rollback) or that were
        validated moments ago (checkpoint phase 2): self-snapshots are valid
        by construction and these restores sit on per-batch hot paths."""
        for attr, value in tree.items():
            if attr == "_update_count":
                self._update_count = int(value)
            else:
                setattr(self, attr, value)

    def _copy_state_dict(self) -> Dict[str, Any]:
        """Snapshot the current state. Arrays are immutable so refs suffice;
        list states need a shallow copy (reference ``metric.py:336``)."""
        return {attr: list(v) if isinstance(v, list) else v for attr, v in self.state_tree().items()}

    def _fold_sharded_state(self, part: Dict[str, Any], prev_count: int) -> None:
        """Fold one merged sharded-update event (``parallel.sharded_update``)
        into the live state.

        ``part`` is this metric's slice of the mesh-reduced state pytree — the
        state one ``update`` over the FULL (unsharded) batch would have
        produced. The default folds it with the declared reductions, weighting
        ``"mean"`` states by the running update count (reference
        ``metric.py:317``). Wrappers whose states are indexed by update event
        rather than accumulated (``Running``'s window slots) override this.
        """
        if prev_count == 0:
            self.load_state_tree(part)
            return
        from torchmetrics_tpu.parallel.sharded import tree_merge

        self.load_state_tree(tree_merge(self._reductions, self.state_tree(), part, weight_a=prev_count, weight_b=1))

    # ---------------------------------------------------------------- update
    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            self._computed = None
            # updates are transactional: an exception mid-update must not
            # leave the count advanced over half-applied state (a checkpoint
            # of that pair would silently skew every "mean" reduction), so
            # count AND states roll back together. The snapshot is O(#states),
            # NOT O(stream): arrays are immutable (a ref suffices) and list
            # ("cat") states are append-only by the add_state contract, so a
            # (ref, len) pair rolls them back by truncation — whether the
            # update appended in place or replaced the attribute.
            prior_state = {
                attr: (v, len(v)) if isinstance(v, list) else v for attr, v in self.state_tree().items()
            }
            self._update_count += 1
            try:
                # disabled-tracing path: a single module-level flag check — the
                # span (and its tag dict) is only ever allocated inside the branch
                if _obs_trace.ENABLED:
                    with _obs_trace.span("metric.update", metric=type(self).__name__, n=self._update_count):
                        with _trace_annotation(self, "update"):
                            update(*args, **kwargs)
                else:
                    with _trace_annotation(self, "update"):
                        update(*args, **kwargs)
            except Exception:
                self._update_count -= 1
                for attr, prior in prior_state.items():
                    if isinstance(prior, tuple):
                        lst, length = prior
                        del lst[length:]  # undo in-place appends; no-op if replaced
                        setattr(self, attr, lst)
                    else:
                        setattr(self, attr, prior)
                raise
            if self.compute_on_cpu:
                self._move_list_states_to_cpu()
            if faults._ACTIVE:  # simulated preemption between COMPLETED updates (checkpoint drills)
                faults.fire("update.preempt")

        return wrapped_func

    def _move_list_states_to_cpu(self) -> None:
        """Offload list states to host memory (reference ``metric.py:500-505``)."""
        cpu = jax.devices("cpu")[0] if any(d.platform == "cpu" for d in jax.devices()) else None
        for key in self._defaults:
            current = getattr(self, key)
            if isinstance(current, list):
                setattr(self, key, [jax.device_put(c, cpu) if cpu is not None else np.asarray(c) for c in current])

    def update(self, *_: Any, **__: Any) -> None:  # pragma: no cover - abstract
        """Override in subclass: fold a batch into the metric state."""
        raise NotImplementedError

    # ---------------------------------------------------------------- compute
    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if self._device_telemetry is not None:
                # compute() is THE host-sync boundary: pending in-graph
                # telemetry becomes device.* gauges here (also on a
                # cache-served compute — the gauges must not go stale)
                _obs_device.drain_metric(self)
            if (_obs_trace.ENABLED or _obs_live.ENABLED) and self._should_unsync:
                # same boundary for cost attribution: state-bytes gauge +
                # ledger row. TOP-LEVEL computes only — forward's per-batch
                # detours (_should_unsync=False) run this wrapper on a
                # temporarily reset single-batch state, which must not
                # overwrite the real footprint
                _obs_attr.metric_boundary(self)
            if self._update_count == 0:
                rank_zero_warn(
                    f"The ``compute`` method of metric {self.__class__.__name__} was called before the ``update`` method"
                    " which may lead to errors, as metric states have not yet been updated.",
                    UserWarning,
                )
            if self._computed is not None:
                value = self._computed
            elif _obs_trace.ENABLED:
                with _obs_trace.span("metric.compute", metric=type(self).__name__, n=self._update_count), self.sync_context(
                    dist_sync_fn=self.dist_sync_fn,
                    should_sync=self._to_sync,
                    should_unsync=self._should_unsync,
                ), _trace_annotation(self, "compute"):
                    value = _squeeze_if_scalar(compute(*args, **kwargs))
            else:
                with self.sync_context(
                    dist_sync_fn=self.dist_sync_fn,
                    should_sync=self._to_sync,
                    should_unsync=self._should_unsync,
                ), _trace_annotation(self, "compute"):
                    value = _squeeze_if_scalar(compute(*args, **kwargs))
            if self.compute_with_cache:
                self._computed = value
            if _obs_trace.ENABLED and self._should_unsync:
                # costs.json emission only from a TOP-LEVEL compute, and only
                # now: the metric.compute/metric.sync spans just closed, so
                # the ledger includes this compute's own cost (forward's
                # per-batch detours run with _should_unsync=False and must
                # not rebuild the ledger per batch; collection members are
                # deferred and emitted once by the collection)
                _obs_attr.maybe_emit()
            return value

        return wrapped_func

    def compute(self) -> Any:  # pragma: no cover - abstract
        """Override in subclass: finalize the metric value from the state."""
        raise NotImplementedError

    # ---------------------------------------------------------------- forward
    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate globally AND return the batch-local value (reference ``metric.py:283-314``)."""
        if self._is_synced:
            raise TorchMetricsUserError("The Metric shouldn't be synced when performing ``forward``")
        full = self.full_state_update or self.full_state_update is None or self.dist_sync_on_step
        if _obs_trace.ENABLED:
            with _obs_trace.span("metric.forward", metric=type(self).__name__, full_state=bool(full)):
                if full:
                    return self._forward_full_state_update(*args, **kwargs)
                return self._forward_reduce_state_update(*args, **kwargs)
        if full:
            return self._forward_full_state_update(*args, **kwargs)
        return self._forward_reduce_state_update(*args, **kwargs)

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Double-update path (reference ``metric.py:316-359``); states being
        immutable makes the snapshot free."""
        self.update(*args, **kwargs)
        _update_count = self._update_count
        _device_telemetry = self._device_telemetry  # reset() inside the detour must not drop it
        self._to_sync = self.dist_sync_on_step
        _temp_compute_with_cache = self.compute_with_cache
        self.compute_with_cache = False
        self._should_unsync = False

        cache = self._copy_state_dict()
        self.reset()
        self.update(*args, **kwargs)
        batch_val = self.compute()

        # restore context (self-snapshot: trusted installer, no validation)
        self._install_state_tree(cache)
        self._update_count = _update_count
        self._device_telemetry = _device_telemetry
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self.compute_with_cache = _temp_compute_with_cache
        self._computed = None
        self._is_synced = False
        return batch_val

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Single-update path (reference ``metric.py:361-399``): compute the
        batch value on a fresh state, then merge the previous global state in."""
        global_state = self._copy_state_dict()
        _update_count = self._update_count
        _device_telemetry = self._device_telemetry  # reset() below must not drop pending telemetry
        self.reset()

        self._to_sync = self.dist_sync_on_step
        _temp_compute_with_cache = self.compute_with_cache
        self.compute_with_cache = False
        self._should_unsync = False

        self.update(*args, **kwargs)
        self._update_count = _update_count + 1
        batch_val = self.compute()

        self._reduce_states(global_state)

        self._device_telemetry = _device_telemetry
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self.compute_with_cache = _temp_compute_with_cache
        self._computed = None
        self._is_synced = False
        return batch_val

    def _reduce_states(self, incoming_state: Dict[str, Any]) -> None:
        """Merge an incoming (older global) state into the current (batch)
        state, per each state's declared reduction (reference ``metric.py:401-433``)."""
        for attr in self._defaults:
            local_state = getattr(self, attr)
            global_state = incoming_state[attr]
            reduce_fn = self._reductions[attr]
            if reduce_fn == "sum":
                reduced = global_state + local_state
            elif reduce_fn == "mean":
                reduced = ((self._update_count - 1) * global_state + local_state) / self._update_count
            elif reduce_fn == "max":
                reduced = jnp.maximum(global_state, local_state)
            elif reduce_fn == "min":
                reduced = jnp.minimum(global_state, local_state)
            elif reduce_fn == "merge":
                reduced = merge_states(global_state, local_state)
            elif reduce_fn == "cat":
                if isinstance(global_state, list):
                    reduced = global_state + local_state
                else:
                    reduced = jnp.concatenate([jnp.atleast_1d(global_state), jnp.atleast_1d(local_state)])
            elif reduce_fn is None and isinstance(global_state, list):
                reduced = _flatten([global_state, local_state])
            elif reduce_fn is None:
                reduced = jnp.stack([global_state, local_state])
            elif callable(reduce_fn):
                reduced = reduce_fn(jnp.stack([jnp.asarray(global_state), jnp.asarray(local_state)]))
            else:
                raise TypeError(f"Unsupported reduce_fn: {reduce_fn}")
            setattr(self, attr, reduced)

    # ------------------------------------------------------------------ sync
    def _sync_dist(self, dist_sync_fn: Callable = gather_all_arrays, process_group: Optional[Any] = None) -> None:
        """Gather every state from all processes and apply its reduction
        (reference ``metric.py:435-474``)."""
        input_dict = {attr: getattr(self, attr) for attr in self._reductions}
        for attr, reduction_fn in self._reductions.items():
            if reduction_fn == "cat" and isinstance(input_dict[attr], list) and len(input_dict[attr]) > 1:
                input_dict[attr] = [dim_zero_cat(input_dict[attr])]
            if reduction_fn == "cat" and isinstance(input_dict[attr], list) and len(input_dict[attr]) == 0:
                # rank with no data: contribute an empty tensor (reference ``metric.py:443-450``)
                input_dict[attr] = [jnp.zeros((0,), dtype=self._dtype)]

        if _obs_trace.ENABLED or _obs_live.ENABLED:
            # the payload this rank contributes to the gather (nbytes is
            # array metadata — no device sync happens here)
            _obs_attr.publish_sync_bytes(self, input_dict)
        output_dict: Dict[str, Any] = {}
        for attr, value in input_dict.items():
            if faults._ACTIVE:  # mid-sync fault point: earlier states are already gathered
                faults.fire("sync.state_gather")
            group = self.process_group if process_group is None else process_group
            if self._reductions[attr] == "merge":
                # sketch state: gather leaf-wise (each leaf is a fixed-shape
                # array, so it rides the same pad/trim array gather as every
                # other state), then transpose to one state pytree per rank
                leaves, treedef = jax.tree_util.tree_flatten(value)
                gathered_leaves = [dist_sync_fn(leaf, group=group) for leaf in leaves]
                n_ranks = len(gathered_leaves[0]) if gathered_leaves else 1
                output_dict[attr] = [
                    treedef.unflatten([g[r] for g in gathered_leaves]) for r in range(n_ranks)
                ]
            elif isinstance(value, list):
                output_dict[attr] = [dist_sync_fn(v, group=group) for v in value]
            else:
                output_dict[attr] = dist_sync_fn(value, group=group)

        for attr, reduction_fn in self._reductions.items():
            if faults._ACTIVE:  # mid-apply fault point: earlier states are already overwritten
                faults.fire("sync.state_apply")
            gathered = output_dict[attr]
            if reduction_fn == "merge":
                if faults._ACTIVE:  # deterministic corrupt-payload drill (lockstep on all ranks)
                    idx = faults.corrupt_index("sync.sketch_state", len(gathered))
                    if idx is not None:
                        gathered = list(gathered)
                        gathered[idx] = _structurally_corrupt_state(gathered[idx])
                self._validate_merge_gather(attr, input_dict[attr], gathered)
                setattr(self, attr, reduce_merge_states(gathered))
                continue
            if isinstance(gathered, list) and len(gathered) == 0:
                setattr(self, attr, [])
                continue
            if isinstance(gathered[0], list):
                gathered = _flatten(gathered)
            else:
                gathered = jnp.stack([jnp.asarray(g) for g in gathered])
            if isinstance(reduction_fn, str):
                reduction_fn = _REDUCTION_MAP[reduction_fn]
            if not (callable(reduction_fn) or reduction_fn is None):
                raise TypeError("reduction_fn must be callable or None")
            reduced = reduction_fn(gathered) if reduction_fn is not None else gathered
            setattr(self, attr, reduced)

    def _validate_merge_gather(self, attr: str, template: Any, gathered: Sequence[Any]) -> None:
        """Structurally validate every rank's gathered sketch state against
        the local one BEFORE merging: a corrupt payload (wrong class, missing
        leaf, reshaped/re-typed leaf) raises :class:`SyncError` naming the
        state and the offending rank instead of detonating inside the merge
        (or, worse, silently merging garbage into every rank's result)."""
        t_leaves, t_def = jax.tree_util.tree_flatten(template)
        for rank, state in enumerate(gathered):
            if type(state) is not type(template):
                raise SyncError(
                    f"merge-state gather: state {attr!r} from rank {rank} has class"
                    f" {type(state).__name__}, expected {type(template).__name__} — corrupt payload"
                )
            leaves, treedef = jax.tree_util.tree_flatten(state)
            if treedef != t_def:
                raise SyncError(
                    f"merge-state gather: state {attr!r} from rank {rank} has pytree structure"
                    f" {treedef}, expected {t_def} — corrupt payload"
                )
            for got, want in zip(leaves, t_leaves):
                got, want = jnp.asarray(got), jnp.asarray(want)
                if got.shape != want.shape or got.dtype != want.dtype:
                    raise SyncError(
                        f"merge-state gather: state {attr!r} from rank {rank} has a leaf of"
                        f" shape {got.shape}/{got.dtype}, expected {want.shape}/{want.dtype} —"
                        " corrupt payload"
                    )

    def _sync_dist_bounded(self, dist_sync_fn: Callable, process_group: Optional[Any], timeout_s: Optional[float]) -> None:
        """Run ``_sync_dist``, optionally under a wall-clock budget.

        With a timeout the collectives run on a daemon worker thread and a
        straggler raises :class:`SyncError` instead of hanging forever. The
        abandoned attempt cannot be cancelled — if it ever completes it may
        still write states, which the caller's cache-restore then overwrites;
        a timed-out group should be considered poisoned (see ``SyncConfig``).
        """
        if not timeout_s:
            self._sync_dist(dist_sync_fn, process_group=process_group)
            return
        import threading

        box: Dict[str, Any] = {}

        def _runner() -> None:
            try:
                self._sync_dist(dist_sync_fn, process_group=process_group)
            except BaseException as err:  # surface EVERYTHING to the waiting thread
                box["err"] = err

        worker = threading.Thread(target=_runner, daemon=True, name=f"tm-tpu-sync-{type(self).__name__}")
        worker.start()
        worker.join(timeout_s)
        if worker.is_alive():
            raise SyncError(
                f"{type(self).__name__}.sync() timed out after {timeout_s}s — straggler rank or lost host?"
            )
        if "err" in box:
            raise box["err"]

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
        sync_config: Optional[SyncConfig] = None,
    ) -> None:
        """Sync state across processes (reference ``metric.py:507-549``),
        fault-tolerantly.

        Attempts are governed by ``sync_config`` (argument, else the metric's
        ``sync_config`` kwarg, else :data:`DEFAULT_SYNC_CONFIG`): each failed
        attempt rolls the states back to the pre-sync cache — a mid-gather
        failure can never leave the metric half-synced — then retries with
        exponential backoff. Exhausted attempts raise :class:`SyncError`, or,
        with ``on_error="local"``, degrade to the local-only state with a
        single :class:`SyncWarning` so best-effort eval logging keeps flowing.
        """
        if self._device_telemetry is not None:
            # sync is the other sanctioned host boundary for device telemetry
            _obs_device.drain_metric(self)
        if (_obs_trace.ENABLED or _obs_live.ENABLED) and should_sync and self._should_unsync:
            # pre-sync state footprint: the bytes about to ride the gather.
            # forward's detour computes reach here on a temporarily reset
            # single-batch state (should_sync=False normally, True under
            # dist_sync_on_step) — not a boundary either way
            _obs_attr.metric_boundary(self)
        if _obs_trace.ENABLED:
            with _obs_trace.span("metric.sync", metric=type(self).__name__, n=self._update_count):
                return self._sync_impl(dist_sync_fn, process_group, should_sync, distributed_available, sync_config)
        return self._sync_impl(dist_sync_fn, process_group, should_sync, distributed_available, sync_config)

    def _sync_impl(
        self,
        dist_sync_fn: Optional[Callable],
        process_group: Optional[Any],
        should_sync: bool,
        distributed_available: Optional[Callable],
        sync_config: Optional[SyncConfig],
    ) -> None:
        if self._is_synced and should_sync:
            raise TorchMetricsUserError("The Metric has already been synced.")
        if distributed_available is None and self.distributed_available_fn is not None:
            distributed_available = self.distributed_available_fn
        is_distributed = distributed_available() if callable(distributed_available) else None
        if not should_sync or not is_distributed:
            return
        if dist_sync_fn is None:
            dist_sync_fn = gather_all_arrays
        cfg = sync_config or self.sync_config or DEFAULT_SYNC_CONFIG
        # cache prior state so accumulation can continue locally after unsync
        # AND so any failed attempt can roll back cleanly
        self._cache = self._copy_state_dict()
        group = process_group or self.process_group
        last_err: Optional[BaseException] = None
        for attempt in range(cfg.attempts):
            try:
                if faults._ACTIVE:
                    faults.fire("sync.attempt")
                # the sync health counters also feed the live plane's
                # liveness derivation (obs/live.py), so they fire when EITHER
                # recorder is on — still nothing on the all-off default path
                if _obs_trace.ENABLED or _obs_live.ENABLED:
                    _obs_counters.inc("metric.sync.attempt")
                self._sync_dist_bounded(dist_sync_fn, group, cfg.timeout_s)
                self._is_synced = True
                return
            except Exception as err:
                # roll back any partial overwrite before retrying/surfacing;
                # fresh list copies so a later attempt cannot alias the cache
                self._install_state_tree({k: list(v) if isinstance(v, list) else v for k, v in self._cache.items()})
                last_err = err
                if _obs_trace.ENABLED or _obs_live.ENABLED:
                    _obs_counters.inc("metric.sync.rollback")
                if _obs_trace.ENABLED:
                    _obs_trace.instant(
                        "metric.sync.rollback",
                        metric=type(self).__name__,
                        attempt=attempt,
                        error=type(err).__name__,
                        reason=str(err)[:200],
                    )
                if attempt + 1 < cfg.attempts:
                    backoff_s = cfg.backoff(attempt)
                    if _obs_trace.ENABLED:
                        _obs_trace.instant(
                            "metric.sync.retry", metric=type(self).__name__, attempt=attempt + 1, backoff_s=backoff_s
                        )
                    time.sleep(backoff_s)
        self._cache = None
        if cfg.on_error == "local":
            if _obs_trace.ENABLED or _obs_live.ENABLED:
                _obs_counters.inc("metric.sync.degrade")
            if _obs_trace.ENABLED:
                _obs_trace.instant(
                    "metric.sync.degrade",
                    metric=type(self).__name__,
                    attempts=cfg.attempts,
                    error=type(last_err).__name__,
                )
            rank_zero_warn(
                f"{type(self).__name__}.sync() failed after {cfg.attempts} attempt(s) ({last_err}); falling back"
                " to local-only state (SyncConfig.on_error='local') — reported values cover this process only.",
                SyncWarning,
            )
            return
        if _obs_trace.ENABLED or _obs_live.ENABLED:
            _obs_counters.inc("metric.sync.failure")
        if _obs_trace.ENABLED:
            _obs_trace.instant(
                "metric.sync.failure",
                metric=type(self).__name__,
                attempts=cfg.attempts,
                error=type(last_err).__name__,
            )
        raise SyncError(
            f"{type(self).__name__}.sync() failed after {cfg.attempts} attempt(s): {last_err}"
        ) from last_err

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore the cached pre-sync local state (reference ``metric.py:551-571``)."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise TorchMetricsUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise TorchMetricsUserError("The internal cache should exist to unsync the Metric.")
        self._install_state_tree(self._cache)  # self-snapshot: trusted
        self._is_synced = False
        self._cache = None

    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> "_SyncContext":
        """Context manager: sync on enter, unsync on exit (reference ``metric.py:573-608``)."""
        return _SyncContext(self, dist_sync_fn, process_group, should_sync, should_unsync, distributed_available)

    # ------------------------------------------------------------------ reset
    def reset(self) -> None:
        """Reset all states to their defaults (reference ``metric.py:692``)."""
        if _obs_trace.ENABLED:
            with _obs_trace.span("metric.reset", metric=type(self).__name__):
                return self._reset_impl()
        self._reset_impl()

    def _reset_impl(self) -> None:
        self._update_count = 0
        self._computed = None
        self._device_telemetry = None
        for attr, default in self._defaults.items():
            if isinstance(default, list):
                setattr(self, attr, [])
            else:
                setattr(self, attr, default)
        self._cache = None
        self._is_synced = False

    def clone(self) -> "Metric":
        """Deep copy of the metric (reference ``metric.py:707``)."""
        return deepcopy(self)

    # -------------------------------------------------------------- serialization
    def save_checkpoint(self) -> Dict[str, Any]:
        """Snapshot the metric (wrapper children included) as one plain dict of
        host numpy arrays plus spec fingerprint, format version and update
        count — self-validating through orbax/msgpack/pickle round-trips.
        See :mod:`torchmetrics_tpu.robustness.checkpoint`."""
        from torchmetrics_tpu.robustness.checkpoint import save_checkpoint

        return save_checkpoint(self)

    def load_checkpoint(self, checkpoint: Dict[str, Any], strict: bool = True) -> None:
        """Validate a :meth:`save_checkpoint` dict end-to-end, then install it.

        A truncated/corrupted payload or a schema mismatch (e.g. different
        ``num_classes``) raises
        :class:`~torchmetrics_tpu.utilities.exceptions.StateRestoreError`
        naming the offending state, and the live metric keeps its previous
        state — never a half-restored metric.
        """
        from torchmetrics_tpu.robustness.checkpoint import load_checkpoint

        load_checkpoint(self, checkpoint, strict=strict)

    def state_dict(self, destination: Optional[Dict] = None, prefix: str = "", keep_vars: bool = False) -> Dict[str, Any]:
        """State-dict of persistent states as host numpy arrays (reference ``metric.py:858-890``)."""
        destination = {} if destination is None else destination
        for key in self._defaults:
            if not self._persistent[key]:
                continue
            current_val = getattr(self, key)
            if isinstance(current_val, list):
                destination[prefix + key] = [np.asarray(v) for v in current_val]
            elif is_sketch_state(current_val):
                destination[prefix + key] = jax.tree_util.tree_map(np.asarray, current_val)
            else:
                destination[prefix + key] = np.asarray(current_val)
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = True, prefix: str = "") -> None:
        """Restore states from a state-dict (reference ``metric.py:907-924``)."""
        for key in self._defaults:
            name = prefix + key
            if name in state_dict:
                value = state_dict[name]
                # `jnp.array` (not `asarray`): on CPU `asarray` can alias the
                # deserialized numpy buffer, and a later donated step would
                # overwrite memory JAX does not own — the PR-12 restore
                # corruption (ML009); copy on install
                if isinstance(value, list):
                    setattr(self, key, [jnp.array(v) for v in value])
                elif is_sketch_state(value):
                    setattr(self, key, jax.tree_util.tree_map(jnp.array, value))
                else:
                    setattr(self, key, jnp.array(value))
            elif strict and self._persistent[key]:
                raise KeyError(f"Missing key {name!r} in state_dict")

    def persistent(self, mode: bool = False) -> None:
        """Toggle persistence of all states (reference ``metric.py:853``)."""
        for key in self._persistent:
            self._persistent[key] = mode

    # ------------------------------------------------------------ device/dtype
    @property
    def device(self):
        """The device the metric states live on."""
        for v in self._defaults:
            current = getattr(self, v)
            if isinstance(current, jax.Array):
                return list(current.devices())[0]
            if isinstance(current, list) and current and isinstance(current[0], jax.Array):
                return list(current[0].devices())[0]
        return self._device or jax.devices()[0]

    @property
    def dtype(self):
        return self._dtype

    def to(self, device=None) -> "Metric":
        """Move all states to a device (reference ``metric.py:801-851`` ``_apply``)."""
        if device is None:
            return self
        self._device = device
        self._apply(lambda x: jax.device_put(x, device))
        return self

    def cpu(self) -> "Metric":
        return self.to(jax.devices("cpu")[0])

    def set_dtype(self, dst_type) -> "Metric":
        """Cast floating states to ``dst_type`` (reference ``metric.py:757-799``);
        arbitrary dtype casting is deliberately only available through this method."""
        self._dtype = jnp.dtype(dst_type)
        self._apply(lambda x: x.astype(dst_type) if jnp.issubdtype(x.dtype, jnp.floating) else x)
        for attr, default in self._defaults.items():
            if isinstance(default, jax.Array) and jnp.issubdtype(default.dtype, jnp.floating):
                self._defaults[attr] = default.astype(dst_type)
            elif is_sketch_state(default):
                self._defaults[attr] = jax.tree_util.tree_map(
                    lambda x: x.astype(dst_type) if jnp.issubdtype(x.dtype, jnp.floating) else x, default
                )
        return self

    def _apply(self, fn: Callable[[Array], Array]) -> None:
        for attr in self._defaults:
            current = getattr(self, attr)
            if isinstance(current, list):
                setattr(self, attr, [fn(jnp.asarray(c)) for c in current])
            elif is_sketch_state(current):
                setattr(self, attr, jax.tree_util.tree_map(fn, current))
            else:
                setattr(self, attr, fn(jnp.asarray(current)))

    # ----------------------------------------------------------------- sliced
    def sliced(self, *, num_cells: int, **kwargs: Any) -> Any:
        """Fan this metric out over up to ``num_cells`` cohort cells — one
        compiled dispatch per batch updates EVERY cohort's copy of the state
        (hashed slice table + a leading ``[num_cells]`` state axis; see
        :class:`~torchmetrics_tpu.parallel.sliced.SlicedPlan`)::

            plan = acc.sliced(num_cells=1024)
            plan.update(cohort_ids, preds, target)   # one dispatch, all cohorts
            per_cohort = plan.results()

        The metric is the pristine per-cell TEMPLATE (``reset()`` first);
        ``kwargs`` pass through to ``SlicedPlan`` (``cat_capacity``,
        ``example_batch``, ``donate``, ``mesh``, ``axis_name``,
        ``key_width``).
        """
        from torchmetrics_tpu.parallel.sliced import SlicedPlan

        return SlicedPlan(self, num_cells=num_cells, **kwargs)

    # --------------------------------------------------------------- plotting
    def plot(self, *args: Any, **kwargs: Any):
        """Plot a single or multiple values from the metric (reference ``metric.py:656-690``)."""
        return self._plot(*args, **kwargs)

    def _plot(self, val=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(
            val,
            ax=ax,
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name,
            name=self.__class__.__name__,
        )

    # ------------------------------------------------------------------- misc
    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs so only those in the update signature pass through
        (reference ``metric.py:926-945``)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = inspect.signature(self.__class__.update).parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        exists_var_keyword = any(v.kind == inspect.Parameter.VAR_KEYWORD for v in _sign_params.values())
        return kwargs if exists_var_keyword else filtered_kwargs

    def __hash__(self) -> int:
        """Hash on id + state contents (reference ``metric.py:947-960``)."""
        hash_vals: List[Any] = [self.__class__.__name__]
        for key in self._defaults:
            val = getattr(self, key)
            if isinstance(val, list):
                hash_vals.extend(np.asarray(v).tobytes() for v in val)
            elif is_sketch_state(val):
                hash_vals.extend(np.asarray(leaf).tobytes() for leaf in jax.tree_util.tree_leaves(val))
            else:
                hash_vals.append(np.asarray(val).tobytes())
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    def type(self, dst_type) -> "Metric":
        return self.set_dtype(dst_type)

    def float(self) -> "Metric":
        return self.set_dtype(jnp.float32)

    def double(self) -> "Metric":
        return self.set_dtype(jnp.float64)

    def half(self) -> "Metric":
        return self.set_dtype(jnp.float16)

    # --------------------------------------------------- composition operators
    # (reference ``metric.py:972-1107``)
    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, self, other)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, other, self)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, self, other)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, other, self)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, self, other)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, other, self)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, self, other)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, other, self)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, other, self)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, self, other)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, other, self)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, other, self)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, other, self)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, other, self)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.equal, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.not_equal, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less_equal, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __invert__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.logical_not, self, None)

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)


def _structurally_corrupt_state(state: Any) -> Any:
    """Test-only mutation used by the ``sync.sketch_state`` fault point: give
    the first leaf a trailing extra axis so structural validation trips."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    leaves[0] = jnp.zeros(tuple(jnp.asarray(leaves[0]).shape) + (2,), jnp.asarray(leaves[0]).dtype)
    return treedef.unflatten(leaves)


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


def _squeeze_if_scalar(data: Any) -> Any:
    from torchmetrics_tpu.utilities.data import _squeeze_if_scalar as _sq

    return _sq(data)


class _SyncContext:
    def __init__(self, metric: Metric, dist_sync_fn, process_group, should_sync, should_unsync, distributed_available) -> None:
        self.metric = metric
        self.kwargs = dict(
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            distributed_available=distributed_available,
        )
        self.should_unsync = should_unsync

    def __enter__(self) -> None:
        self.metric.sync(**self.kwargs)

    def __exit__(self, *exc: Any) -> None:
        self.metric.unsync(should_unsync=self.should_unsync and self.metric._is_synced)


class CompositionalMetric(Metric):
    """Lazy arithmetic composition of metrics (reference ``metric.py:1122-1245``)."""

    def __init__(self, operator: Callable, metric_a: Union[Metric, float, int, Array, None], metric_b: Union[Metric, float, int, Array, None]) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) else (jnp.asarray(metric_a) if metric_a is not None else None)
        self.metric_b = metric_b if isinstance(metric_b, Metric) else (jnp.asarray(metric_b) if metric_b is not None else None)

    def _sync_dist(self, dist_sync_fn=None, process_group=None) -> None:
        # No syncing required here: child metrics sync themselves (reference ``metric.py:1161``)
        pass

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._computed = None
            return None
        if val_b is None:
            if isinstance(self.metric_b, Metric):
                self._computed = None
                return None
            self._computed = self.op(val_a)
        else:
            self._computed = self.op(val_a, val_b)
        return self._computed

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()
        self._update_count = 0
        self._computed = None

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else 'op'}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

    def __hash__(self) -> int:
        return object.__hash__(self)
