# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Ready-made stream-target factories for ``metricserve``.

A wire ``create`` names its metric target declaratively — a
``module:callable`` path plus JSON kwargs (see
:func:`~torchmetrics_tpu.serve.stream.resolve_target`) — because a daemon
cannot receive live Python objects. These are the built-ins the docs, tests
and bench use; deployments register their own factories the same way (any
importable callable returning a ``Metric``, ``MetricCollection`` or
``SlicedPlan`` works).
"""
from __future__ import annotations

from typing import Any

__all__ = [
    "accuracy",
    "binary_accuracy",
    "binary_average_precision",
    "cardinality",
    "checked_binary_accuracy",
    "collection",
    "drift",
    "guarded_binary_accuracy",
    "guarded_mean_squared_error",
    "heavy_hitters",
    "quantile",
    "sliced_accuracy",
]


def accuracy(num_classes: int = 4, average: str = "micro") -> Any:
    """A plain ``MulticlassAccuracy`` — the simplest stream target."""
    from torchmetrics_tpu.classification import MulticlassAccuracy

    return MulticlassAccuracy(num_classes=num_classes, average=average, validate_args=False)


def binary_accuracy(threshold: float = 0.5) -> Any:
    """Elementwise (sum-state) binary accuracy — replica ``sync()`` folds it
    across ranks at the drain compute."""
    from torchmetrics_tpu.classification import BinaryAccuracy

    return BinaryAccuracy(threshold=threshold, validate_args=False)


def checked_binary_accuracy(threshold: float = 0.5) -> Any:
    """Binary accuracy WITH host-side argument validation: a target value
    outside ``{0, 1}`` raises in the worker. Shape/dtype-clean batches with
    bad values pass wire admission and kill the apply — the deterministic
    poison batch the dead-letter quarantine drills against."""
    from torchmetrics_tpu.classification import BinaryAccuracy

    return BinaryAccuracy(threshold=threshold, validate_args=True)


def guarded_binary_accuracy(threshold: float = 0.5, policy: str = "mask") -> Any:
    """Binary accuracy under the StateGuard (``robustness/guard.py``): the
    domain contract (finite preds in [0, 1], target in {0, 1}) is compiled
    into the update step. ``policy="mask"`` accumulates only valid rows,
    ``"reject"`` vetoes whole invalid batches, ``"propagate"`` only counts —
    the stream publishes the verdicts as ``guard.<stream>.*`` gauges."""
    from torchmetrics_tpu.classification import BinaryAccuracy
    from torchmetrics_tpu.robustness.guard import enable_guard

    return enable_guard(BinaryAccuracy(threshold=threshold, validate_args=False), policy=policy)


def guarded_mean_squared_error(policy: str = "propagate") -> Any:
    """MSE under the StateGuard — float error-sum state, so a propagated NaN
    frame actually poisons state and trips the in-program poison probe: the
    canonical target for the serve plane's known-good rollback drill."""
    from torchmetrics_tpu.regression.mse import MeanSquaredError
    from torchmetrics_tpu.robustness.guard import enable_guard

    return enable_guard(MeanSquaredError(), policy=policy)


def binary_average_precision() -> Any:
    """Cat (list-state) average precision — per-rank rows gather (pad/trim)
    across ranks at the drain compute."""
    from torchmetrics_tpu.classification import BinaryAveragePrecision

    return BinaryAveragePrecision(validate_args=False)


def quantile(q: float = 0.5, capacity: int = 256, levels: int = 14) -> Any:
    """Bounded-memory KLL quantile — the ``dist_reduce_fx="merge"`` regime;
    ranks pairwise-merge sketches at the drain compute."""
    from torchmetrics_tpu import Quantile

    return Quantile(q=q, capacity=capacity, levels=levels)


def collection(num_classes: int = 4) -> Any:
    """An accuracy + AUROC ``MetricCollection`` — pair with ``fused=True``
    for the one-dispatch evaluation plane."""
    from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassAUROC
    from torchmetrics_tpu.collections import MetricCollection

    return MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=num_classes, validate_args=False),
            "auroc": MulticlassAUROC(num_classes=num_classes, validate_args=False),
        }
    )


def drift(
    reference: Any = None,
    bins: int = 64,
    lo: float = 0.0,
    hi: float = 1.0,
    thresholds: Any = None,
    patience: int = 3,
    reference_checkpoint: Any = None,
    reference_path: Any = None,
    reference_state: Any = None,
) -> Any:
    """A :class:`~torchmetrics_tpu.drift.DriftScore` stream — live-window
    drift vs a pinned reference, published as ``drift.<stream>.*`` gauges
    that can floor ``/healthz``.

    All kwargs are wire-JSON-able: ``reference`` is a raw sample (list of
    floats) binned at ``bins/lo/hi``; ``reference_checkpoint`` is a path to
    a pickled PR-2 checkpoint payload to pin the reference from instead
    (``reference_path``/``reference_state`` narrow the lookup);
    ``thresholds`` maps score names to ``[warn, critical]`` pairs.
    """
    from torchmetrics_tpu.drift import DriftScore

    ckpt = None
    if reference_checkpoint is not None:
        import pickle

        with open(reference_checkpoint, "rb") as fh:
            ckpt = pickle.load(fh)
    if thresholds is not None:
        thresholds = {k: tuple(v) if isinstance(v, (list, tuple)) else v for k, v in dict(thresholds).items()}
    return DriftScore(
        reference=reference,
        bins=bins,
        lo=lo,
        hi=hi,
        thresholds=thresholds,
        patience=patience,
        reference_checkpoint=ckpt,
        reference_path=reference_path,
        reference_state=reference_state,
    )


def cardinality(precision: int = 12) -> Any:
    """A :class:`~torchmetrics_tpu.drift.Cardinality` stream — HyperLogLog
    distinct count of the streamed tags (``drift.<stream>.cardinality``
    gauge rides ``/metrics``)."""
    from torchmetrics_tpu.drift import Cardinality

    return Cardinality(precision=precision)


def heavy_hitters(depth: int = 4, width: int = 1024, k: int = 32) -> Any:
    """A :class:`~torchmetrics_tpu.drift.HeavyHitters` stream — top-``k``
    hot tags via Count-Min; query via stream snapshots/compute."""
    from torchmetrics_tpu.drift import HeavyHitters

    return HeavyHitters(depth=depth, width=width, k=k)


def sliced_accuracy(num_classes: int = 4, num_cells: int = 16, key_width: int = 1) -> Any:
    """A per-cohort accuracy ``SlicedPlan``; wire batches lead with the
    integer cohort-key column(s): ``[keys, preds, target]``."""
    from torchmetrics_tpu.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=num_classes, validate_args=False)
    return metric.sliced(num_cells=num_cells, key_width=key_width)
