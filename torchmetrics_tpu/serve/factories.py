# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Ready-made stream-target factories for ``metricserve``.

A wire ``create`` names its metric target declaratively — a
``module:callable`` path plus JSON kwargs (see
:func:`~torchmetrics_tpu.serve.stream.resolve_target`) — because a daemon
cannot receive live Python objects. These are the built-ins the docs, tests
and bench use; deployments register their own factories the same way (any
importable callable returning a ``Metric``, ``MetricCollection`` or
``SlicedPlan`` works).
"""
from __future__ import annotations

from typing import Any

__all__ = [
    "accuracy",
    "binary_accuracy",
    "binary_average_precision",
    "checked_binary_accuracy",
    "collection",
    "quantile",
    "sliced_accuracy",
]


def accuracy(num_classes: int = 4, average: str = "micro") -> Any:
    """A plain ``MulticlassAccuracy`` — the simplest stream target."""
    from torchmetrics_tpu.classification import MulticlassAccuracy

    return MulticlassAccuracy(num_classes=num_classes, average=average, validate_args=False)


def binary_accuracy(threshold: float = 0.5) -> Any:
    """Elementwise (sum-state) binary accuracy — replica ``sync()`` folds it
    across ranks at the drain compute."""
    from torchmetrics_tpu.classification import BinaryAccuracy

    return BinaryAccuracy(threshold=threshold, validate_args=False)


def checked_binary_accuracy(threshold: float = 0.5) -> Any:
    """Binary accuracy WITH host-side argument validation: a target value
    outside ``{0, 1}`` raises in the worker. Shape/dtype-clean batches with
    bad values pass wire admission and kill the apply — the deterministic
    poison batch the dead-letter quarantine drills against."""
    from torchmetrics_tpu.classification import BinaryAccuracy

    return BinaryAccuracy(threshold=threshold, validate_args=True)


def binary_average_precision() -> Any:
    """Cat (list-state) average precision — per-rank rows gather (pad/trim)
    across ranks at the drain compute."""
    from torchmetrics_tpu.classification import BinaryAveragePrecision

    return BinaryAveragePrecision(validate_args=False)


def quantile(q: float = 0.5, capacity: int = 256, levels: int = 14) -> Any:
    """Bounded-memory KLL quantile — the ``dist_reduce_fx="merge"`` regime;
    ranks pairwise-merge sketches at the drain compute."""
    from torchmetrics_tpu import Quantile

    return Quantile(q=q, capacity=capacity, levels=levels)


def collection(num_classes: int = 4) -> Any:
    """An accuracy + AUROC ``MetricCollection`` — pair with ``fused=True``
    for the one-dispatch evaluation plane."""
    from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassAUROC
    from torchmetrics_tpu.collections import MetricCollection

    return MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=num_classes, validate_args=False),
            "auroc": MulticlassAUROC(num_classes=num_classes, validate_args=False),
        }
    )


def sliced_accuracy(num_classes: int = 4, num_cells: int = 16, key_width: int = 1) -> Any:
    """A per-cohort accuracy ``SlicedPlan``; wire batches lead with the
    integer cohort-key column(s): ``[keys, preds, target]``."""
    from torchmetrics_tpu.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=num_classes, validate_args=False)
    return metric.sliced(num_cells=num_cells, key_width=key_width)
