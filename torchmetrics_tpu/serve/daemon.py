# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""The ``metricserve`` daemon: registry + control plane + ingest plane.

:class:`ServeDaemon` multiplexes many :class:`~torchmetrics_tpu.serve.stream.
Stream`\\ s under one base directory::

    <base_dir>/
      streams/<name>/spec.json    # the declarative StreamSpec — restart fuel
      streams/<name>/store/       # the stream's CheckpointStore
      streams/<name>/costs.json   # cost ledger, written at compute boundaries
      status/                     # live-plane status.rank<k>.json files

and exposes two planes:

- **control** — localhost HTTP (``/v1/streams`` CRUD + ingest/flush/drain,
  plus the repair verbs ``revive`` — half-open a parked stream's circuit
  breaker — and ``deadletter`` list/requeue/purge for the poison-batch
  quarantine; ``/healthz`` and ``/metrics`` riding the PR-7 publisher;
  health is the WORST stream via the ``serve.<name>.health_state`` gauges),
  port 0 by default so concurrent daemons never collide;
- **ingest** — a newline-JSON unix-socket fast path (one wire frame per
  line, blocking-with-deadline backpressure instead of HTTP 429 retries).

**Restart = resume.** ``start()`` re-creates every stream whose
``spec.json`` survives under ``streams/``; each evaluator restores through
the validate-all-then-apply ladder and the create/status responses carry
``next_seq`` so clients replay exactly the unpersisted suffix.

**Drain discipline.** ``shutdown(drain=True)`` (the SIGTERM path) stops
admitting, then drains streams **sequentially in sorted-name order** — on a
multi-host deployment every rank walks the same order, so the collective
sync inside each final ``compute()`` lines up across ranks — and finishes
with one final telemetry tick so the last ``status.rank<k>.json`` carries
the drain-final counters.

Chaos hooks: ``serve.accept`` fires on stream create, ``serve.ingest`` on
every admission, ``serve.drain`` at each stream drain (see
:mod:`torchmetrics_tpu.robustness.faults`).
"""
from __future__ import annotations

import json
import os
import shutil
import socketserver
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu.obs import attribution as _obs_attr
from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.serve import wire
from torchmetrics_tpu.serve.stream import Stream, StreamSpec

__all__ = ["ServeDaemon"]


class ServeDaemon:
    """One always-on eval service over one base directory.

    Args:
        base_dir: durable root (created on start); layout above.
        http: control-plane bind — ``"host:port"`` / ``":port"`` / int port;
            default ``127.0.0.1:0`` (ephemeral; read the bound address off
            :meth:`http_address`).
        socket_path: unix-socket ingest path, ``None`` disables the socket
            plane (HTTP ingest still works).
        publish: start the live plane (status files under
            ``<base_dir>/status``) if it is not already on; the daemon then
            owns the publisher and stops it (final tick included) at
            shutdown.
        rank: process rank label for stores/status (default auto-detect).
    """

    def __init__(
        self,
        base_dir: str,
        http: Any = ":0",
        socket_path: Optional[str] = None,
        publish: bool = True,
        rank: Optional[int] = None,
    ) -> None:
        self.base_dir = str(base_dir)
        self._http_spec = http
        self.socket_path = None if socket_path is None else str(socket_path)
        self._publish = bool(publish)
        self._rank = rank
        #: per-boot nonce stamped on every state export and on ``/healthz`` —
        #: a federation fold never mixes two boots' windows, and a restarted
        #: leaf's replayed prefix dedups against the epoch change
        self.epoch: Optional[str] = None
        self._streams: Dict[str, Stream] = {}
        self._creating: set = set()  # names reserved while their dir/store is built
        self._lock = threading.Lock()
        self._accepting = False
        self._owns_publisher = False
        self._http_server: Any = None
        self._http_thread: Optional[threading.Thread] = None
        self._sock_server: Any = None
        self._sock_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServeDaemon":
        # fresh epoch per boot — state exported before a crash can never be
        # confused with state exported after the restart's replay
        self.epoch = uuid.uuid4().hex[:12]
        os.makedirs(os.path.join(self.base_dir, "streams"), exist_ok=True)
        if self._publish and not _obs_live.ENABLED:
            _obs_live.enable(directory=os.path.join(self.base_dir, "status"), rank=self._rank)
            self._owns_publisher = True
        _obs_live.register_probe("metricserve", self._probe)
        self._restore_streams()
        self._accepting = True
        self._start_http()
        if self.socket_path is not None:
            self._start_socket()
        return self

    def _restore_streams(self) -> None:
        """Restart fuel: re-create every stream whose spec.json survives,
        sorted so multi-rank restarts open stores in the same order."""
        root = os.path.join(self.base_dir, "streams")
        for name in sorted(os.listdir(root)):
            spec_path = os.path.join(root, name, "spec.json")
            if not os.path.isfile(spec_path):
                continue
            with open(spec_path) as fh:
                spec = StreamSpec.from_wire(json.load(fh))
            stream = Stream(spec, os.path.join(root, name, "store"))
            stream.start()
            self._streams[spec.name] = stream

    def shutdown(self, drain: bool = True) -> Dict[str, Any]:
        """The SIGTERM path: stop admitting, drain every stream (sorted —
        deterministic collective order across ranks), emit per-stream costs,
        publish one final telemetry tick, then close the servers."""
        self._accepting = False
        results: Dict[str, Any] = {}
        with self._lock:
            streams = sorted(self._streams.items())
        for name, stream in streams:
            if drain:
                results[name] = stream.drain()
                self._emit_costs(name)
            else:
                stream.abandon()
        if self._owns_publisher:
            # the probe is still registered: the publisher's final tick
            # carries the drain-final serve.<name>.* gauges
            _obs_live.disable()
            self._owns_publisher = False
        _obs_live.unregister_probe("metricserve")
        self._stop_servers()
        return results

    def _stop_servers(self) -> None:
        for server, thread in ((self._http_server, self._http_thread), (self._sock_server, self._sock_thread)):
            if server is not None:
                server.shutdown()
                server.server_close()
                if thread is not None:
                    thread.join(timeout=10.0)
        self._http_server = self._http_thread = None
        self._sock_server = self._sock_thread = None
        if self.socket_path is not None:
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass

    # -------------------------------------------------------------- registry
    def create_stream(self, spec_obj: Dict[str, Any]) -> Dict[str, Any]:
        if not self._accepting:
            return wire.error("draining", "daemon is shutting down; no new streams")
        if faults._ACTIVE:
            faults.fire("serve.accept")
        try:
            spec = StreamSpec.from_wire(spec_obj)
        except (wire.WireError, ValueError, TypeError) as err:
            return wire.error("bad_request", str(err))
        stream_dir = os.path.join(self.base_dir, "streams", spec.name)
        # reserve the name under the lock, build the dir/store OUTSIDE it —
        # holding _lock across the spec write and Stream.start() would stall
        # every ingest/flush request behind this stream's disk I/O (ML012)
        with self._lock:
            if spec.name in self._streams or spec.name in self._creating:
                return wire.error("exists", f"stream {spec.name} already exists")
            self._creating.add(spec.name)
        try:
            os.makedirs(stream_dir, exist_ok=True)
            with open(os.path.join(stream_dir, "spec.json"), "w") as fh:
                json.dump(spec.to_wire(), fh, separators=(",", ":"))
            try:
                stream = Stream(spec, os.path.join(stream_dir, "store"))
                next_seq = stream.start()
            except Exception as err:
                shutil.rmtree(stream_dir, ignore_errors=True)
                return wire.error("bad_request", f"stream {spec.name} failed to open: {err}")
            with self._lock:
                self._streams[spec.name] = stream
        finally:
            with self._lock:
                self._creating.discard(spec.name)
        return wire.ok(stream=spec.name, next_seq=next_seq)

    def _get(self, name: str) -> Optional[Stream]:
        with self._lock:
            return self._streams.get(name)

    def ingest(
        self, name: str, seq: Any, batch: Any, *, block: bool = False, deadline_s: Optional[float] = None
    ) -> Dict[str, Any]:
        if not self._accepting:
            return wire.error("draining", "daemon is shutting down")
        stream = self._get(name)
        if stream is None:
            return wire.error("not_found", f"no stream named {name!r}")
        return stream.offer(seq, batch, block=block, deadline_s=deadline_s)

    def flush(self, name: str) -> Dict[str, Any]:
        stream = self._get(name)
        if stream is None:
            return wire.error("not_found", f"no stream named {name!r}")
        return stream.flush()

    def drain_stream(self, name: str) -> Dict[str, Any]:
        stream = self._get(name)
        if stream is None:
            return wire.error("not_found", f"no stream named {name!r}")
        result = stream.drain()
        if result.get("ok"):
            self._emit_costs(name)
        return result

    def revive_stream(self, name: str) -> Dict[str, Any]:
        """Half-open a parked (circuit-open) stream and retry — the operator
        verb behind ``ctl revive``."""
        stream = self._get(name)
        if stream is None:
            return wire.error("not_found", f"no stream named {name!r}")
        return stream.revive()

    def deadletter(self, name: str, action: str = "list", seq: Any = None) -> Dict[str, Any]:
        """Quarantine management: ``list`` the records, ``requeue`` one back
        through the exactly-once path, or ``purge`` it for good."""
        stream = self._get(name)
        if stream is None:
            return wire.error("not_found", f"no stream named {name!r}")
        if action == "list":
            return stream.deadletter_list()
        if action == "requeue":
            return stream.deadletter_requeue(seq)
        if action == "purge":
            return stream.deadletter_purge(seq)
        return wire.error("bad_request", f"unknown deadletter action {action!r} (list|requeue|purge)")

    def delete_stream(self, name: str) -> Dict[str, Any]:
        with self._lock:
            stream = self._streams.pop(name, None)
        if stream is None:
            return wire.error("not_found", f"no stream named {name!r}")
        dropped = stream.abandon()
        shutil.rmtree(os.path.join(self.base_dir, "streams", name), ignore_errors=True)
        return wire.ok(stream=name, dropped=dropped)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            streams = sorted(self._streams.values(), key=lambda s: s.spec.name)
        return wire.ok(
            accepting=self._accepting,
            epoch=self.epoch,
            rank=_obs_live._detect_rank() if self._rank is None else self._rank,
            streams=[s.status() for s in streams],
        )

    # ---------------------------------------------------------------- export
    def export_state(self, name: Optional[str] = None, fingerprint: Optional[str] = None) -> Dict[str, Any]:
        """The ``/v1/state`` federation verb: per-stream checkpoint payloads
        stamped with this boot's epoch and each stream's applied-seq
        watermark. ``name`` narrows to one stream; ``fingerprint`` pins the
        export to a registry fingerprint (mismatch → ``fingerprint_mismatch``,
        HTTP 409 — the aggregator quarantines instead of folding a foreign
        schema)."""
        if name is not None:
            stream = self._get(name)
            if stream is None:
                return wire.error("not_found", f"no stream named {name!r}")
            result = stream.export(fingerprint=fingerprint)
            if result.get("ok"):
                result["epoch"] = self.epoch
            return result
        with self._lock:
            streams = sorted(self._streams.items())
        exports: Dict[str, Any] = {}
        for sname, stream in streams:
            exports[sname] = stream.export(fingerprint=fingerprint)
        return wire.ok(epoch=self.epoch, streams=exports)

    def _emit_costs(self, name: str) -> None:
        """Per-stream cost ledger at a compute boundary — the attribution
        plane's ledger is process-wide, stamped here with the stream it was
        emitted for."""
        path = os.path.join(self.base_dir, "streams", name, "costs.json")
        try:
            _obs_attr.write_costs(path)
        except Exception:
            _obs_counters.inc("serve.costs_errors")

    # ---------------------------------------------------------------- probe
    def _probe(self) -> Dict[str, float]:
        with self._lock:
            streams = list(self._streams.values())
        gauges: Dict[str, float] = {"serve.streams": float(len(streams))}
        for stream in streams:
            gauges.update(stream.gauges())
        return gauges

    # ----------------------------------------------------------------- http
    def http_address(self) -> Optional[Tuple[str, int]]:
        """``(host, port)`` the control plane bound (port 0 resolves here)."""
        if self._http_server is None:
            return None
        return self._http_server.server_address[:2]

    def _start_http(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        host, port = _obs_live._parse_http_spec(self._http_spec)
        daemon = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:
                pass

            def _send_json(self, obj: Dict[str, Any], code: Optional[int] = None) -> None:
                if code is None:
                    code = 200 if obj.get("ok", True) else _ERROR_HTTP_STATUS.get(
                        obj.get("error", {}).get("code"), 400
                    )
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if obj.get("ok") is False and obj["error"].get("code") == "backpressure":
                    self.send_header("Retry-After", str(obj["error"].get("retry_after_s", 0.05)))
                self.end_headers()
                self.wfile.write(body)

            def _query(self) -> Dict[str, str]:
                from urllib.parse import parse_qsl

                if "?" not in self.path:
                    return {}
                return dict(parse_qsl(self.path.split("?", 1)[1]))

            def _body(self) -> Dict[str, Any]:
                length = int(self.headers.get("Content-Length", 0))
                obj = wire.decode_frame(self.rfile.read(length)) if length else {}
                if obj:
                    wire.check_version(obj)
                return obj

            def _route(self) -> None:
                path = self.path.split("?", 1)[0].rstrip("/")
                parts = [p for p in path.split("/") if p]
                try:
                    if self.command == "GET" and path == "/healthz":
                        publisher = _obs_live.publisher()
                        health = publisher.health() if publisher else _obs_live.derive_health(
                            {}, _obs_live.sample_probes()
                        )
                        health["epoch"] = daemon.epoch
                        self._send_json(health, code=health["http_status"])
                    elif self.command == "GET" and path == "/metrics":
                        publisher = _obs_live.publisher()
                        if publisher is None:
                            self._send_json(wire.error("failed", "live plane is off"), code=503)
                            return
                        body = publisher.render_metrics().encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "application/openmetrics-text; version=1.0.0; charset=utf-8")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    elif self.command == "GET" and path == "/v1/state":
                        self._send_json(daemon.export_state(fingerprint=self._query().get("fingerprint")))
                    elif parts[:2] == ["v1", "streams"]:
                        self._streams_route(parts[2:])
                    else:
                        self._send_json(
                            wire.error("not_found", "metricserve control plane: /v1/streams, /healthz, /metrics")
                        )
                except wire.WireError as err:
                    self._send_json(wire.error("bad_request", str(err)))
                except Exception as err:  # the control plane must answer, never hang up
                    self._send_json(wire.error("failed", f"{type(err).__name__}: {err}"), code=500)

            def _streams_route(self, rest: List[str]) -> None:
                if not rest:
                    if self.command == "GET":
                        self._send_json(daemon.status())
                    elif self.command == "POST":
                        body = self._body()
                        body.pop("v", None)
                        self._send_json(daemon.create_stream(body))
                    else:
                        self._send_json(wire.error("bad_request", f"{self.command} not supported here"))
                    return
                name = rest[0]
                action = rest[1] if len(rest) > 1 else None
                if self.command == "DELETE" and action is None:
                    self._send_json(daemon.delete_stream(name))
                elif self.command == "GET" and action is None:
                    stream = daemon._get(name)
                    if stream is None:
                        self._send_json(wire.error("not_found", f"no stream named {name!r}"))
                    else:
                        self._send_json(wire.ok(**stream.status()))
                elif self.command == "POST" and action == "ingest":
                    body = self._body()
                    self._send_json(daemon.ingest(name, body.get("seq"), body.get("batch")))
                elif self.command == "POST" and action == "flush":
                    self._send_json(daemon.flush(name))
                elif self.command == "POST" and action == "drain":
                    self._send_json(daemon.drain_stream(name))
                elif self.command == "POST" and action == "revive":
                    self._send_json(daemon.revive_stream(name))
                elif self.command == "GET" and action == "state":
                    self._send_json(daemon.export_state(name, fingerprint=self._query().get("fingerprint")))
                elif self.command == "GET" and action == "deadletter":
                    self._send_json(daemon.deadletter(name, "list"))
                elif self.command == "POST" and action == "deadletter":
                    body = self._body()
                    self._send_json(daemon.deadletter(name, body.get("action", "list"), body.get("seq")))
                else:
                    self._send_json(wire.error("bad_request", f"{self.command} {self.path} not supported"))

            do_GET = do_POST = do_DELETE = _route

        self._http_server = ThreadingHTTPServer((host, port), _Handler)
        self._http_server.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._http_server.serve_forever, daemon=True, name="metricserve-http"
        )
        self._http_thread.start()

    # --------------------------------------------------------------- socket
    def _start_socket(self) -> None:
        daemon = self
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

        class _SockServer(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        class _SockHandler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                # one frame per line; the connection stays open for a whole
                # replay session (the socket plane's win over per-batch HTTP)
                for line in self.rfile:
                    if not line.strip():
                        continue
                    try:
                        frame = wire.decode_frame(line)
                        wire.check_version(frame)
                        reply = daemon._handle_frame(frame)
                    except wire.WireError as err:
                        reply = wire.error("bad_request", str(err))
                    except Exception as err:
                        reply = wire.error("failed", f"{type(err).__name__}: {err}")
                    try:
                        self.wfile.write(wire.encode_frame(reply))
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        return

        self._sock_server = _SockServer(self.socket_path, _SockHandler)
        self._sock_thread = threading.Thread(
            target=self._sock_server.serve_forever, daemon=True, name="metricserve-socket"
        )
        self._sock_thread.start()

    def _handle_frame(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Dispatch one socket frame: ``op`` selects the control verb; ingest
        blocks with a deadline (``deadline_s``, default 5s) instead of the
        HTTP 429 round-trip."""
        op = frame.get("op")
        name = frame.get("stream")
        if op == "ingest":
            deadline = frame.get("deadline_s", 5.0)
            return self.ingest(name, frame.get("seq"), frame.get("batch"), block=True, deadline_s=deadline)
        if op == "create":
            return self.create_stream(frame.get("spec") or {})
        if op == "status":
            if name:
                stream = self._get(name)
                return wire.ok(**stream.status()) if stream else wire.error("not_found", f"no stream named {name!r}")
            return self.status()
        if op == "flush":
            return self.flush(name)
        if op == "drain":
            return self.drain_stream(name)
        if op == "delete":
            return self.delete_stream(name)
        if op == "revive":
            return self.revive_stream(name)
        if op == "deadletter":
            return self.deadletter(name, frame.get("action", "list"), frame.get("seq"))
        if op == "state":
            return self.export_state(name, fingerprint=frame.get("fingerprint"))
        return wire.error("bad_request", f"unknown op {op!r}")


#: wire error code → HTTP status (backpressure maps to 429 + Retry-After)
_ERROR_HTTP_STATUS = {
    "backpressure": 429,
    "bad_seq": 409,
    "not_found": 404,
    "exists": 409,
    "draining": 503,
    "failed": 500,
    "bad_payload": 400,
    "bad_request": 400,
    "unsupported_version": 400,
    "fingerprint_mismatch": 409,
}
