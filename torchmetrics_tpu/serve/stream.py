# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""One durable, self-healing evaluation stream inside a ``metricserve`` daemon.

A :class:`Stream` is the service-side unit the daemon multiplexes: one named
(model-version × dataset) evaluation owning

- a declarative :class:`StreamSpec` (factory import path + evaluator knobs,
  the wire-facing description a ``create`` request carries),
- its own :class:`~torchmetrics_tpu.robustness.store.CheckpointStore`
  sub-directory (restart = resume from the snapshot cursor, never recount),
- a bounded ingest queue (admission control — the **only** place a batch
  waits) feeding ONE worker thread that pumps the evaluator's open-loop
  serve API (:meth:`~torchmetrics_tpu.robustness.runner.StreamingEvaluator.
  serve_step`), optionally through a
  :class:`~torchmetrics_tpu.parallel.feed.DeviceFeed` so host decode overlaps
  device work exactly like a batch run.

**Exactly-once ingest.** Every batch carries a client sequence number. The
stream acks ``seq == next_seq`` (advancing), re-acks ``seq < next_seq``
(duplicate — idempotent replay), and rejects ``seq > next_seq`` with the
expected value (gap — the client rewinds). After a daemon crash ``next_seq``
restarts at the restored snapshot cursor, so the client replays exactly the
acked-but-unpersisted suffix and no sample is counted twice or dropped.

**Supervision.** A worker exception is no longer terminal. The supervisor
(the worker thread's own outer loop) rebuilds the evaluator from the spec,
restores from the newest valid snapshot, and replays the acked-but-unapplied
suffix from an in-memory **retained buffer** (pruned once a batch is covered
by two snapshots, capped at ``max(256, 4 × queue_max)``) — exactly-once
holds across in-process restarts with no client involvement. Restarts back
off exponentially with jitter and are budgeted by a **circuit breaker**:
more than ``max_restarts`` failures inside ``restart_window_s`` parks the
stream (state ``failed``, circuit ``open``, health ``stalled``); a manual
:meth:`revive` (``ctl revive``) half-opens the circuit for one probe
incarnation — the next failure re-opens it, the next successful apply
closes it.

**Poison-batch quarantine.** A batch that kills the worker
``poison_threshold`` times in a row is dead-lettered: its seq + wire payload
+ error + attempt count are appended to the stream's ``deadletter.jsonl``
(atomic temp+fsync+replace, the ``store_format`` discipline), the cursor
advances past it (:meth:`~torchmetrics_tpu.robustness.runner.
StreamingEvaluator.serve_skip` — the skip still moves the durable
watermark), and the stream keeps serving. ``ctl deadletter list|requeue|
purge`` manages the quarantine; a requeued payload re-enters through the
normal exactly-once admission at the current watermark. The quarantine
survives daemon restarts (re-read from disk at stream construction).

**Disk-fault degradation.** ENOSPC/EIO on a snapshot or dead-letter write
retries briefly, then detaches the store and keeps serving **in-memory-only**
(health ``degraded``, ``store.write_failures`` counter); a recovery probe
re-attempts the write every ``_RECOVERY_PROBE_S`` and re-enables durability
the moment disk recovers.

**Control ops ride the batch queue.** flush/drain must serialize with the
batches already admitted, so ops travel the same queue. With a DeviceFeed in
front, an op enqueues a leafless ``()`` marker into the feed (an empty
pytree — ``device_put`` stages nothing) and parks the op itself on a FIFO
side-channel; the worker executes the op when the marker surfaces, which is
exactly its queue position. Each worker incarnation gets a FRESH queue and
side-channel: a superseded DeviceFeed staging thread (blocked in the old
source) notices the swap and winds down instead of stealing live batches.

**Dropped-batch accounting.** ``serve.dropped_batches`` counts batches the
daemon ACKED but will never apply — the suffix abandoned when a stream is
deleted or fails unrecoverably, plus purged dead-letter records. A parked
(circuit-open) stream does NOT latch its pending suffix: the retained buffer
still holds it and a revive applies it, so the counter stays zero on every
healable path.
"""
from __future__ import annotations

import errno
import json
import os
import queue
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.robustness import store_format as _fmt
from torchmetrics_tpu.robustness.store import CheckpointStore
from torchmetrics_tpu.serve import wire

__all__ = ["StreamSpec", "Stream", "decode_batch", "resolve_target"]

#: ``()`` is the op marker: real batches are always NON-empty tuples (or a
#: bare array), so an empty tuple is unambiguous — and leafless, so a
#: DeviceFeed stages it as a no-op instead of choking on non-array leaves
_OP_MARKER: Tuple[()] = ()

_STATE_HEALTH = {
    "starting": 0,
    "serving": 0,
    "draining": 0,
    "drained": 0,
    "failed": 3,
}

#: numeric state codes for the ``serve.<name>.state`` gauge (gauges are
#: floats; scrapers map back through this table)
STATE_CODES = {"starting": 0, "serving": 1, "draining": 2, "drained": 3, "failed": 4}

#: numeric circuit codes for the ``serve.<name>.circuit_state`` gauge
CIRCUIT_CODES = {"closed": 0, "half_open": 1, "open": 2}

#: snapshot/dead-letter write retries before degrading to in-memory-only,
#: and the base of their exponential backoff
_DISK_RETRIES = 3
_DISK_RETRY_BASE_S = 0.01
#: cadence of the degraded stream's durability recovery probe
_RECOVERY_PROBE_S = 0.5


def _is_disk_error(err: BaseException) -> bool:
    """The resource-exhaustion class the degradation path absorbs."""
    return isinstance(err, OSError) and err.errno in (errno.ENOSPC, errno.EIO)


class _Unrecoverable(RuntimeError):
    """A worker failure supervision must NOT retry (exactly-once would break)."""


class _Halt(RuntimeError):
    """The stream was abandoned while the worker was down — exit quietly."""


def resolve_target(path: str, kwargs: Optional[Dict[str, Any]] = None) -> Any:
    """Build a stream's metric target from a ``module:callable`` factory
    path — the declarative form a wire ``create`` carries (a server cannot
    receive live Python objects). The factory returns a ``Metric``,
    ``MetricCollection`` or ``SlicedPlan``; see
    :mod:`torchmetrics_tpu.serve.factories` for ready-made ones."""
    module_name, sep, attr = path.partition(":")
    if not sep or not module_name or not attr:
        raise ValueError(f"target must be 'module:callable', got {path!r}")
    import importlib

    factory = importlib.import_module(module_name)
    for part in attr.split("."):
        factory = getattr(factory, part)
    return factory(**(kwargs or {}))


def decode_batch(batch: Any) -> Tuple[Any, ...]:
    """Wire batch (list of nested number lists, one per positional update
    argument) → tuple of arrays. One decode path for the daemon AND for
    parity tests replaying the same stream in-process, so a resumed service
    run compares bitwise against an uninterrupted one."""
    import numpy as np

    if not isinstance(batch, (list, tuple)) or not batch:
        raise wire.WireError("batch must be a non-empty JSON list (one entry per update argument)")
    return tuple(np.asarray(part) for part in batch)


def _batch_signature(decoded: Tuple[Any, ...]) -> Tuple[Tuple[Tuple[int, ...], str], ...]:
    """(trailing shape, dtype) per part — the aval the stream pins at its
    first accepted batch. The LEADING dim is the batch dim and may vary
    (clients split unevenly); everything else must match."""
    return tuple((tuple(part.shape[1:]), str(part.dtype)) for part in decoded)


class StreamSpec:
    """Declarative stream description — what a wire ``create`` carries.

    Args:
        name: registry key; one path component (no ``/``, no ``.`` — it names
            a store sub-directory and a ``serve.<name>.*`` gauge family).
        target: ``module:callable`` factory path for the metric target.
        kwargs: keyword arguments for the factory.
        fused: drive a ``MetricCollection`` target through the fused plane.
        fused_options: fused-plan build kwargs (``cat_capacity`` etc.; a
            fused collection with cat-state members NEEDS ``cat_capacity``
            so its carries get fixed-capacity buffers).
        window: ``WindowRing`` knobs (``slots`` + ``every_n``/``every_s``)
            wrapped around the target, or ``None``.
        snapshot_every_n / snapshot_every_s: evaluator snapshot cadence.
        queue_max: ingest queue bound (admission control), default 64.
        use_feed: stage batches through a ``DeviceFeed`` (default True).
        watchdog_timeout_s / on_stall: evaluator watchdog policy.
        max_restarts: circuit-breaker budget — more than this many worker
            failures inside ``restart_window_s`` parks the stream with the
            circuit ``open`` (``0`` = any failure parks immediately).
        restart_window_s: the sliding window the budget counts over.
        backoff_base_s / backoff_max_s: restart backoff — attempt ``n``
            sleeps ``min(max, base·2ⁿ⁻¹)`` plus the same again in jitter.
        poison_threshold: consecutive worker deaths on the SAME batch before
            it is dead-lettered and skipped (≥ 1).
        guard_ring: depth of the StateGuard known-good rollback ring (≥ 1) —
            how many verified post-batch states are retained in memory for
            an instant rollback when the poison probe trips. Only consulted
            when the target metric is guarded (``robustness/guard.py``).
        guard_recover_s: the sliding window guard rollbacks are counted over
            for health: one rollback inside the window reads stalling, two
            or more read degraded (floors ``/healthz`` at 503 until the
            window drains).
    """

    _FIELDS = (
        "name", "target", "kwargs", "fused", "fused_options", "window", "snapshot_every_n",
        "snapshot_every_s", "queue_max", "use_feed", "watchdog_timeout_s", "on_stall",
        "max_restarts", "restart_window_s", "backoff_base_s", "backoff_max_s", "poison_threshold",
        "guard_ring", "guard_recover_s",
    )

    def __init__(
        self,
        name: str,
        target: str,
        kwargs: Optional[Dict[str, Any]] = None,
        fused: bool = False,
        fused_options: Optional[Dict[str, Any]] = None,
        window: Optional[Dict[str, Any]] = None,
        snapshot_every_n: Optional[int] = None,
        snapshot_every_s: Optional[float] = None,
        queue_max: int = 64,
        use_feed: bool = True,
        watchdog_timeout_s: Optional[float] = None,
        on_stall: str = "raise",
        max_restarts: int = 5,
        restart_window_s: float = 60.0,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        poison_threshold: int = 3,
        guard_ring: int = 4,
        guard_recover_s: float = 60.0,
    ) -> None:
        if not name or any(ch in name for ch in "/\\.") or name != name.strip():
            raise ValueError(
                f"stream name {name!r} must be one clean path component (it names a store"
                " sub-directory and a serve.<name>.* gauge family — no '/', '\\\\' or '.')"
            )
        if queue_max < 1:
            raise ValueError(f"queue_max must be >= 1, got {queue_max}")
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if restart_window_s <= 0:
            raise ValueError(f"restart_window_s must be > 0, got {restart_window_s}")
        if backoff_base_s <= 0 or backoff_max_s < backoff_base_s:
            raise ValueError(
                f"need 0 < backoff_base_s <= backoff_max_s, got {backoff_base_s}/{backoff_max_s}"
            )
        if poison_threshold < 1:
            raise ValueError(f"poison_threshold must be >= 1, got {poison_threshold}")
        if guard_ring < 1:
            raise ValueError(f"guard_ring must be >= 1, got {guard_ring}")
        if guard_recover_s <= 0:
            raise ValueError(f"guard_recover_s must be > 0, got {guard_recover_s}")
        self.name = name
        self.target = target
        self.kwargs = dict(kwargs or {})
        self.fused = bool(fused)
        self.fused_options = dict(fused_options) if fused_options else None
        self.window = dict(window) if window else None
        self.snapshot_every_n = snapshot_every_n
        self.snapshot_every_s = snapshot_every_s
        self.queue_max = int(queue_max)
        self.use_feed = bool(use_feed)
        self.watchdog_timeout_s = watchdog_timeout_s
        self.on_stall = on_stall
        self.max_restarts = int(max_restarts)
        self.restart_window_s = float(restart_window_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.poison_threshold = int(poison_threshold)
        self.guard_ring = int(guard_ring)
        self.guard_recover_s = float(guard_recover_s)

    def to_wire(self) -> Dict[str, Any]:
        return {field: getattr(self, field) for field in self._FIELDS}

    @classmethod
    def from_wire(cls, obj: Dict[str, Any]) -> "StreamSpec":
        unknown = sorted(set(obj) - set(cls._FIELDS))
        if unknown:
            raise wire.WireError(f"unknown StreamSpec field(s): {', '.join(unknown)}")
        if "name" not in obj or "target" not in obj:
            raise wire.WireError("StreamSpec needs at least 'name' and 'target'")
        return cls(**obj)

    def build_evaluator(self, store_dir: str) -> Any:
        """Materialize the evaluator this spec describes over ``store_dir``.

        ``write_rank=None``: a daemon rank owns its whole base directory, so
        EVERY rank persists (multi-host deployments give each rank its own
        base dir and fold state through the merge-state sync at compute)."""
        from torchmetrics_tpu.robustness.runner import StreamingEvaluator

        metric = resolve_target(self.target, self.kwargs)
        ring = None
        if self.window is not None:
            from torchmetrics_tpu.parallel.windowing import WindowRing

            ring = WindowRing(metric, **self.window)
        store = CheckpointStore(store_dir, keep_last=3, write_rank=None)
        return StreamingEvaluator(
            metric,
            store=store,
            snapshot_every_n=self.snapshot_every_n,
            snapshot_every_s=self.snapshot_every_s,
            fused=self.fused,
            fused_options=self.fused_options,
            window_ring=ring,
            watchdog_timeout_s=self.watchdog_timeout_s,
            on_stall=self.on_stall,
        )


class _Op:
    """One control op riding the batch queue (see the module docstring)."""

    __slots__ = ("name", "done", "result", "error")

    def __init__(self, name: str) -> None:
        self.name = name
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None

    def finish(self, result: Any = None, error: Optional[BaseException] = None) -> None:
        self.result, self.error = result, error
        self.done.set()


class Stream:
    """One running stream: spec + evaluator + bounded queue + supervised worker."""

    def __init__(self, spec: StreamSpec, store_dir: str) -> None:
        self.spec = spec
        self.store_dir = str(store_dir)
        #: sibling of the store dir — survives store prunes AND daemon restarts
        self.deadletter_path = os.path.join(
            os.path.dirname(os.path.abspath(self.store_dir)), "deadletter.jsonl"
        )
        self.evaluator = spec.build_evaluator(self.store_dir)
        self._queue: "queue.Queue[Tuple[str, Any]]" = queue.Queue(maxsize=spec.queue_max)
        self._pending_ops: "deque[_Op]" = deque()
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._finished = threading.Event()
        self.state = "starting"
        self.next_seq = 0  # acked watermark; meaningful once _ready is set
        self.result: Optional[Any] = None
        self.failure: Optional[str] = None
        self.last_failure: Optional[str] = None  # newest worker crash (survives healing)
        self.dropped = 0
        self._dropped_latched = False
        # --- supervision / circuit breaker -------------------------------
        self.circuit = "closed"
        self.restarts = 0
        self._failure_times: "deque[float]" = deque()  # monotonic, pruned to the window
        self._opened_once = False
        self._evaluator_dirty = False  # the evaluator died mid-step: rebuild before reuse
        self._applying = False  # worker is inside a batch apply (poison accounting)
        self._crash_seq: Optional[int] = None  # consecutive-crash culprit
        self._crash_count = 0
        # --- retained in-flight buffer (exactly-once across restarts) ----
        self._retained: Dict[int, Tuple[Any, Any]] = {}  # seq -> (wire batch, decoded)
        self._retained_floor = 0  # seqs below were pruned/evicted — unrecoverable
        self._retain_cap = max(256, 4 * spec.queue_max)
        self._last_snap_step = 0  # retention keeps everything >= the PREVIOUS snapshot
        self._snap_seen_t: Optional[float] = None
        # --- dead-letter quarantine --------------------------------------
        self._deadletter: Dict[int, Dict[str, Any]] = {}
        self._quarantined: set = set()
        self._dl_dirty = False  # records newer than the on-disk file (disk fault)
        self._dl_write_lock = threading.Lock()
        self._load_deadletter()
        # --- StateGuard known-good rollback ring -------------------------
        self._guard_metric: Optional[Any] = None  # the guarded target, re-resolved per incarnation
        self._guard_ring: "deque[Tuple[int, Dict[str, Any], int]]" = deque(maxlen=spec.guard_ring)
        self._guard_rollback_times: "deque[float]" = deque()  # monotonic, pruned to guard_recover_s
        self.guard_rollbacks = 0
        self.guard_poisoned_total = 0  # poison detections (the latch itself resets on rollback)
        # --- durability degradation --------------------------------------
        self._durable = True
        self._store_ref: Optional[CheckpointStore] = None  # parked store while degraded
        self._probe_at = 0.0
        self.write_failures = 0
        # --- payload validation ------------------------------------------
        self._avals: Optional[Tuple[Tuple[Tuple[int, ...], str], ...]] = None
        self._drain_op: Optional[_Op] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"metricserve-{spec.name}"
        )

    # ----------------------------------------------------------- lifecycle
    def start(self, timeout_s: float = 60.0) -> int:
        """Start the worker, wait for the durable open (snapshot restore) to
        finish, and return the cursor batches resume from — the ``next_seq``
        a client must replay from."""
        self._thread.start()
        if not self._ready.wait(timeout_s):
            raise TimeoutError(f"stream {self.spec.name} did not open within {timeout_s}s")
        with self._lock:
            if self.state == "failed":
                raise RuntimeError(f"stream {self.spec.name} failed to open: {self.failure}")
            return self.next_seq

    def _run(self) -> None:
        """Supervisor: serve until clean exit; on a crash decide restart vs
        park/fail. Backoff/circuit/poison policy lives in :meth:`_supervise`."""
        try:
            while True:
                try:
                    self._serve_once()
                    return
                except BaseException as err:  # the worker must report, never vanish
                    self._evaluator_dirty = True
                    if not self._supervise(err):
                        return
        finally:
            self._ready.set()
            self._finished.set()

    def _serve_once(self) -> None:
        """One worker incarnation: open (restore), replay the retained
        suffix, then pump the live queue until a drain/abandon ends it."""
        if self._evaluator_dirty:
            # the previous incarnation died mid-step: its in-memory state is
            # suspect — rebuild from the spec and restore through the
            # durability plane's recovery ladder
            try:
                self.evaluator._unregister_probes()
            except Exception:
                pass
            self.evaluator = self.spec.build_evaluator(self.store_dir)
            self._evaluator_dirty = False
        start = int(self.evaluator.serve_open())
        self._opened_once = True
        if not self._durable:
            # still degraded: reads worked, writes stay off until the
            # recovery probe flips durability back on
            with self._lock:
                self._store_ref = self.evaluator.store
                self.evaluator.store = None
        self._snap_seen_t = self.evaluator._last_snapshot_t
        self._last_snap_step = start
        self._guard_open()
        if self.spec.use_feed:
            # a superseded staging thread may still be draining the OLD
            # queue; give its in-flight op hand-off a beat to land before we
            # collect the side-channel (batches need no grace: the retained
            # buffer re-feeds them regardless of who consumed the queue item)
            time.sleep(0.05)
        with self._lock:
            if self.state == "failed":
                raise _Halt(self.failure or "stream stopped")
            if start > self.next_seq:
                self.next_seq = start  # fresh process over an older store
            evicted = [
                s for s in range(start, self.next_seq)
                if s not in self._retained and s not in self._quarantined and s < self._retained_floor
            ]
            if evicted:
                raise _Unrecoverable(
                    f"acked batch(es) {evicted[:5]} fell below the retained-buffer floor"
                    f" ({self._retained_floor}) and the snapshot restore only reached cursor"
                    f" {start} — exactly-once replay is impossible"
                )
            replay = [
                (s, self._retained[s][1] if s in self._retained else None)
                for s in range(start, self.next_seq)
            ]
            # synthetic skips are NOT carried over: replay regenerates them
            # from the quarantine set, and a stale one would double-advance
            # the cursor
            parked: "deque[_Op]" = deque(op for op in self._pending_ops if op.name != "skip")
            while True:
                try:
                    kind, payload = self._queue.get_nowait()
                except queue.Empty:
                    break
                if kind == "op":
                    parked.append(payload)
            # fresh queue + side-channel per incarnation: a stale DeviceFeed
            # stager blocked in the old source can never steal live batches
            self._queue = queue.Queue(maxsize=self.spec.queue_max)
            self._pending_ops = deque()
            pending = self._pending_ops
            live_queue = self._queue
            if self.state == "starting":
                self.state = "serving"
        self._ready.set()
        source = self._source(live_queue, pending, replay, parked)
        if self.spec.use_feed:
            from torchmetrics_tpu.parallel.feed import DeviceFeed

            items: Any = DeviceFeed(source)
        else:
            items = source
        try:
            for item in items:
                if isinstance(item, tuple) and not item:
                    self._exec_op(pending.popleft())
                else:
                    self._applying = True
                    if faults._ACTIVE:
                        faults.fire("serve.worker.crash")
                    self._step_guarded(item)
                    self._applying = False
                    if self._guard_metric is not None:
                        self._guard_after_apply(item)
                    self._note_applied()
                self._after_apply()
            # the source ended: a drain (or abandon) op asked for the close
            final_op = pending.popleft()
            try:
                if final_op.name == "abandon":
                    self.evaluator._unregister_probes()
                    final_op.finish()
                else:
                    result = self._close_guarded()
                    with self._lock:
                        self.result = wire.to_jsonable(result)
                        self.state = "drained"
                    final_op.finish(result=self.result)
            except BaseException as err:
                # never leave the drain caller waiting out its timeout:
                # report, then let supervision decide the stream's fate
                final_op.finish(error=err)
                raise
        except BaseException:
            # an op accepted into this incarnation must outlive its death:
            # whatever was marker-yielded but unexecuted (minus synthetic
            # skips) plus whatever never left the parked deque is handed to
            # the next incarnation — or error-finished by the failure path
            with self._lock:
                self._pending_ops = deque(
                    [op for op in pending if op.name != "skip" and not op.done.is_set()]
                    + [op for op in parked if not op.done.is_set()]
                )
            raise

    def _source(
        self,
        live_queue: "queue.Queue[Tuple[str, Any]]",
        pending: "deque[_Op]",
        replay: List[Tuple[int, Any]],
        parked: "deque[_Op]",
    ) -> Any:
        """Replayed suffix + re-parked ops + live queue → one iterator the
        (optional) DeviceFeed stages. Ends at drain/abandon — or quietly when
        a restart has superseded this incarnation's queue."""
        for seq, decoded in replay:
            if decoded is None or seq in self._quarantined:
                # quarantined (or a requeued dead-letter hole): advance the
                # cursor without applying so the watermark stays seq == cursor
                pending.append(_Op("skip"))
                yield _OP_MARKER
            else:
                yield decoded
        while parked:
            op = parked.popleft()
            pending.append(op)
            if op.name in ("drain", "abandon"):
                stop = RuntimeError(f"stream {self.spec.name} is past {op.name}")
                while parked:
                    parked.popleft().finish(error=stop)
                return
            yield _OP_MARKER
        while True:
            try:
                kind, payload = live_queue.get(timeout=1.0)
            except queue.Empty:
                if live_queue is not self._queue:
                    return  # superseded incarnation: wind down the stale feed
                continue
            if kind == "batch":
                seq, decoded = payload
                if seq in self._quarantined:
                    pending.append(_Op("skip"))
                    yield _OP_MARKER
                else:
                    yield decoded
            elif payload.name in ("drain", "abandon"):
                pending.append(payload)
                return
            else:
                pending.append(payload)
                yield _OP_MARKER

    # ------------------------------------------------- disk-fault degradation
    def _note_write_failure(self, err: BaseException) -> None:
        _obs_counters.inc("store.write_failures")
        with self._lock:
            # counter mutates under the same lock its readers take (ML012) —
            # writer and recovery-probe threads both call this path
            self.write_failures += 1
            self.last_failure = f"{type(err).__name__}: {err}"

    def _enter_degraded(self) -> None:
        """Detach the store: the stream keeps serving in-memory-only while
        the recovery probe retries the write path."""
        with self._lock:
            if not self._durable:
                return
            self._durable = False
            self._store_ref = self.evaluator.store
            self.evaluator.store = None
            self._probe_at = time.monotonic() + _RECOVERY_PROBE_S

    def _handle_disk_fault(self, err: OSError) -> bool:
        """A snapshot write hit ENOSPC/EIO: retry with backoff, then degrade.
        True when a retry landed the write (durability intact)."""
        self._note_write_failure(err)
        delay = _DISK_RETRY_BASE_S
        for _ in range(_DISK_RETRIES):
            time.sleep(delay)
            delay *= 2
            try:
                self.evaluator.snapshot()
                return True
            except OSError as retry_err:
                if not _is_disk_error(retry_err):
                    raise
                self._note_write_failure(retry_err)
        self._enter_degraded()
        return False

    def _step_guarded(self, item: Any) -> None:
        cursor_before = self.evaluator.cursor
        try:
            self.evaluator.serve_step(item)
        except OSError as err:
            # ENOSPC/EIO with the cursor already advanced = the batch applied
            # and only its cadence snapshot failed — absorb into degradation
            if _is_disk_error(err) and self.evaluator.cursor > cursor_before:
                self._handle_disk_fault(err)
            else:
                raise

    def _close_guarded(self) -> Any:
        try:
            return self.evaluator.serve_close()
        except OSError as err:
            if not _is_disk_error(err):
                raise
            # the members are already folded back and only the FINAL snapshot
            # hit disk exhaustion: degrade and compute in memory rather than
            # fail the whole drain
            self._note_write_failure(err)
            self._enter_degraded()
            evaluator = self.evaluator
            compute = evaluator.metric.compute_all if evaluator._is_plan else evaluator.metric.compute
            return evaluator._bounded(compute, "compute")

    # ----------------------------------------------- StateGuard rollback ring
    def _guard_open(self) -> None:
        """Per-incarnation guard wiring: resolve whether this evaluator's
        target is a guarded plain Metric, point the runner's cadence-snapshot
        gate at the poison probe (a just-corrupted state must not reach disk
        in the window between the apply and the rollback), and seed the
        rollback ring with the just-restored — hence verified — state.

        Ring entries are ``(cursor, state dict, update_count)``;
        ``_copy_state_dict`` holds array REFERENCES, so a deep ring costs
        pointers per batch, not state copies."""
        self._guard_ring.clear()
        self._guard_metric = None
        evaluator = self.evaluator
        if self.spec.fused or self.spec.window is not None or evaluator._is_plan:
            return  # ring rollback needs a plain Metric target owning its own states
        metric = getattr(evaluator, "metric", None)
        if metric is None or getattr(metric, "_guard_policy", None) is None:
            return
        self._guard_metric = metric
        evaluator.snapshot_gate = self._guard_snapshot_gate
        self._guard_capture()

    def _guard_snapshot_gate(self) -> bool:
        metric = self._guard_metric
        return metric is None or int(metric.guard_poisoned) == 0

    def _guard_capture(self) -> None:
        metric = self._guard_metric
        self._guard_ring.append(
            (int(self.evaluator.cursor), metric._copy_state_dict(), metric._update_count)
        )

    def _guard_after_apply(self, item: Any) -> None:
        """Poison-probe checkpoint after every applied batch: clean → retain
        the post-batch state in the ring; tripped → restore the newest
        known-good entry (the state BEFORE the offending batch), quarantine
        the batch to the dead-letter ledger with its guard verdict, and skip
        past it — no disk restore, no client replay (later batches are still
        queued; the skip moves the watermark exactly one seq)."""
        metric = self._guard_metric
        if int(metric.guard_poisoned) == 0:
            self._guard_capture()
            return
        evaluator = self.evaluator
        culprit = int(evaluator.cursor) - 1
        if not self._guard_ring:
            raise _Unrecoverable(
                f"poison probe tripped at seq {culprit} with an empty rollback ring"
            )
        cursor0, state, count = self._guard_ring[-1]
        metric._install_state_tree(state)
        metric._update_count = count
        metric._computed = None
        evaluator.cursor = cursor0
        with self._lock:
            self.guard_rollbacks += 1
            self.guard_poisoned_total += 1
            self._guard_rollback_times.append(time.monotonic())
        _obs_counters.inc("serve.guard_rollbacks")
        from torchmetrics_tpu.robustness.guard import batch_verdict_host

        verdict = batch_verdict_host(metric, item if isinstance(item, tuple) else (item,))
        err = RuntimeError(f"StateGuard poison probe: state went non-finite applying seq {culprit}")
        self._quarantine(culprit, err, guard=verdict)
        # advance the watermark past the quarantined batch; the cadence
        # snapshot inside the skip persists the ROLLED-BACK truth (the latch
        # is down again, so the gate passes)
        cursor_before = evaluator.cursor
        try:
            evaluator.serve_skip()
        except OSError as skip_err:
            if _is_disk_error(skip_err) and evaluator.cursor > cursor_before:
                self._handle_disk_fault(skip_err)
            else:
                raise
        self._guard_capture()

    def _guard_health_code(self) -> int:
        """0 ok / 1 stalling / 2 degraded from rollbacks inside the sliding
        ``guard_recover_s`` window — the ``guard.<name>.health_state`` gauge
        the live plane floors ``/healthz`` with (one recent rollback is an
        incident; repeats mean the upstream is actively feeding poison)."""
        with self._lock:
            horizon = time.monotonic() - self.spec.guard_recover_s
            while self._guard_rollback_times and self._guard_rollback_times[0] < horizon:
                self._guard_rollback_times.popleft()
            recent = len(self._guard_rollback_times)
        return 2 if recent >= 2 else (1 if recent == 1 else 0)

    def _after_apply(self) -> None:
        """Post-item housekeeping on the worker: retained-buffer pruning when
        a snapshot lands, and the degraded-mode durability recovery probe."""
        evaluator = self.evaluator
        if (
            self._durable
            and evaluator.store is not None
            and evaluator._last_snapshot_t != self._snap_seen_t
        ):
            self._snap_seen_t = evaluator._last_snapshot_t
            step = evaluator.store.last_step()
            if step is not None and step > self._last_snap_step:
                # a NEW snapshot landed: batches below the PREVIOUS one can
                # never be replayed again, even if the newest proves corrupt
                # and the restore ladder falls back one level
                floor = self._last_snap_step
                with self._lock:
                    for seq in [s for s in self._retained if s < floor]:
                        del self._retained[seq]
                    if floor > self._retained_floor:
                        self._retained_floor = floor
                self._last_snap_step = step
        if (not self._durable or self._dl_dirty) and time.monotonic() >= self._probe_at:
            self._probe_at = time.monotonic() + _RECOVERY_PROBE_S
            self._recover_durability()

    def _recover_durability(self) -> None:
        if not self._durable:
            self.evaluator.store = self._store_ref
            try:
                self.evaluator.snapshot()
            except OSError as err:
                if not _is_disk_error(err):
                    self.evaluator.store = None
                    raise
                self._note_write_failure(err)
                self.evaluator.store = None
                return
            with self._lock:
                self._durable = True
                self._store_ref = None
            self._snap_seen_t = None  # force the prune scan to re-baseline
        if self._dl_dirty:
            self._persist_deadletter()

    # --------------------------------------------------- dead-letter storage
    def _load_deadletter(self) -> None:
        """Re-read the quarantine at construction — dead-letter state must
        survive a daemon restart."""
        try:
            with open(self.deadletter_path, "r", encoding="utf-8") as fh:
                lines = fh.readlines()
        except (FileNotFoundError, OSError):
            return
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
                seq = int(record["seq"])
            except (ValueError, TypeError, KeyError):
                continue  # a torn line can only predate atomic_write — skip it
            self._deadletter[seq] = record
            self._quarantined.add(seq)

    def _write_deadletter(self) -> None:
        with self._lock:
            records = [self._deadletter[s] for s in sorted(self._deadletter)]
        lines = [json.dumps(record, separators=(",", ":"), sort_keys=True) for record in records]
        data = ("\n".join(lines) + "\n").encode() if lines else b""
        if faults._ACTIVE:
            try:
                faults.fire("deadletter.write")
            except faults.FaultInjected as err:
                raise OSError(errno.ENOSPC, f"injected disk exhaustion: {err}") from None
        _fmt.atomic_write(self.deadletter_path, data)

    def _persist_deadletter(self) -> None:
        """Atomic whole-file rewrite with the disk-fault retry/degrade
        discipline; on exhaustion the quarantine stays memory-only (dirty)
        and the recovery probe re-persists it."""
        with self._dl_write_lock:
            delay = _DISK_RETRY_BASE_S
            for attempt in range(_DISK_RETRIES + 1):
                try:
                    # _dl_write_lock exists ONLY to serialize deadletter-file
                    # writers; holding it across the write is its purpose and
                    # no reader/ingest path ever contends on it
                    # metriclint: disable=ML012 -- dedicated writer-serialization lock
                    self._write_deadletter()
                    self._dl_dirty = False
                    return
                except OSError as err:
                    if not _is_disk_error(err):
                        raise
                    self._note_write_failure(err)
                    if attempt < _DISK_RETRIES:
                        # backoff under the dedicated writer-serialization lock
                        # is intentional: a concurrent writer SHOULD wait out
                        # the retry window rather than race the rewrite
                        # metriclint: disable=ML012 -- intentional backoff under writer lock
                        time.sleep(delay)
                        delay *= 2
            self._dl_dirty = True

    def _quarantine(self, seq: int, err: BaseException, guard: Optional[Dict[str, Any]] = None) -> None:
        with self._lock:
            entry = self._retained.pop(seq, None)
            record = {
                "seq": seq,
                "stream": self.spec.name,
                "batch": entry[0] if entry is not None else None,
                "error": f"{type(err).__name__}: {err}",
                "attempts": self._crash_count,
                "quarantined_at": time.time(),
            }
            if guard is not None:
                # the StateGuard verdict for the poisoned batch: nan/inf/
                # domain row counts — metricdoctor pretty-prints these
                record["guard"] = guard
            self._deadletter[seq] = record
            self._quarantined.add(seq)
        _obs_counters.inc("serve.deadletter")
        self._persist_deadletter()

    # ------------------------------------------------------------ supervision
    def _supervise(self, err: BaseException) -> bool:
        """Decide the crashed worker's fate: True = restart (after backoff),
        False = stream parked/failed/halted. Runs on the worker thread."""
        applying, self._applying = self._applying, False
        if isinstance(err, _Halt):
            self._release_waiters(RuntimeError(str(err)))
            return False
        if isinstance(err, _Unrecoverable) or not self._opened_once:
            self._fail(err)
            return False
        with self._lock:
            halted = self.state == "failed"  # deleted/abandoned while crashing
            self.last_failure = f"{type(err).__name__}: {err}"
        if halted:
            self._release_waiters(err)
            return False
        _obs_counters.inc("serve.worker_crashes")
        if not applying:
            # the crash hit between batches (op/feed/open): the evaluator is
            # still cursor-consistent — persist it so the restart replays the
            # shortest possible suffix (best-effort; degradation handles disk)
            try:
                if self._durable and self.evaluator.store is not None:
                    self.evaluator.snapshot()
                    self._after_apply()
            except BaseException:
                pass
        quarantined_now = False
        if applying:
            culprit = int(self.evaluator.cursor)
            if culprit == self._crash_seq:
                # _crash_seq/_crash_count are confined to the single
                # supervisor thread; no other thread reads or writes them
                # metriclint: disable=ML012 -- supervisor-thread-confined counter
                self._crash_count += 1
            else:
                self._crash_seq, self._crash_count = culprit, 1
            if self._crash_count >= self.spec.poison_threshold and culprit not in self._quarantined:
                self._quarantine(culprit, err)
                quarantined_now = True
                self._crash_seq, self._crash_count = None, 0
                # the poisonous cause is removed — fresh restart budget
                self._failure_times.clear()
        if self.circuit == "half_open" and not quarantined_now:
            self._park(err)
            return False
        now = time.monotonic()
        self._failure_times.append(now)
        while self._failure_times and now - self._failure_times[0] > self.spec.restart_window_s:
            self._failure_times.popleft()
        if len(self._failure_times) > self.spec.max_restarts:
            self._park(err)
            return False
        with self._lock:
            self.restarts += 1
        _obs_counters.inc("serve.worker_restarts")
        attempt = len(self._failure_times)
        base = min(self.spec.backoff_max_s, self.spec.backoff_base_s * (2 ** (attempt - 1)))
        deadline = time.monotonic() + base + random.uniform(0.0, base)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return True
            with self._lock:
                abandoned = self.state == "failed"
            if abandoned:  # deleted during the backoff: don't wait it out
                self._release_waiters(err)
                return False
            time.sleep(min(0.02, remaining))

    def _park(self, err: BaseException) -> None:
        """Open the circuit: the stream stops restarting and waits for a
        manual :meth:`revive`. Pending acked batches stay retained (NOT
        latched as dropped) — a revive applies them."""
        with self._lock:
            self.circuit = "open"
        wrapped = RuntimeError(
            f"circuit open after {len(self._failure_times)} worker failure(s) within"
            f" {self.spec.restart_window_s:g}s (last: {type(err).__name__}: {err})"
            f" — revive {self.spec.name!r} to retry"
        )
        _obs_counters.inc("serve.circuit_open")
        self._fail(wrapped, latch_dropped=False)

    def revive(self, timeout_s: float = 60.0) -> Dict[str, Any]:
        """Half-open a parked stream's circuit and start one probe worker
        incarnation: its first successful apply closes the circuit, its first
        failure re-opens it. Only valid with the circuit ``open``."""
        with self._lock:
            if not (self.state == "failed" and self.circuit == "open"):
                return wire.error(
                    "bad_request",
                    f"stream {self.spec.name} is not parked"
                    f" (state {self.state}, circuit {self.circuit})",
                )
        self._thread.join(timeout=10.0)  # the parked worker is exiting; let it
        with self._lock:
            self.circuit = "half_open"
            self.state = "starting"
            self.failure = None
            self._failure_times.clear()
            self._crash_seq, self._crash_count = None, 0
            self._evaluator_dirty = True
            self._ready = threading.Event()
            self._finished = threading.Event()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=f"metricserve-{self.spec.name}"
            )
        try:
            next_seq = self.start(timeout_s)
        except (RuntimeError, TimeoutError) as err:
            return wire.error("failed", f"revive of {self.spec.name} failed: {err}")
        return wire.ok(stream=self.spec.name, revived=True, next_seq=next_seq, circuit=self.circuit)

    def _note_applied(self) -> None:
        """A batch fully applied: reset poison accounting and close a
        half-open circuit (the probe incarnation proved itself)."""
        if self._crash_seq is not None and self.evaluator.cursor > self._crash_seq:
            # the SUSPECT batch itself applied cleanly, so it is not poison;
            # a replayed batch BELOW the suspect proves nothing — resetting
            # there would let a poison batch behind a long replay suffix
            # crash-loop forever without ever reaching poison_threshold
            self._crash_seq, self._crash_count = None, 0
        if self.circuit != "closed":
            with self._lock:
                self.circuit = "closed"
            self._failure_times.clear()

    def _exec_op(self, op: _Op) -> None:
        try:
            if op.name == "flush":
                recovered = True
                try:
                    step = self.evaluator.snapshot()
                except OSError as err:
                    if not _is_disk_error(err):
                        raise
                    recovered = self._handle_disk_fault(err)
                    step = self.evaluator.cursor if recovered else None
                op.finish(result={
                    "snapshot_step": step,
                    "cursor": self.evaluator.cursor,
                    "durable": bool(self._durable),
                })
            elif op.name == "export":
                # captured ON the worker thread, so the payload is a
                # consistent cut: exactly the applied batches, cursor == the
                # watermark stamped on the slice
                op.finish(result=self.evaluator._payload())
            elif op.name == "skip":
                cursor_before = self.evaluator.cursor
                try:
                    self.evaluator.serve_skip()
                except OSError as err:
                    if _is_disk_error(err) and self.evaluator.cursor > cursor_before:
                        self._handle_disk_fault(err)
                    else:
                        raise
                op.finish()
            else:
                raise ValueError(f"unknown stream op {op.name!r}")
        except BaseException as err:
            op.finish(error=err)
            raise

    def _fail(self, err: BaseException, latch_dropped: bool = True) -> None:
        with self._lock:
            if self.state not in ("drained", "failed"):
                self.state = "failed"
                self.failure = f"{type(err).__name__}: {err}"
                if latch_dropped:
                    self._latch_dropped_locked()
        # the worker is dead: withdraw the evaluator's live probes so a
        # parked stream's last watchdog margin can't poison a LATER daemon's
        # /healthz in this process (revive re-registers via serve_open)
        try:
            self.evaluator._unregister_probes()
        except Exception:
            pass
        self._release_waiters(err)

    def _release_waiters(self, err: BaseException) -> None:
        """Fail every parked/queued op with the cause (queued batches are
        dropped from the queue — the retained buffer still holds them)."""
        while self._pending_ops:
            self._pending_ops.popleft().finish(error=err)
        while True:
            try:
                kind, payload = self._queue.get_nowait()
            except queue.Empty:
                break
            if kind == "op":
                payload.finish(error=err)

    def _latch_dropped_locked(self) -> None:
        """Latch acked-but-never-applied batches into the dropped counter
        (once — parked streams latch only if later deleted, not on park)."""
        if self._dropped_latched:
            return
        self._dropped_latched = True
        pending = max(0, self.next_seq - self.evaluator.cursor)
        if pending:
            self.dropped += pending
            _obs_counters.inc("serve.dropped_batches", pending)

    # -------------------------------------------------------------- ingest
    def offer(
        self, seq: Any, batch: Any, *, block: bool = False, deadline_s: Optional[float] = None
    ) -> Dict[str, Any]:
        """Admit one wire batch under the seq protocol; returns a wire
        envelope. ``block=False`` is the HTTP mode (full queue → an immediate
        ``backpressure`` error the daemon maps to 429 + ``Retry-After``);
        ``block=True`` is the socket mode (wait up to ``deadline_s`` for a
        slot, then the same error)."""
        if not self._ready.is_set():
            if not self._ready.wait(deadline_s if block and deadline_s else 0.05):
                return wire.error(
                    "backpressure", f"stream {self.spec.name} is still opening", retry_after_s=0.1
                )
        if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
            return wire.error("bad_request", f"seq must be a non-negative int, got {seq!r}")
        try:
            decoded = decode_batch(batch)
        except wire.WireError as err:
            return wire.error("bad_request", str(err))
        bad = self._check_payload(decoded)
        if bad is not None:
            return bad
        if faults._ACTIVE:
            faults.fire("serve.ingest")
        # seq check + enqueue + ack are ONE atomic step under the lock —
        # two racing offers of the same seq must not both enqueue. The socket
        # mode retries the non-blocking attempt until its deadline rather
        # than blocking inside the lock (status/gauges stay responsive).
        deadline = None if deadline_s is None else time.monotonic() + deadline_s
        while True:
            with self._lock:
                if self.state == "failed":
                    message = f"stream {self.spec.name} failed: {self.failure}"
                    if self.circuit == "open":
                        message += " (circuit open — revive to retry)"
                    return wire.error("failed", message, circuit=self.circuit)
                if self.state in ("draining", "drained"):
                    return wire.error("draining", f"stream {self.spec.name} is {self.state}")
                if seq < self.next_seq:
                    # duplicate replay — ack idempotently, nothing re-applied
                    return wire.ok(stream=self.spec.name, duplicate=True, next_seq=self.next_seq)
                if seq > self.next_seq:
                    return wire.error(
                        "bad_seq",
                        f"gap: got seq {seq}, expected {self.next_seq} — rewind the replay",
                        expected=self.next_seq,
                    )
                try:
                    self._queue.put_nowait(("batch", (seq, decoded)))
                except queue.Full:
                    pass
                else:
                    self._admit_locked(seq, batch, decoded)
                    return wire.ok(stream=self.spec.name, next_seq=self.next_seq)
            if not block or (deadline is not None and time.monotonic() >= deadline):
                return wire.error(
                    "backpressure",
                    f"stream {self.spec.name} ingest queue is full ({self.spec.queue_max})",
                    retry_after_s=0.05,
                )
            time.sleep(0.005)

    def _admit_locked(self, seq: int, batch: Any, decoded: Tuple[Any, ...]) -> None:
        """Book-keeping for an enqueued batch: pin the aval signature at the
        first accept, retain the payload for crash replay, advance the ack
        watermark. Caller holds the lock and has already enqueued."""
        if self._avals is None:
            self._avals = _batch_signature(decoded)
        if seq not in self._quarantined:
            self._retained[seq] = (batch, decoded)
            while len(self._retained) > self._retain_cap:
                oldest = next(iter(self._retained))
                del self._retained[oldest]
                self._retained_floor = max(self._retained_floor, oldest + 1)
        self.next_seq = seq + 1

    def _check_payload(self, decoded: Tuple[Any, ...]) -> Optional[Dict[str, Any]]:
        """``bad_payload`` wire error when ``decoded`` disagrees with the
        stream's first-accepted batch avals, else None. Leading (batch) dims
        may differ; part count, dtypes and trailing shapes may not."""
        expected = self._avals
        if expected is None:
            return None
        got = _batch_signature(decoded)
        if got == expected:
            return None
        if len(got) != len(expected):
            message = f"batch has {len(got)} part(s), stream {self.spec.name} expects {len(expected)}"
        else:
            part = next(i for i in range(len(got)) if got[i] != expected[i])
            message = (
                f"part {part}: expected trailing shape {expected[part][0]} dtype"
                f" {expected[part][1]}, got {got[part][0]} dtype {got[part][1]}"
            )
        return wire.error(
            "bad_payload",
            f"payload disagrees with the stream's first-accepted batch — {message}",
            expected=[[list(shape), dtype] for shape, dtype in expected],
            got=[[list(shape), dtype] for shape, dtype in got],
        )

    # ------------------------------------------------------------- control
    def _submit_op(self, name: str, timeout_s: float) -> _Op:
        op = _Op(name)
        with self._lock:
            if self.state == "failed":
                op.finish(error=RuntimeError(self.failure or "stream failed"))
                return op
            if self.state in ("draining", "drained") and name != "drain":
                op.finish(error=RuntimeError(f"stream {self.spec.name} is {self.state}"))
                return op
            if name == "drain":
                if self.state == "drained":
                    op.finish(result=self.result)
                    return op
                if self.state == "draining":
                    live = self._drain_op
                    if live is not None and (not live.done.is_set() or live.error is None):
                        return live  # ride the drain already in flight
                    # the previous drain died with its worker: submit a fresh one
                self.state = "draining"
                self._drain_op = op
        deadline = time.monotonic() + timeout_s
        while True:
            target = self._queue
            try:
                target.put(("op", op), timeout=0.1)
            except queue.Full:
                if time.monotonic() >= deadline:
                    op.finish(
                        error=RuntimeError(f"stream {self.spec.name} queue stayed full for {timeout_s}s")
                    )
                    return op
                continue
            if target is self._queue or op.done.is_set():
                return op
            # a restart swapped the queue mid-put: the op may sit in a
            # superseded queue nobody reads — re-submit into the live one
            # (flush is idempotent; drain dedups through _drain_op)

    def flush(self, timeout_s: float = 60.0) -> Dict[str, Any]:
        """Snapshot now, AFTER everything already admitted has applied."""
        op = self._submit_op("flush", timeout_s)
        if not op.done.wait(timeout_s):
            return wire.error("failed", f"flush of {self.spec.name} timed out after {timeout_s}s")
        if op.error is not None:
            return wire.error("failed", f"flush failed: {op.error}")
        return wire.ok(stream=self.spec.name, **op.result)

    def export(self, timeout_s: float = 60.0, fingerprint: Optional[str] = None) -> Dict[str, Any]:
        """The ``/v1/state`` verb: a consistent state slice for federation.

        The payload (PR-2 checkpoint format, arrays wire-encoded with their
        dtypes) is captured on the worker thread via the op queue, so the
        stamped ``watermark`` is exactly the applied-batch cursor of the
        serialized state — an aggregator folding it can dedup a restarted
        leaf's replayed prefix against it. A drained stream exports its
        final state directly (the worker is gone; nothing mutates it).
        ``fingerprint`` pins the export: a mismatch is the typed
        ``fingerprint_mismatch`` error (HTTP 409) instead of a payload the
        caller would have to reject after the fact.
        """
        have = self.evaluator._fingerprint()
        if fingerprint is not None and fingerprint != have:
            return wire.error(
                "fingerprint_mismatch",
                f"stream {self.spec.name} carries registry fingerprint {have},"
                f" the export was pinned to {fingerprint}",
                expected=fingerprint,
                got=have,
            )
        with self._lock:
            state = self.state
        if state == "drained":
            payload = self.evaluator._payload()
        else:
            op = self._submit_op("export", timeout_s)
            if not op.done.wait(timeout_s):
                return wire.error("failed", f"export of {self.spec.name} timed out after {timeout_s}s")
            if op.error is not None:
                return wire.error("failed", f"export failed: {op.error}")
            payload = op.result
        return wire.ok(
            stream=self.spec.name,
            watermark=int(payload["cursor"]),
            kind=payload["kind"],
            fingerprint=have,
            windowed=self.spec.window is not None,
            spec={"target": self.spec.target, "kwargs": self.spec.kwargs,
                  "fused": self.spec.fused, "fused_options": self.spec.fused_options},
            state=wire.encode_state(payload),
        )

    def drain(self, timeout_s: float = 300.0) -> Dict[str, Any]:
        """Apply every admitted batch, final snapshot + compute; returns the
        results envelope. Idempotent — a second drain returns the same
        results."""
        if faults._ACTIVE:
            faults.fire("serve.drain")
        op = self._submit_op("drain", timeout_s)
        if not op.done.wait(timeout_s):
            return wire.error("failed", f"drain of {self.spec.name} timed out after {timeout_s}s")
        if op.error is not None:
            return wire.error("failed", f"drain failed: {op.error}")
        return wire.ok(stream=self.spec.name, cursor=self.evaluator.cursor, results=op.result)

    def abandon(self) -> int:
        """Stop the stream WITHOUT computing (the delete path): unblocks the
        worker, latches acked-but-unapplied batches as dropped, returns the
        dropped count."""
        with self._lock:
            already = self.state in ("drained", "failed")
            if not already:
                self.state = "failed"
                self.failure = "deleted"
            if self.state == "failed":
                # a parked stream deferred this latch hoping for a revive;
                # deletion makes its pending suffix unrecoverable for real
                self._latch_dropped_locked()
        if not already:
            # wake the worker: the abandon sentinel ends the source without a
            # final compute; the state machine above already stopped offers
            try:
                self._queue.put(("op", _Op("abandon")), timeout=5.0)
            except queue.Full:
                pass
        self._thread.join(timeout=10.0)
        return self.dropped

    # --------------------------------------------------------- dead letters
    def deadletter_list(self) -> Dict[str, Any]:
        """The quarantine, oldest first (payloads included — they are the
        recovery artifact)."""
        with self._lock:
            records = [dict(self._deadletter[s]) for s in sorted(self._deadletter)]
        return wire.ok(stream=self.spec.name, deadletter=records, depth=len(records))

    def deadletter_requeue(self, seq: Any) -> Dict[str, Any]:
        """Re-admit a quarantined payload through the normal exactly-once
        path at the CURRENT watermark (it gets a new seq). If re-admission
        fails the record is reinstated — a dead letter is never lost."""
        if not isinstance(seq, int) or isinstance(seq, bool):
            return wire.error("bad_request", f"seq must be an int, got {seq!r}")
        with self._lock:
            record = self._deadletter.get(seq)
            if record is not None and record.get("batch") is None:
                return wire.error(
                    "bad_request",
                    f"dead-letter seq {seq} kept no payload (evicted before quarantine) — purge it",
                )
            if record is not None:
                del self._deadletter[seq]
                self._quarantined.discard(seq)
        if record is None:
            return wire.error(
                "not_found", f"stream {self.spec.name} has no dead-letter record for seq {seq}"
            )
        self._persist_deadletter()
        reply = self._offer_at_watermark(record["batch"])
        if not reply.get("ok"):
            with self._lock:
                self._deadletter[seq] = record
                self._quarantined.add(seq)
            self._persist_deadletter()
            return reply
        return wire.ok(
            stream=self.spec.name, requeued=seq, as_seq=reply["as_seq"], next_seq=reply["next_seq"]
        )

    def _offer_at_watermark(self, batch: Any, deadline_s: float = 5.0) -> Dict[str, Any]:
        """Admit ``batch`` at whatever ``next_seq`` is when the slot opens —
        the requeue path must reserve its seq atomically (racing a concurrent
        client offer for a fixed seq could silently orphan the payload)."""
        try:
            decoded = decode_batch(batch)
        except wire.WireError as err:
            return wire.error("bad_request", str(err))
        bad = self._check_payload(decoded)
        if bad is not None:
            return bad
        deadline = time.monotonic() + deadline_s
        while True:
            with self._lock:
                if self.state == "failed":
                    return wire.error("failed", f"stream {self.spec.name} failed: {self.failure}")
                if self.state in ("draining", "drained"):
                    return wire.error("draining", f"stream {self.spec.name} is {self.state}")
                seq = self.next_seq
                try:
                    self._queue.put_nowait(("batch", (seq, decoded)))
                except queue.Full:
                    pass
                else:
                    self._admit_locked(seq, batch, decoded)
                    return wire.ok(stream=self.spec.name, as_seq=seq, next_seq=self.next_seq)
            if time.monotonic() >= deadline:
                return wire.error(
                    "backpressure",
                    f"stream {self.spec.name} ingest queue is full ({self.spec.queue_max})",
                    retry_after_s=0.05,
                )
            time.sleep(0.005)

    def deadletter_purge(self, seq: Any) -> Dict[str, Any]:
        """Drop a quarantined record for good; its batch counts as dropped
        (acked, never applied, now unrecoverable)."""
        if not isinstance(seq, int) or isinstance(seq, bool):
            return wire.error("bad_request", f"seq must be an int, got {seq!r}")
        with self._lock:
            record = self._deadletter.pop(seq, None)
            if record is not None:
                self._quarantined.discard(seq)
                self.dropped += 1
        if record is None:
            return wire.error(
                "not_found", f"stream {self.spec.name} has no dead-letter record for seq {seq}"
            )
        _obs_counters.inc("serve.dropped_batches")
        self._persist_deadletter()
        with self._lock:
            depth = len(self._deadletter)
        return wire.ok(stream=self.spec.name, purged=seq, depth=depth)

    # -------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        with self._lock:
            info: Dict[str, Any] = {
                "name": self.spec.name,
                "state": self.state,
                "cursor": self.evaluator.cursor,
                "next_seq": self.next_seq,
                "pending": max(0, self.next_seq - self.evaluator.cursor),
                "queue_depth": self._queue.qsize(),
                "queue_max": self.spec.queue_max,
                "dropped": self.dropped,
                "kind": self.evaluator._kind(),
                "restarts": self.restarts,
                "circuit": self.circuit,
                "deadletter_depth": len(self._deadletter),
                "durable": bool(self._durable and not self._dl_dirty),
                "write_failures": self.write_failures,
            }
            if self.failure is not None:
                info["failure"] = self.failure
            if self.last_failure is not None:
                info["last_failure"] = self.last_failure
            if self.result is not None:
                info["results"] = self.result
            guard_metric = self._guard_metric
        if guard_metric is not None:
            guard_info: Dict[str, Any] = {"policy": getattr(guard_metric, "_guard_policy", None)}
            try:
                from torchmetrics_tpu.robustness.guard import guard_counters

                guard_info.update(guard_counters(guard_metric))
            except Exception:
                pass  # a mid-trace read must never take status down
            # cumulative stream-side counts LAST: guard_counters' "poisoned"
            # is the latch (always 0 again after a successful rollback)
            guard_info["rollbacks"] = self.guard_rollbacks
            guard_info["poisoned"] = self.guard_poisoned_total
            info["guard"] = guard_info
        return info

    def health_code(self) -> int:
        """0 ok … 3 stalled (the ``serve.<name>.health_state`` gauge): a
        failed/parked stream is stalled; a queue ≥ 90% full is stalling
        (admission is about to push back); a degraded (in-memory-only)
        stream is degraded while it still serves. Watchdog-margin decay
        rides the evaluator's own runner probe, not this code."""
        with self._lock:
            code = _STATE_HEALTH.get(self.state, 0)
            if self.state == "serving" and self._queue.qsize() >= max(1, int(0.9 * self.spec.queue_max)):
                code = max(code, 1)
            if self.state in ("serving", "draining") and (not self._durable or self._dl_dirty):
                code = max(code, 2)
            return code

    def gauges(self) -> Dict[str, float]:
        """The ``serve.<name>.*`` gauge family (daemon probe fodder), plus
        the metric's own ``drift.<name>.*`` family when the target publishes
        serve gauges (the drift subsystem: psi/kl/ks/severity/cardinality)."""
        prefix = f"serve.{self.spec.name}."
        with self._lock:
            state, qsize = self.state, self._queue.qsize()
            next_seq, dropped = self.next_seq, self.dropped
            restarts, circuit = self.restarts, self.circuit
            deadletter_depth = len(self._deadletter)
            durable = self._durable and not self._dl_dirty
        out = {
            prefix + "health_state": float(self.health_code()),
            prefix + "state": float(STATE_CODES.get(state, 0)),
            prefix + "cursor": float(self.evaluator.cursor),
            prefix + "pending": float(max(0, next_seq - self.evaluator.cursor)),
            prefix + "queue_depth": float(qsize),
            prefix + "dropped": float(dropped),
            prefix + "restarts": float(restarts),
            prefix + "circuit_state": float(CIRCUIT_CODES.get(circuit, 0)),
            prefix + "deadletter_depth": float(deadletter_depth),
            prefix + "durability": 1.0 if durable else 0.0,
        }
        serve_fn = getattr(getattr(self.evaluator, "metric", None), "serve_gauges", None)
        if callable(serve_fn):
            try:
                for key, val in serve_fn().items():
                    out[f"drift.{self.spec.name}.{key}"] = float(val)
            except Exception:  # a gauge read must never take the probe down
                _obs_counters.inc("serve.gauge_read_failures")
        guard_metric = self._guard_metric
        if guard_metric is not None:
            gp = f"guard.{self.spec.name}."
            try:
                from torchmetrics_tpu.robustness.guard import guard_counters

                counters = guard_counters(guard_metric)
                out[gp + "masked"] = float(counters["masked_rows"])
                out[gp + "rejected"] = float(counters["rejected_batches"])
                out[gp + "nan_rows"] = float(counters["nan_rows"])
                out[gp + "inf_rows"] = float(counters["inf_rows"])
                out[gp + "domain_rows"] = float(counters["domain_rows"])
                out[gp + "rollbacks"] = float(self.guard_rollbacks)
                out[gp + "poisoned"] = float(self.guard_poisoned_total)
                out[gp + "health_state"] = float(self._guard_health_code())
            except Exception:  # ditto: counters read device scalars
                _obs_counters.inc("serve.gauge_read_failures")
        return out
