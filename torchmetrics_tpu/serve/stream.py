# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""One durable evaluation stream inside a ``metricserve`` daemon.

A :class:`Stream` is the service-side unit the daemon multiplexes: one named
(model-version × dataset) evaluation owning

- a declarative :class:`StreamSpec` (factory import path + evaluator knobs,
  the wire-facing description a ``create`` request carries),
- its own :class:`~torchmetrics_tpu.robustness.store.CheckpointStore`
  sub-directory (restart = resume from the snapshot cursor, never recount),
- a bounded ingest queue (admission control — the **only** place a batch
  waits) feeding ONE worker thread that pumps the evaluator's open-loop
  serve API (:meth:`~torchmetrics_tpu.robustness.runner.StreamingEvaluator.
  serve_step`), optionally through a
  :class:`~torchmetrics_tpu.parallel.feed.DeviceFeed` so host decode overlaps
  device work exactly like a batch run.

**Exactly-once ingest.** Every batch carries a client sequence number. The
stream acks ``seq == next_seq`` (advancing), re-acks ``seq < next_seq``
(duplicate — idempotent replay), and rejects ``seq > next_seq`` with the
expected value (gap — the client rewinds). After a crash ``next_seq``
restarts at the restored snapshot cursor, so the client replays exactly the
acked-but-unpersisted suffix and no sample is counted twice or dropped.

**Control ops ride the batch queue.** flush/drain must serialize with the
batches already admitted, so ops travel the same queue. With a DeviceFeed in
front, an op enqueues a leafless ``()`` marker into the feed (an empty
pytree — ``device_put`` stages nothing) and parks the op itself on a FIFO
side-channel; the worker executes the op when the marker surfaces, which is
exactly its queue position.

**Dropped-batch accounting.** ``serve.dropped_batches`` counts batches the
daemon ACKED but will never apply — the suffix abandoned when a stream fails
or is deleted with work still queued. Graceful drain applies everything
first, and a crash never acks, so the counter stays zero on every healthy
path; the sustained-load bench latches on it.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.robustness.store import CheckpointStore
from torchmetrics_tpu.serve import wire

__all__ = ["StreamSpec", "Stream", "decode_batch", "resolve_target"]

#: ``()`` is the op marker: real batches are always NON-empty tuples (or a
#: bare array), so an empty tuple is unambiguous — and leafless, so a
#: DeviceFeed stages it as a no-op instead of choking on non-array leaves
_OP_MARKER: Tuple[()] = ()

_STATE_HEALTH = {
    "starting": 0,
    "serving": 0,
    "draining": 0,
    "drained": 0,
    "failed": 3,
}

#: numeric state codes for the ``serve.<name>.state`` gauge (gauges are
#: floats; scrapers map back through this table)
STATE_CODES = {"starting": 0, "serving": 1, "draining": 2, "drained": 3, "failed": 4}


def resolve_target(path: str, kwargs: Optional[Dict[str, Any]] = None) -> Any:
    """Build a stream's metric target from a ``module:callable`` factory
    path — the declarative form a wire ``create`` carries (a server cannot
    receive live Python objects). The factory returns a ``Metric``,
    ``MetricCollection`` or ``SlicedPlan``; see
    :mod:`torchmetrics_tpu.serve.factories` for ready-made ones."""
    module_name, sep, attr = path.partition(":")
    if not sep or not module_name or not attr:
        raise ValueError(f"target must be 'module:callable', got {path!r}")
    import importlib

    factory = importlib.import_module(module_name)
    for part in attr.split("."):
        factory = getattr(factory, part)
    return factory(**(kwargs or {}))


def decode_batch(batch: Any) -> Tuple[Any, ...]:
    """Wire batch (list of nested number lists, one per positional update
    argument) → tuple of arrays. One decode path for the daemon AND for
    parity tests replaying the same stream in-process, so a resumed service
    run compares bitwise against an uninterrupted one."""
    import numpy as np

    if not isinstance(batch, (list, tuple)) or not batch:
        raise wire.WireError("batch must be a non-empty JSON list (one entry per update argument)")
    return tuple(np.asarray(part) for part in batch)


class StreamSpec:
    """Declarative stream description — what a wire ``create`` carries.

    Args:
        name: registry key; one path component (no ``/``, no ``.`` — it names
            a store sub-directory and a ``serve.<name>.*`` gauge family).
        target: ``module:callable`` factory path for the metric target.
        kwargs: keyword arguments for the factory.
        fused: drive a ``MetricCollection`` target through the fused plane.
        fused_options: fused-plan build kwargs (``cat_capacity`` etc.; a
            fused collection with cat-state members NEEDS ``cat_capacity``
            so its carries get fixed-capacity buffers).
        window: ``WindowRing`` knobs (``slots`` + ``every_n``/``every_s``)
            wrapped around the target, or ``None``.
        snapshot_every_n / snapshot_every_s: evaluator snapshot cadence.
        queue_max: ingest queue bound (admission control), default 64.
        use_feed: stage batches through a ``DeviceFeed`` (default True).
        watchdog_timeout_s / on_stall: evaluator watchdog policy.
    """

    _FIELDS = (
        "name", "target", "kwargs", "fused", "fused_options", "window", "snapshot_every_n",
        "snapshot_every_s", "queue_max", "use_feed", "watchdog_timeout_s", "on_stall",
    )

    def __init__(
        self,
        name: str,
        target: str,
        kwargs: Optional[Dict[str, Any]] = None,
        fused: bool = False,
        fused_options: Optional[Dict[str, Any]] = None,
        window: Optional[Dict[str, Any]] = None,
        snapshot_every_n: Optional[int] = None,
        snapshot_every_s: Optional[float] = None,
        queue_max: int = 64,
        use_feed: bool = True,
        watchdog_timeout_s: Optional[float] = None,
        on_stall: str = "raise",
    ) -> None:
        if not name or any(ch in name for ch in "/\\.") or name != name.strip():
            raise ValueError(
                f"stream name {name!r} must be one clean path component (it names a store"
                " sub-directory and a serve.<name>.* gauge family — no '/', '\\\\' or '.')"
            )
        if queue_max < 1:
            raise ValueError(f"queue_max must be >= 1, got {queue_max}")
        self.name = name
        self.target = target
        self.kwargs = dict(kwargs or {})
        self.fused = bool(fused)
        self.fused_options = dict(fused_options) if fused_options else None
        self.window = dict(window) if window else None
        self.snapshot_every_n = snapshot_every_n
        self.snapshot_every_s = snapshot_every_s
        self.queue_max = int(queue_max)
        self.use_feed = bool(use_feed)
        self.watchdog_timeout_s = watchdog_timeout_s
        self.on_stall = on_stall

    def to_wire(self) -> Dict[str, Any]:
        return {field: getattr(self, field) for field in self._FIELDS}

    @classmethod
    def from_wire(cls, obj: Dict[str, Any]) -> "StreamSpec":
        unknown = sorted(set(obj) - set(cls._FIELDS))
        if unknown:
            raise wire.WireError(f"unknown StreamSpec field(s): {', '.join(unknown)}")
        if "name" not in obj or "target" not in obj:
            raise wire.WireError("StreamSpec needs at least 'name' and 'target'")
        return cls(**obj)

    def build_evaluator(self, store_dir: str) -> Any:
        """Materialize the evaluator this spec describes over ``store_dir``.

        ``write_rank=None``: a daemon rank owns its whole base directory, so
        EVERY rank persists (multi-host deployments give each rank its own
        base dir and fold state through the merge-state sync at compute)."""
        from torchmetrics_tpu.robustness.runner import StreamingEvaluator

        metric = resolve_target(self.target, self.kwargs)
        ring = None
        if self.window is not None:
            from torchmetrics_tpu.parallel.windowing import WindowRing

            ring = WindowRing(metric, **self.window)
        store = CheckpointStore(store_dir, keep_last=3, write_rank=None)
        return StreamingEvaluator(
            metric,
            store=store,
            snapshot_every_n=self.snapshot_every_n,
            snapshot_every_s=self.snapshot_every_s,
            fused=self.fused,
            fused_options=self.fused_options,
            window_ring=ring,
            watchdog_timeout_s=self.watchdog_timeout_s,
            on_stall=self.on_stall,
        )


class _Op:
    """One control op riding the batch queue (see the module docstring)."""

    __slots__ = ("name", "done", "result", "error")

    def __init__(self, name: str) -> None:
        self.name = name
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None

    def finish(self, result: Any = None, error: Optional[BaseException] = None) -> None:
        self.result, self.error = result, error
        self.done.set()


class Stream:
    """One running stream: spec + evaluator + bounded queue + worker thread."""

    def __init__(self, spec: StreamSpec, store_dir: str) -> None:
        self.spec = spec
        self.store_dir = str(store_dir)
        self.evaluator = spec.build_evaluator(self.store_dir)
        self._queue: "queue.Queue[Tuple[str, Any]]" = queue.Queue(maxsize=spec.queue_max)
        self._pending_ops: "deque[_Op]" = deque()
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._finished = threading.Event()
        self.state = "starting"
        self.next_seq = 0  # acked watermark; meaningful once _ready is set
        self.result: Optional[Any] = None
        self.failure: Optional[str] = None
        self.dropped = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"metricserve-{spec.name}"
        )

    # ----------------------------------------------------------- lifecycle
    def start(self, timeout_s: float = 60.0) -> int:
        """Start the worker, wait for the durable open (snapshot restore) to
        finish, and return the cursor batches resume from — the ``next_seq``
        a client must replay from."""
        self._thread.start()
        if not self._ready.wait(timeout_s):
            raise TimeoutError(f"stream {self.spec.name} did not open within {timeout_s}s")
        with self._lock:
            if self.state == "failed":
                raise RuntimeError(f"stream {self.spec.name} failed to open: {self.failure}")
            return self.next_seq

    def _run(self) -> None:
        try:
            start = self.evaluator.serve_open()
            with self._lock:
                self.next_seq = start
                self.state = "serving"
            self._ready.set()
            source = self._source()
            if self.spec.use_feed:
                from torchmetrics_tpu.parallel.feed import DeviceFeed

                items: Any = DeviceFeed(source)
            else:
                items = source
            for item in items:
                if isinstance(item, tuple) and not item:
                    self._exec_op(self._pending_ops.popleft())
                else:
                    self.evaluator.serve_step(item)
            # the source ended: a drain (or abandon) op asked for the close
            final_op = self._pending_ops.popleft()
            if final_op.name == "abandon":
                self.evaluator._unregister_probes()
                final_op.finish()
            else:
                result = self.evaluator.serve_close()
                with self._lock:
                    self.result = wire.to_jsonable(result)
                    self.state = "drained"
                final_op.finish(result=self.result)
        except BaseException as err:  # the worker must report, never vanish
            self._fail(err)
        finally:
            self._ready.set()
            self._finished.set()

    def _source(self) -> Any:
        """Queue → iterator the (optional) DeviceFeed stages. Ends at drain."""
        while True:
            kind, payload = self._queue.get()
            if kind == "batch":
                yield payload
            elif payload.name in ("drain", "abandon"):
                self._pending_ops.append(payload)
                return
            else:
                self._pending_ops.append(payload)
                yield _OP_MARKER

    def _exec_op(self, op: _Op) -> None:
        try:
            if op.name == "flush":
                step = self.evaluator.snapshot()
                op.finish(result={"snapshot_step": step, "cursor": self.evaluator.cursor})
            else:
                raise ValueError(f"unknown stream op {op.name!r}")
        except BaseException as err:
            op.finish(error=err)
            raise

    def _fail(self, err: BaseException) -> None:
        with self._lock:
            if self.state in ("drained", "failed"):
                return
            self.state = "failed"
            self.failure = f"{type(err).__name__}: {err}"
            self._latch_dropped_locked()
        # release every parked waiter with the cause
        while self._pending_ops:
            self._pending_ops.popleft().finish(error=err)
        while True:
            try:
                kind, payload = self._queue.get_nowait()
            except queue.Empty:
                break
            if kind == "op":
                payload.finish(error=err)

    def _latch_dropped_locked(self) -> None:
        """Latch acked-but-never-applied batches into the dropped counter."""
        pending = max(0, self.next_seq - self.evaluator.cursor)
        if pending:
            self.dropped += pending
            _obs_counters.inc("serve.dropped_batches", pending)

    # -------------------------------------------------------------- ingest
    def offer(
        self, seq: Any, batch: Any, *, block: bool = False, deadline_s: Optional[float] = None
    ) -> Dict[str, Any]:
        """Admit one wire batch under the seq protocol; returns a wire
        envelope. ``block=False`` is the HTTP mode (full queue → an immediate
        ``backpressure`` error the daemon maps to 429 + ``Retry-After``);
        ``block=True`` is the socket mode (wait up to ``deadline_s`` for a
        slot, then the same error)."""
        if not self._ready.is_set():
            if not self._ready.wait(deadline_s if block and deadline_s else 0.05):
                return wire.error(
                    "backpressure", f"stream {self.spec.name} is still opening", retry_after_s=0.1
                )
        if not isinstance(seq, int) or isinstance(seq, bool) or seq < 0:
            return wire.error("bad_request", f"seq must be a non-negative int, got {seq!r}")
        try:
            decoded = decode_batch(batch)
        except wire.WireError as err:
            return wire.error("bad_request", str(err))
        if faults._ACTIVE:
            faults.fire("serve.ingest")
        # seq check + enqueue + ack are ONE atomic step under the lock —
        # two racing offers of the same seq must not both enqueue. The socket
        # mode retries the non-blocking attempt until its deadline rather
        # than blocking inside the lock (status/gauges stay responsive).
        deadline = None if deadline_s is None else time.monotonic() + deadline_s
        while True:
            with self._lock:
                if self.state == "failed":
                    return wire.error("failed", f"stream {self.spec.name} failed: {self.failure}")
                if self.state in ("draining", "drained"):
                    return wire.error("draining", f"stream {self.spec.name} is {self.state}")
                if seq < self.next_seq:
                    # duplicate replay — ack idempotently, nothing re-applied
                    return wire.ok(stream=self.spec.name, duplicate=True, next_seq=self.next_seq)
                if seq > self.next_seq:
                    return wire.error(
                        "bad_seq",
                        f"gap: got seq {seq}, expected {self.next_seq} — rewind the replay",
                        expected=self.next_seq,
                    )
                try:
                    self._queue.put_nowait(("batch", decoded))
                except queue.Full:
                    pass
                else:
                    self.next_seq += 1
                    return wire.ok(stream=self.spec.name, next_seq=self.next_seq)
            if not block or (deadline is not None and time.monotonic() >= deadline):
                return wire.error(
                    "backpressure",
                    f"stream {self.spec.name} ingest queue is full ({self.spec.queue_max})",
                    retry_after_s=0.05,
                )
            time.sleep(0.005)

    # ------------------------------------------------------------- control
    def _submit_op(self, name: str, timeout_s: float) -> _Op:
        op = _Op(name)
        with self._lock:
            if self.state == "failed":
                op.finish(error=RuntimeError(self.failure or "stream failed"))
                return op
            if self.state in ("draining", "drained") and name != "drain":
                op.finish(error=RuntimeError(f"stream {self.spec.name} is {self.state}"))
                return op
            if name == "drain":
                if self.state in ("draining", "drained"):
                    op.finish(result=self.result)
                    return op
                self.state = "draining"
        try:
            self._queue.put(("op", op), timeout=timeout_s)
        except queue.Full:
            op.finish(error=RuntimeError(f"stream {self.spec.name} queue stayed full for {timeout_s}s"))
        return op

    def flush(self, timeout_s: float = 60.0) -> Dict[str, Any]:
        """Snapshot now, AFTER everything already admitted has applied."""
        op = self._submit_op("flush", timeout_s)
        if not op.done.wait(timeout_s):
            return wire.error("failed", f"flush of {self.spec.name} timed out after {timeout_s}s")
        if op.error is not None:
            return wire.error("failed", f"flush failed: {op.error}")
        return wire.ok(stream=self.spec.name, **op.result)

    def drain(self, timeout_s: float = 300.0) -> Dict[str, Any]:
        """Apply every admitted batch, final snapshot + compute; returns the
        results envelope. Idempotent — a second drain returns the same
        results."""
        if faults._ACTIVE:
            faults.fire("serve.drain")
        op = self._submit_op("drain", timeout_s)
        if not op.done.wait(timeout_s):
            return wire.error("failed", f"drain of {self.spec.name} timed out after {timeout_s}s")
        if op.error is not None:
            return wire.error("failed", f"drain failed: {op.error}")
        return wire.ok(stream=self.spec.name, cursor=self.evaluator.cursor, results=op.result)

    def abandon(self) -> int:
        """Stop the stream WITHOUT computing (the delete path): unblocks the
        worker, latches acked-but-unapplied batches as dropped, returns the
        dropped count."""
        with self._lock:
            already = self.state in ("drained", "failed")
            if not already:
                self.state = "failed"
                self.failure = "deleted"
                self._latch_dropped_locked()
        if not already:
            # wake the worker: the abandon sentinel ends the source without a
            # final compute; the state machine above already stopped offers
            try:
                self._queue.put(("op", _Op("abandon")), timeout=5.0)
            except queue.Full:
                pass
        self._thread.join(timeout=10.0)
        return self.dropped

    # -------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        with self._lock:
            info: Dict[str, Any] = {
                "name": self.spec.name,
                "state": self.state,
                "cursor": self.evaluator.cursor,
                "next_seq": self.next_seq,
                "pending": max(0, self.next_seq - self.evaluator.cursor),
                "queue_depth": self._queue.qsize(),
                "queue_max": self.spec.queue_max,
                "dropped": self.dropped,
                "kind": self.evaluator._kind(),
            }
            if self.failure is not None:
                info["failure"] = self.failure
            if self.result is not None:
                info["results"] = self.result
            return info

    def health_code(self) -> int:
        """0 ok … 3 stalled (the ``serve.<name>.health_state`` gauge): a
        failed stream is stalled; a queue ≥ 90% full is stalling (admission
        is about to push back). Watchdog-margin decay rides the evaluator's
        own runner probe, not this code."""
        with self._lock:
            code = _STATE_HEALTH.get(self.state, 0)
            if self.state == "serving" and self._queue.qsize() >= max(1, int(0.9 * self.spec.queue_max)):
                code = max(code, 1)
            return code

    def gauges(self) -> Dict[str, float]:
        """The ``serve.<name>.*`` gauge family (daemon probe fodder)."""
        prefix = f"serve.{self.spec.name}."
        with self._lock:
            state, qsize = self.state, self._queue.qsize()
            next_seq, dropped = self.next_seq, self.dropped
        return {
            prefix + "health_state": float(self.health_code()),
            prefix + "state": float(STATE_CODES.get(state, 0)),
            prefix + "cursor": float(self.evaluator.cursor),
            prefix + "pending": float(max(0, next_seq - self.evaluator.cursor)),
            prefix + "queue_depth": float(qsize),
            prefix + "dropped": float(dropped),
        }
