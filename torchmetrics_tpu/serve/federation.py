# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""``metricserve`` federation — two-tier fleet aggregation over merge states.

One daemon sustains ~10^5 samples/s (r008); "millions of users" means many
leaf daemons whose states fold into one fleet-wide answer. The fold itself is
the easy half — every state kind is mergeable under its declared
``dist_reduce_fx`` (SURVEY §3: distribution is sharding) — so this module
spends its complexity on the fleet's FAILURE modes, managed as states rather
than exceptions:

- **double counting** — a restarted leaf replays its unpersisted suffix, so a
  naive pull would fold the replayed prefix twice. Every leaf export is
  stamped with the leaf's per-boot **epoch** nonce and the applied-seq
  **watermark** of the serialized state; the aggregator keeps ONE slot per
  (leaf, stream) and replaces it wholesale (snapshot semantics, never
  increments), accepting a new epoch only once its watermark has caught up
  with the slot it would replace. A fold therefore never mixes two boots'
  windows and a replayed prefix dedups structurally.
- **partial outage** — one pull supervisor per leaf (timeout / retry /
  exponential-backoff-with-jitter, the :class:`SyncConfig` semantics)
  classifies each leaf ``fresh | lagging | unreachable | quarantined``; an
  unreachable leaf's last slots keep contributing (stale but correct) and the
  aggregate is annotated with ``fleet.coverage`` instead of failing.
- **corrupt deltas** — every pulled payload is decoded and then proven
  against a freshly built reference metric through the PR-2
  validate-ALL-then-apply ladder *before any slot is touched*: a corrupt
  payload names the leaf, quarantines it (excluded from the fold until a
  clean pull heals it), and never half-folds.
- **aggregator loss** — validated slots are checkpointed through
  :class:`CheckpointStore`, so a SIGKILLed aggregator resumes its fold state
  without re-pulling history the leaves may no longer hold.

``/healthz`` is worst-leaf-floored: lagging → ``stalling``, unreachable or
quarantined → ``degraded`` with a reason naming the leaf and the coverage
fraction. Folding supports ``metric`` and ``collection`` streams; a
``sliced`` plan aggregates locally (its carry is not cross-leaf mergeable)
and is reported as a per-stream error instead of poisoning the rest.

Lock discipline (ML012): ``_lock`` guards only dict snapshots/assignment.
Pulls, payload validation, fold-state saves and registry writes all run
outside it; fold-state saves go through a single writer loop.
"""
from __future__ import annotations

import json
import os
import random
import tempfile
import threading
import urllib.error
import urllib.request
import uuid
from typing import Any, Dict, List, Optional, Tuple

from torchmetrics_tpu.obs import counters as _obs_counters
from torchmetrics_tpu.obs import live as _obs_live
from torchmetrics_tpu.robustness.store import CheckpointStore
from torchmetrics_tpu.robustness.sync_config import SyncConfig
from torchmetrics_tpu.serve import wire
from torchmetrics_tpu.serve.stream import resolve_target
from torchmetrics_tpu.utilities.exceptions import StateRestoreError

__all__ = [
    "FleetAggregator",
    "LEAF_STATES",
    "LEAF_STATE_CODES",
    "LEAF_HEALTH_CODES",
    "decode_state",
]

#: the managed leaf states (ISSUE-17 classification)
LEAF_STATES = ("fresh", "lagging", "unreachable", "quarantined")

#: leaf state → numeric gauge code (``fleet.leaf.<name>.state``)
LEAF_STATE_CODES = {"fresh": 0, "lagging": 1, "unreachable": 2, "quarantined": 3}

#: leaf state → health-severity code (``fleet.leaf.<name>.health_state``,
#: the obs ladder: 0 ok, 1 stalling, 2 degraded, 3 stalled) — a lagging leaf
#: still contributes (stale slots), so it only *stalls*; an unreachable or
#: quarantined leaf degrades the fleet
LEAF_HEALTH_CODES = {"fresh": 0, "lagging": 1, "unreachable": 2, "quarantined": 2}

_FOLD_PAYLOAD_VERSION = 1
_SLOT_KEYS = ("epoch", "watermark", "fingerprint", "kind", "spec", "payload")


# ------------------------------------------------------------------- codec
def decode_state(value: Any) -> Any:
    """Inverse of :func:`torchmetrics_tpu.serve.wire.encode_state`: rebuild
    exact-dtype ndarrays from ``{"__nd__": dtype, "shape": [...], "data"}``
    markers (ml_dtypes names like ``bfloat16`` included) so the strict
    restore ladder accepts the round-trip, and ``{"__bytes__": ...}`` back
    into bytes."""
    import numpy as np

    if isinstance(value, dict):
        if wire.ND_KEY in value:
            dtype = _resolve_dtype(str(value[wire.ND_KEY]))
            data = value.get("data")
            shape = tuple(int(d) for d in value.get("shape", ()))
            return np.asarray(data, dtype=dtype).reshape(shape)
        if set(value) == {"__bytes__"}:
            return str(value["__bytes__"]).encode("latin-1")
        return {k: decode_state(v) for k, v in value.items()}
    if isinstance(value, list):
        return [decode_state(v) for v in value]
    return value


def _resolve_dtype(name: str) -> Any:
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError):
        raise StateRestoreError(f"state payload carries unknown dtype {name!r}") from None


# -------------------------------------------------------------------- fold
def _disable_dist(target: Any) -> None:
    """The fleet fold IS the distribution: reference metrics built for
    folding must never enter a cross-process collective (it would also
    deadlock the lockstep multiprocess scenarios)."""
    from torchmetrics_tpu.parallel.sharded import _walk_metrics

    for _path, m in _walk_metrics(target):
        m.distributed_available_fn = lambda: False


def _assert_finite_payload(node: Any, path: str = "checkpoint", in_sketch: bool = False) -> None:
    """Walk a decoded export checkpoint and refuse any float leaf carrying a
    non-finite value BEFORE it can be folded into the fleet aggregate — the
    federation face of the StateGuard poison probe: one leaf that propagated
    a NaN locally must quarantine here, not poison every downstream fold.

    Sketch payloads (``__sketch__``-marked) legitimately carry ±inf sentinels
    (KLL empty slots, reservoir empty tags), so inside them only NaN is a
    defect; everywhere else Inf is corruption too."""
    import numpy as np

    from torchmetrics_tpu.robustness.spec import SKETCH_PAYLOAD_KEY

    if isinstance(node, dict):
        in_sketch = in_sketch or SKETCH_PAYLOAD_KEY in node
        for key, value in node.items():
            _assert_finite_payload(value, f"{path}.{key}", in_sketch)
        return
    if isinstance(node, (list, tuple)):
        for i, value in enumerate(node):
            _assert_finite_payload(value, f"{path}[{i}]", in_sketch)
        return
    if isinstance(node, np.ndarray) and np.issubdtype(node.dtype, np.floating):
        bad = np.isnan(node).any() if in_sketch else not np.isfinite(node).all()
        if bad:
            raise StateRestoreError(f"non-finite value in export state at {path}")


def _fold_metric(acc: Any, other: Any) -> None:
    """Fold ``other``'s state into ``acc`` under each state's declared
    ``dist_reduce_fx`` — ``mean`` states weighted by update counts, plain
    numeric host counters summed. Both must be the same deep structure
    (guaranteed upstream by the per-slot fingerprint check)."""
    from torchmetrics_tpu.parallel.sharded import _walk_metrics, tree_merge

    for (path_a, ma), (path_b, mb) in zip(_walk_metrics(acc), _walk_metrics(other)):
        if path_a != path_b:
            raise StateRestoreError(
                f"fold walk diverged: {path_a!r} vs {path_b!r} — the leaves do not share a schema"
            )
        if mb._update_count == 0:
            continue
        if ma._update_count == 0:
            ma._install_state_tree(mb.state_tree(include_count=True))
        else:
            merged = tree_merge(
                ma._reductions,
                ma.state_tree(include_count=False),
                mb.state_tree(include_count=False),
                weight_a=float(ma._update_count),
                weight_b=float(mb._update_count),
            )
            ma._install_state_tree(merged)
            ma._update_count += mb._update_count
        for attr in getattr(ma, "_host_counters", ()):
            va, vb = getattr(ma, attr, None), getattr(mb, attr, None)
            if isinstance(va, (int, float)) and not isinstance(va, bool) and isinstance(vb, (int, float)):
                setattr(ma, attr, va + vb)
        ma._computed = None


class FleetAggregator:
    """The aggregator tier: pulls per-stream state deltas from N leaf
    ``ServeDaemon``\\ s and folds them into one fleet-wide answer.

    Args:
        base_dir: durable root — ``leaves.json`` (the registry, restart
            fuel) and ``fold/`` (the :class:`CheckpointStore` of validated
            slots) live here.
        http: control-plane bind (``"host:port"`` / ``":port"`` / int);
            default ephemeral. Routes: ``/healthz``, ``/v1/fleet``,
            ``/v1/fleet/aggregate``, ``POST/DELETE /v1/fleet/leaves``.
        pull_interval_s: cadence of each leaf's pull supervisor (jittered so
            N supervisors never pull in lockstep).
        sync: retry/backoff policy per pull (the :class:`SyncConfig`
            semantics; jitter is added on every backoff sleep).
        fingerprint: optional registry fingerprint to pin every pull to —
            a leaf serving a different schema answers 409 and is quarantined
            instead of folded.
        checkpoint_every_s: fold-state persistence cadence (single writer
            loop; a save also runs at shutdown).
        publish: register the ``fleet.*`` gauges as a live-plane probe.
        keep_last: fold-store retention.
    """

    def __init__(
        self,
        base_dir: str,
        http: Any = ":0",
        pull_interval_s: float = 1.0,
        sync: Optional[SyncConfig] = None,
        fingerprint: Optional[str] = None,
        checkpoint_every_s: float = 2.0,
        publish: bool = True,
        keep_last: Optional[int] = 3,
    ) -> None:
        self.base_dir = str(base_dir)
        self._http_spec = http
        self.pull_interval_s = float(pull_interval_s)
        self.sync = sync if sync is not None else SyncConfig(timeout_s=5.0, retries=2, backoff_base_s=0.1)
        self.fingerprint = fingerprint
        self.checkpoint_every_s = float(checkpoint_every_s)
        self._publish = bool(publish)
        #: per-boot nonce — an aggregate answer names the aggregator boot
        #: that produced it, symmetric with the leaf epochs it folded
        self.epoch: Optional[str] = None
        self._leaves: Dict[str, Dict[str, Any]] = {}
        self._slots: Dict[str, Dict[str, Dict[str, Any]]] = {}  # leaf -> stream -> slot
        self._leaf_state: Dict[str, str] = {}
        self._leaf_reason: Dict[str, Optional[str]] = {}
        self._leaf_fails: Dict[str, int] = {}
        self._leaf_stops: Dict[str, threading.Event] = {}
        self._supervisors: Dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._accepting = False
        self._dirty = False
        self._fold_seq = 0
        self._fold_store = CheckpointStore(
            os.path.join(self.base_dir, "fold"), keep_last=keep_last, write_rank=None
        )
        self._fold_thread: Optional[threading.Thread] = None
        self._http_server: Any = None
        self._http_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FleetAggregator":
        self.epoch = uuid.uuid4().hex[:12]
        os.makedirs(self.base_dir, exist_ok=True)
        self._load_registry()
        self._resume_fold_state()
        self._accepting = True
        if self._publish:
            _obs_live.register_probe("metricfleet", self._probe)
        self._start_http()
        with self._lock:
            names = sorted(self._leaves)
        for name in names:
            self._start_supervisor(name)
        self._fold_thread = threading.Thread(target=self._fold_loop, daemon=True, name="fleet-fold")
        self._fold_thread.start()
        return self

    def shutdown(self) -> None:
        """Stop supervisors, persist the fold state one last time, close the
        control plane. Restart = :meth:`start` on the same ``base_dir``."""
        self._accepting = False
        self._stop.set()
        with self._lock:
            stops = list(self._leaf_stops.values())
            threads = list(self._supervisors.values())
            fold_thread = self._fold_thread
        for stop in stops:
            stop.set()
        for thread in threads:
            thread.join(timeout=10.0)
        if fold_thread is not None:
            fold_thread.join(timeout=10.0)
        self._save_fold_state()
        if self._publish:
            _obs_live.unregister_probe("metricfleet")
        if self._http_server is not None:
            self._http_server.shutdown()
            self._http_server.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=10.0)
            self._http_server = self._http_thread = None

    # ------------------------------------------------------------- registry
    def _registry_path(self) -> str:
        return os.path.join(self.base_dir, "leaves.json")

    def _load_registry(self) -> None:
        try:
            with open(self._registry_path()) as fh:
                registry = json.load(fh)
        except (OSError, ValueError):
            return
        if not isinstance(registry, dict):
            return
        with self._lock:
            for name, url in registry.items():
                self._leaves[str(name)] = {"url": str(url)}
                self._leaf_state[str(name)] = "lagging"
                self._leaf_reason[str(name)] = "awaiting first pull"

    def _persist_registry(self, registry: Dict[str, str]) -> None:
        # atomic publish; concurrent add/remove handlers race benignly —
        # last writer wins with a complete snapshot, never a torn file
        data = json.dumps(registry, indent=2, sort_keys=True).encode()
        fd, tmp = tempfile.mkstemp(prefix="leaves.json.tmp-", dir=self.base_dir)
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(data)
            os.replace(tmp, self._registry_path())
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def add_leaf(self, name: str, url: str) -> Dict[str, Any]:
        """Register a leaf daemon by control-plane URL and start pulling."""
        if not self._accepting:
            return wire.error("draining", "aggregator is shutting down")
        if not name or "." in name or "/" in name:
            return wire.error("bad_request", f"leaf name {name!r} must be non-empty without '.' or '/'")
        with self._lock:
            if name in self._leaves:
                return wire.error("exists", f"leaf {name} is already registered")
            self._leaves[name] = {"url": str(url).rstrip("/")}
            self._leaf_state[name] = "lagging"
            self._leaf_reason[name] = "awaiting first pull"
            registry = {n: info["url"] for n, info in self._leaves.items()}
        self._persist_registry(registry)
        self._start_supervisor(name)
        return wire.ok(leaf=name, url=url)

    def remove_leaf(self, name: str) -> Dict[str, Any]:
        """Deregister a leaf; its slots leave the fold immediately."""
        with self._lock:
            if name not in self._leaves:
                return wire.error("not_found", f"no leaf named {name!r}")
            del self._leaves[name]
            self._slots.pop(name, None)
            self._leaf_state.pop(name, None)
            self._leaf_reason.pop(name, None)
            self._leaf_fails.pop(name, None)
            stop = self._leaf_stops.pop(name, None)
            thread = self._supervisors.pop(name, None)
            self._dirty = True
            registry = {n: info["url"] for n, info in self._leaves.items()}
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=5.0)
        self._persist_registry(registry)
        return wire.ok(leaf=name)

    def leaves(self) -> List[str]:
        with self._lock:
            return sorted(self._leaves)

    # ---------------------------------------------------------- supervision
    def _start_supervisor(self, name: str) -> None:
        stop = threading.Event()
        if self._stop.is_set():
            return
        thread = threading.Thread(
            target=self._supervise, args=(name, stop), daemon=True, name=f"fleet-pull-{name}"
        )
        with self._lock:
            if name not in self._leaves or name in self._supervisors:
                return
            self._leaf_stops[name] = stop
            self._supervisors[name] = thread
        thread.start()

    def _supervise(self, name: str, stop: threading.Event) -> None:
        while not stop.is_set() and not self._stop.is_set():
            try:
                self.pull_leaf(name, stop=stop)
            except Exception:
                _obs_counters.inc("fleet.pull_errors")
            # jittered cadence: N supervisors started together must not pull
            # (and retry) in lockstep against recovering leaves
            stop.wait(self.pull_interval_s + random.uniform(0.0, 0.25 * self.pull_interval_s))

    def pull_now(self) -> None:
        """One synchronous pull of every registered leaf (tests/benches use
        this for deterministic rounds instead of sleeping on the cadence)."""
        for name in self.leaves():
            try:
                self.pull_leaf(name)
            except Exception:
                _obs_counters.inc("fleet.pull_errors")

    def pull_leaf(self, name: str, stop: Optional[threading.Event] = None) -> None:
        """Pull, validate and (atomically) apply one leaf's state export."""
        with self._lock:
            info = self._leaves.get(name)
        if info is None:
            return
        stop = stop if stop is not None else self._stop
        body, failure = self._fetch_state(name, info["url"], stop)
        if body is None:
            if failure is not None:  # None failure == quarantined inside _fetch_state
                self._classify(name, "unreachable", failure)
            return
        _obs_counters.inc("fleet.pulls")
        epoch = str(body.get("epoch"))
        streams = body.get("streams")
        if not isinstance(streams, dict):
            self._classify(name, "quarantined", "state export carries no stream map")
            return
        candidates: List[Tuple[str, Dict[str, Any]]] = []
        lagging_reason: Optional[str] = None
        for sname in sorted(streams):
            env = streams[sname]
            if not isinstance(env, dict) or not env.get("ok"):
                err = (env or {}).get("error", {}) if isinstance(env, dict) else {}
                if err.get("code") == "fingerprint_mismatch":
                    self._classify(name, "quarantined", f"stream {sname}: {err.get('message')}")
                    return
                lagging_reason = f"stream {sname} export failed: {err.get('message', 'no envelope')}"
                continue
            try:
                candidates.append((sname, self._validated_slot(env, epoch)))
            except Exception as err:
                # validate-ALL-then-apply across the whole leaf: one corrupt
                # stream quarantines the pull and NOTHING from it is folded
                _obs_counters.inc("fleet.quarantined_payloads")
                self._classify(name, "quarantined", f"stream {sname} payload rejected: {err}")
                return
        replaying: List[str] = []
        with self._lock:
            if name not in self._leaves:
                return
            slots = self._slots.setdefault(name, {})
            for sname, slot in candidates:
                prev = slots.get(sname)
                if prev is None or int(slot["watermark"]) >= int(prev["watermark"]):
                    slots[sname] = slot
                elif slot["epoch"] != prev["epoch"]:
                    # the leaf restarted and is still replaying its suffix:
                    # keep the old boot's higher-watermark slot (dedup) until
                    # the new epoch catches up — a fold never mixes windows
                    replaying.append(sname)
                # same-epoch lower watermark: stale read, keep the newer slot
            self._dirty = True
        if replaying:
            self._classify(name, "lagging", f"restarted; replay behind on stream(s) {replaying}")
        elif lagging_reason is not None:
            self._classify(name, "lagging", lagging_reason)
        else:
            self._classify(name, "fresh", None)

    def _fetch_state(
        self, name: str, url: str, stop: threading.Event
    ) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
        """GET ``<url>/v1/state`` under the SyncConfig retry policy. Returns
        ``(body, None)`` on success, ``(None, reason)`` after exhaustion, or
        ``(None, None)`` when the leaf was quarantined here (409)."""
        target = url.rstrip("/") + "/v1/state"
        if self.fingerprint:
            target += f"?fingerprint={self.fingerprint}"
        failure: Optional[str] = None
        for attempt in range(self.sync.attempts):
            if stop.is_set():
                return None, failure or "aggregator stopping"
            try:
                with urllib.request.urlopen(target, timeout=self.sync.timeout_s or 5.0) as resp:
                    return json.loads(resp.read()), None
            except urllib.error.HTTPError as err:
                try:
                    envelope = json.loads(err.read())
                except Exception:
                    envelope = None
                code = (envelope or {}).get("error", {}).get("code")
                if code == "fingerprint_mismatch":
                    self._classify(
                        name, "quarantined", envelope["error"].get("message", "fingerprint mismatch")
                    )
                    return None, None
                failure = f"HTTP {err.code} from {target}: {code or err.reason}"
            except (urllib.error.URLError, OSError, ValueError) as err:
                failure = f"{type(err).__name__}: {getattr(err, 'reason', err)}"
            if attempt + 1 < self.sync.attempts:
                # exponential backoff with jitter — a fleet of aggregator
                # retries must not thundering-herd a recovering leaf
                stop.wait(self.sync.backoff(attempt) + random.uniform(0.0, self.sync.backoff_base_s))
        return None, failure

    def _validated_slot(self, env: Dict[str, Any], epoch: str) -> Dict[str, Any]:
        """Decode one stream export and PROVE it against a fresh reference
        metric (the PR-2 validate-ALL-then-apply ladder) before it can become
        a slot. Raises on any defect; never applies anything."""
        payload = decode_state(env.get("state"))
        if not isinstance(payload, dict) or "checkpoint" not in payload:
            raise StateRestoreError("export payload carries no checkpoint")
        _assert_finite_payload(payload["checkpoint"])
        watermark = env.get("watermark")
        if not isinstance(watermark, int) or watermark < 0:
            raise StateRestoreError(f"export watermark {watermark!r} is not a non-negative int")
        if payload.get("cursor") != watermark:
            raise StateRestoreError(
                f"export watermark {watermark} disagrees with payload cursor {payload.get('cursor')!r}"
            )
        kind = env.get("kind")
        spec = env.get("spec")
        if not isinstance(spec, dict) or not spec.get("target"):
            raise StateRestoreError("export carries no stream spec")
        if kind in ("metric", "collection"):
            self._build_loaded(spec, kind, payload["checkpoint"])  # raises on corruption
        elif kind != "sliced":
            raise StateRestoreError(f"unknown export kind {kind!r}")
        return {
            "epoch": epoch,
            "watermark": int(watermark),
            "fingerprint": env.get("fingerprint"),
            "kind": kind,
            "spec": {"target": spec["target"], "kwargs": spec.get("kwargs") or {}},
            "windowed": bool(env.get("windowed", False)),
            "payload": payload,
        }

    def _build_loaded(self, spec: Dict[str, Any], kind: str, checkpoint: Dict[str, Any]) -> Any:
        """Fresh reference target from the stream spec, loaded with
        ``checkpoint`` through the validate-ALL-then-apply ladder. The
        references never sync — the fleet fold IS the distribution."""
        from torchmetrics_tpu.robustness.checkpoint import load_checkpoint

        target = resolve_target(spec["target"], spec.get("kwargs") or {})
        if kind == "collection":
            from torchmetrics_tpu.collections import MetricCollection

            if not isinstance(target, MetricCollection):
                raise StateRestoreError(
                    f"spec {spec['target']!r} builds a {type(target).__name__}, export says collection"
                )
            members = dict(target.items(keep_base=True, copy_state=False))
            if not isinstance(checkpoint, dict):
                raise StateRestoreError("collection checkpoint is not a member dict")
            missing = sorted(set(members) - set(checkpoint))
            extra = sorted(set(checkpoint) - set(members))
            if missing or extra:
                raise StateRestoreError(
                    "collection checkpoint does not match the spec:"
                    + (f" missing member(s) {missing}" if missing else "")
                    + (f" unexpected member(s) {extra}" if extra else "")
                )
            for mname, member in members.items():
                _disable_dist(member)
                load_checkpoint(member, checkpoint[mname])
        else:
            _disable_dist(target)
            load_checkpoint(target, checkpoint)
        return target

    def _classify(self, name: str, state: str, reason: Optional[str]) -> None:
        changed = False
        with self._lock:
            if name not in self._leaves:
                return
            if self._leaf_state.get(name) != state:
                changed = True
            self._leaf_state[name] = state
            self._leaf_reason[name] = reason
            if state == "unreachable":
                self._leaf_fails[name] = self._leaf_fails.get(name, 0) + 1
            elif state == "fresh":
                self._leaf_fails[name] = 0
        if changed:
            _obs_counters.inc(f"fleet.classify.{state}")

    # ----------------------------------------------------------------- fold
    def aggregate(self) -> Dict[str, Any]:
        """Fold every slot into the fleet-wide answer — sorted-leaf order per
        stream (cat states concatenate deterministically), ``mean`` states
        weighted by update counts, sketches through their union merge. A
        quarantined leaf is excluded; the answer is coverage-annotated."""
        with self._lock:
            slots_by_leaf = {leaf: dict(streams) for leaf, streams in self._slots.items()}
            leaf_state = dict(self._leaf_state)
            leaf_reason = dict(self._leaf_reason)
            registered = sorted(self._leaves)
            fold_seq = self._fold_seq
        per_stream: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
        for leaf in sorted(slots_by_leaf):
            if leaf not in leaf_state or leaf_state.get(leaf) == "quarantined":
                continue
            for sname, slot in slots_by_leaf[leaf].items():
                per_stream.setdefault(sname, []).append((leaf, slot))
        results: Dict[str, Any] = {}
        errors: Dict[str, str] = {}
        for sname in sorted(per_stream):
            entries = per_stream[sname]
            kinds = sorted({str(slot["kind"]) for _, slot in entries})
            fingerprints = sorted({str(slot["fingerprint"]) for _, slot in entries})
            if len(kinds) > 1 or len(fingerprints) > 1:
                errors[sname] = (
                    f"leaves disagree on the stream schema: kinds={kinds} fingerprints={fingerprints}"
                )
                continue
            if kinds[0] not in ("metric", "collection"):
                errors[sname] = f"kind {kinds[0]!r} does not fold across leaves (sliced plans aggregate locally)"
                continue
            try:
                results[sname] = self._fold_stream(kinds[0], entries)
            except Exception as err:
                errors[sname] = f"fold failed: {type(err).__name__}: {err}"
        _obs_counters.inc("fleet.folds")
        covered = [l for l in registered if leaf_state.get(l) in ("fresh", "lagging")]
        return {
            "epoch": self.epoch,
            "fold_seq": fold_seq,
            "coverage": (len(covered) / len(registered)) if registered else 1.0,
            "leaves": {
                l: {"state": leaf_state.get(l, "lagging"), "reason": leaf_reason.get(l)}
                for l in registered
            },
            "streams": results,
            "errors": errors,
        }

    def _fold_stream(self, kind: str, entries: List[Tuple[str, Dict[str, Any]]]) -> Dict[str, Any]:
        acc = None
        folded: List[Dict[str, Any]] = []
        for leaf, slot in entries:  # already in sorted-leaf order
            inst = self._build_loaded(slot["spec"], kind, slot["payload"]["checkpoint"])
            if acc is None:
                acc = inst
            elif kind == "collection":
                a_members = dict(acc.items(keep_base=True, copy_state=False))
                b_members = dict(inst.items(keep_base=True, copy_state=False))
                for mname in sorted(a_members):
                    _fold_metric(a_members[mname], b_members[mname])
            else:
                _fold_metric(acc, inst)
            folded.append({"leaf": leaf, "epoch": slot["epoch"], "watermark": slot["watermark"]})
        return {
            "kind": kind,
            "value": wire.to_jsonable(acc.compute()),
            "windowed": any(slot.get("windowed") for _, slot in entries),
            "leaves": folded,
        }

    # ------------------------------------------------------ fold-state store
    def _fold_loop(self) -> None:
        # the SINGLE fold-state writer: supervisors only flip _dirty, so no
        # save ever runs under (or competes for) the slot lock
        while not self._stop.wait(self.checkpoint_every_s):
            self._save_fold_state()

    def _save_fold_state(self) -> None:
        with self._lock:
            if not self._dirty:
                return
            self._dirty = False
            self._fold_seq += 1
            seq = self._fold_seq
            slots = {leaf: dict(streams) for leaf, streams in self._slots.items()}
        payload = {"payload_version": _FOLD_PAYLOAD_VERSION, "fold_seq": seq, "slots": slots}
        try:
            self._fold_store.save(payload, step=seq)
        except Exception:
            _obs_counters.inc("fleet.fold_store_errors")

    def _resume_fold_state(self) -> None:
        last = self._fold_store.last_step()
        if last is not None:
            self._fold_seq = int(last)
        restored = self._fold_store.latest(validate=_validate_fold_payload)
        if restored is None:
            return
        _step, payload = restored
        with self._lock:
            for leaf, streams in payload["slots"].items():
                if leaf not in self._leaves:
                    continue  # removed while we were down: the registry wins
                self._slots[leaf] = dict(streams)
                self._leaf_state[leaf] = "lagging"
                self._leaf_reason[leaf] = "restored from fold checkpoint; awaiting first pull"

    # --------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        """Worst-leaf-floored fleet health with a coverage-annotated reason —
        computed from this aggregator's OWN classification, independent of
        any process-global live plane."""
        with self._lock:
            registered = sorted(self._leaves)
            leaf_state = dict(self._leaf_state)
            leaf_reason = dict(self._leaf_reason)
        state, reason = "ok", None

        def escalate(candidate: str, why: str) -> None:
            nonlocal state, reason
            if _obs_live._SEVERITY[candidate] > _obs_live._SEVERITY[state]:
                state, reason = candidate, why

        covered = sum(1 for l in registered if leaf_state.get(l, "lagging") in ("fresh", "lagging"))
        coverage = (covered / len(registered)) if registered else 1.0
        for leaf in registered:
            ls = leaf_state.get(leaf, "lagging")
            why = leaf_reason.get(leaf)
            if ls == "lagging":
                escalate("stalling", f"leaf {leaf} is lagging" + (f": {why}" if why else ""))
            elif ls in ("unreachable", "quarantined"):
                escalate(
                    "degraded",
                    f"leaf {leaf} is {ls}" + (f": {why}" if why else "")
                    + f" — fleet coverage {covered}/{len(registered)}, aggregate is partial",
                )
        return {
            "state": state,
            "reason": reason,
            "http_status": _obs_live.HEALTH_HTTP_STATUS[state],
            "epoch": self.epoch,
            "coverage": coverage,
            "leaves": {
                l: {"state": leaf_state.get(l, "lagging"), "reason": leaf_reason.get(l)}
                for l in registered
            },
        }

    def fleet_status(self) -> Dict[str, Any]:
        with self._lock:
            registered = sorted(self._leaves)
            leaves = {
                l: {
                    "url": self._leaves[l]["url"],
                    "state": self._leaf_state.get(l, "lagging"),
                    "reason": self._leaf_reason.get(l),
                    "failures": self._leaf_fails.get(l, 0),
                    "streams": {
                        sname: {"epoch": slot["epoch"], "watermark": slot["watermark"], "kind": slot["kind"]}
                        for sname, slot in sorted(self._slots.get(l, {}).items())
                    },
                }
                for l in registered
            }
            fold_seq = self._fold_seq
        covered = sum(1 for info in leaves.values() if info["state"] in ("fresh", "lagging"))
        return wire.ok(
            epoch=self.epoch,
            accepting=self._accepting,
            fold_seq=fold_seq,
            coverage=(covered / len(leaves)) if leaves else 1.0,
            leaves=leaves,
        )

    # ---------------------------------------------------------------- probe
    def _probe(self) -> Dict[str, float]:
        with self._lock:
            registered = sorted(self._leaves)
            leaf_state = dict(self._leaf_state)
            slot_counts = {l: len(self._slots.get(l, {})) for l in registered}
            fold_seq = self._fold_seq
        covered = sum(1 for l in registered if leaf_state.get(l, "lagging") in ("fresh", "lagging"))
        gauges: Dict[str, float] = {
            "fleet.leaves": float(len(registered)),
            "fleet.coverage": (covered / len(registered)) if registered else 1.0,
            "fleet.fold_seq": float(fold_seq),
        }
        for l in registered:
            ls = leaf_state.get(l, "lagging")
            gauges[f"fleet.leaf.{l}.state"] = float(LEAF_STATE_CODES[ls])
            gauges[f"fleet.leaf.{l}.health_state"] = float(LEAF_HEALTH_CODES[ls])
            gauges[f"fleet.leaf.{l}.streams"] = float(slot_counts.get(l, 0))
        return gauges

    # ----------------------------------------------------------------- http
    def http_address(self) -> Optional[Tuple[str, int]]:
        if self._http_server is None:
            return None
        return self._http_server.server_address[:2]

    def _start_http(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        host, port = _obs_live._parse_http_spec(self._http_spec)
        agg = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:
                pass

            def _send_json(self, obj: Dict[str, Any], code: Optional[int] = None) -> None:
                if code is None:
                    code = 200 if obj.get("ok", True) else _ERROR_HTTP_STATUS.get(
                        obj.get("error", {}).get("code"), 400
                    )
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self) -> None:
                path = self.path.split("?", 1)[0].rstrip("/")
                parts = [p for p in path.split("/") if p]
                try:
                    if self.command == "GET" and path == "/healthz":
                        health = agg.health()
                        self._send_json(health, code=health["http_status"])
                    elif self.command == "GET" and path == "/v1/fleet":
                        self._send_json(agg.fleet_status())
                    elif self.command == "GET" and path == "/v1/fleet/aggregate":
                        self._send_json(wire.ok(**agg.aggregate()))
                    elif parts[:3] == ["v1", "fleet", "leaves"]:
                        if self.command == "POST" and len(parts) == 3:
                            length = int(self.headers.get("Content-Length", 0))
                            body = wire.decode_frame(self.rfile.read(length)) if length else {}
                            self._send_json(agg.add_leaf(str(body.get("name")), str(body.get("url"))))
                        elif self.command == "DELETE" and len(parts) == 4:
                            self._send_json(agg.remove_leaf(parts[3]))
                        else:
                            self._send_json(wire.error("bad_request", f"{self.command} {self.path} not supported"))
                    else:
                        self._send_json(
                            wire.error(
                                "not_found",
                                "fleet control plane: /healthz, /v1/fleet, /v1/fleet/aggregate, /v1/fleet/leaves",
                            )
                        )
                except wire.WireError as err:
                    self._send_json(wire.error("bad_request", str(err)))
                except Exception as err:  # the control plane must answer, never hang up
                    self._send_json(wire.error("failed", f"{type(err).__name__}: {err}"), code=500)

            do_GET = do_POST = do_DELETE = _route

        self._http_server = ThreadingHTTPServer((host, port), _Handler)
        self._http_server.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._http_server.serve_forever, daemon=True, name="fleet-http"
        )
        self._http_thread.start()


def _validate_fold_payload(payload: Dict[str, Any]) -> None:
    """``CheckpointStore.latest`` hook for the aggregator's own fold state —
    structural validation only; every slot is re-proven through the full
    checkpoint ladder at the next fold anyway."""
    if payload.get("payload_version") != _FOLD_PAYLOAD_VERSION:
        raise StateRestoreError(
            f"fold-state payload_version {payload.get('payload_version')!r} is not supported"
        )
    slots = payload.get("slots")
    if not isinstance(slots, dict):
        raise StateRestoreError("fold-state payload carries no slot map")
    for leaf, streams in slots.items():
        if not isinstance(streams, dict):
            raise StateRestoreError(f"fold-state slots for leaf {leaf!r} are not a dict")
        for sname, slot in streams.items():
            missing = [k for k in _SLOT_KEYS if k not in slot]
            if missing:
                raise StateRestoreError(
                    f"fold-state slot {leaf}/{sname} is missing key(s) {missing} — truncated payload?"
                )


#: wire error code → HTTP status for the aggregator control plane
_ERROR_HTTP_STATUS = {
    "not_found": 404,
    "exists": 409,
    "draining": 503,
    "failed": 500,
    "bad_request": 400,
    "fingerprint_mismatch": 409,
}
