# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""``metricserve`` — the always-on eval-service plane.

The library planes (fused PR 9, sliced + windowed PR 10, durability PR 5,
live telemetry PR 7) compose here into a deployable daemon: many named
durable streams behind one HTTP control plane and a unix-socket ingest
plane. Run it with ``python tools/metricserve.py serve``; talk to it —
without importing jax — with ``python tools/metricserve.py ctl``.
"""
from torchmetrics_tpu.serve.daemon import ServeDaemon
from torchmetrics_tpu.serve.federation import FleetAggregator, decode_state
from torchmetrics_tpu.serve.stream import Stream, StreamSpec, decode_batch, resolve_target
from torchmetrics_tpu.serve.wire import WIRE_VERSION, WireError, encode_state

__all__ = [
    "FleetAggregator",
    "ServeDaemon",
    "Stream",
    "StreamSpec",
    "WIRE_VERSION",
    "WireError",
    "decode_batch",
    "decode_state",
    "encode_state",
    "resolve_target",
]
