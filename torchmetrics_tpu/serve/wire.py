# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""``metricserve`` wire schema — versioned, stdlib-only, jax-free.

Every message the daemon speaks — HTTP control-plane bodies AND the
newline-JSON local-socket ingest frames — is one JSON object carrying the
schema version under ``"v"``. This module is the single source of truth for
that envelope; it deliberately imports NOTHING outside the stdlib so the
``metricserve ctl`` client mode can load it by file path (the metricscope
idiom) on a supervisor host that cannot import jax.

Envelope
--------
Request frames (socket) / request bodies (HTTP POST)::

    {"v": 1, "op": "ingest", "stream": "m1-val", "seq": 7, "batch": [...]}

Response frames / bodies::

    {"v": 1, "ok": true, ...fields}
    {"v": 1, "ok": false, "error": {"code": "backpressure", "message": "...",
                                    "retry_after_s": 0.05, ...detail}}

Error codes are machine-switchable (:data:`ERROR_CODES`): ``backpressure``
(queue full — retry after ``retry_after_s``), ``bad_seq`` (gap: the body
carries ``expected`` so the client can rewind its replay), ``not_found``,
``exists``, ``draining`` (daemon is shutting down, nothing new is admitted),
``failed`` (the stream's worker died or its circuit breaker is open — the
body carries the cause), ``bad_payload`` (the batch decodes but its
part count / dtype / trailing shape disagree with the stream's
first-accepted batch — the body carries ``expected`` and ``got``),
``bad_request``, ``unsupported_version`` and ``fingerprint_mismatch`` (a
state export was requested pinned to a registry fingerprint the stream does
not carry — HTTP 409; the federation plane quarantines the leaf instead of
folding a foreign schema).

Batches on the wire are JSON lists of (nested) number lists — one entry per
positional update argument; the server decodes them to arrays. A sliced
stream's batch leads with its integer cohort-key column(s) (the
``plan.update(keys, *batch)`` calling convention). JSON numbers round-trip
binary64 exactly, so results read back from a drain compare bitwise against
an in-process run.

State payloads (the ``/v1/state`` export verb) carry arrays through
:func:`encode_state` — a ``{"__nd__": dtype, "shape": [...], "data": ...}``
marker per array — because a bare ``tolist()`` erases the dtype and the
strict restore ladder on the aggregator side rightly refuses a float64 tree
for a float32 metric. Encoding is duck-typed (``dtype``/``shape``/
``tolist``) so this module stays stdlib-only; decoding needs numpy and lives
in :mod:`torchmetrics_tpu.serve.federation`.
"""
from __future__ import annotations

import json
from typing import Any, Dict

__all__ = [
    "WIRE_VERSION",
    "ERROR_CODES",
    "ND_KEY",
    "WireError",
    "ok",
    "error",
    "encode_frame",
    "decode_frame",
    "check_version",
    "to_jsonable",
    "encode_state",
]

#: bump when a frame/body field changes meaning; the daemon rejects other
#: versions with ``unsupported_version`` instead of guessing
WIRE_VERSION = 1

ERROR_CODES = (
    "backpressure",
    "bad_seq",
    "not_found",
    "exists",
    "draining",
    "failed",
    "bad_payload",
    "bad_request",
    "unsupported_version",
    "fingerprint_mismatch",
)

#: marker key for a dtype-preserving array in a state payload
ND_KEY = "__nd__"


class WireError(ValueError):
    """A frame/body that violates the wire schema."""


def ok(**fields: Any) -> Dict[str, Any]:
    """A success envelope: ``{"v": 1, "ok": True, **fields}``."""
    return {"v": WIRE_VERSION, "ok": True, **fields}


def error(code: str, message: str, **detail: Any) -> Dict[str, Any]:
    """An error envelope with a machine-switchable ``code``."""
    if code not in ERROR_CODES:
        raise WireError(f"unknown error code {code!r} (add it to ERROR_CODES first)")
    return {"v": WIRE_VERSION, "ok": False, "error": {"code": code, "message": message, **detail}}


def encode_frame(obj: Dict[str, Any]) -> bytes:
    """One newline-terminated compact-JSON frame (the socket unit)."""
    return json.dumps(obj, separators=(",", ":")).encode() + b"\n"


def decode_frame(line: bytes) -> Dict[str, Any]:
    """Parse one frame; raises :class:`WireError` on non-JSON / non-object."""
    try:
        obj = json.loads(line)
    except ValueError as err:
        raise WireError(f"frame is not JSON: {err}") from None
    if not isinstance(obj, dict):
        raise WireError(f"frame must be a JSON object, got {type(obj).__name__}")
    return obj


def check_version(obj: Dict[str, Any]) -> None:
    """Reject a frame/body whose ``"v"`` is missing or not ours."""
    version = obj.get("v")
    if version != WIRE_VERSION:
        raise WireError(
            f"unsupported wire version {version!r} (this daemon speaks v{WIRE_VERSION})"
        )


def to_jsonable(value: Any) -> Any:
    """Results/checkpoint values → plain JSON types, duck-typed so this
    module never imports numpy/jax: array-likes go through ``tolist()``,
    0-d scalars through ``item()``, dict keys become strings (a
    ``SlicedPlan.results()`` tuple key renders as ``"(3, 1)"``)."""
    if isinstance(value, dict):
        return {str(k) if not isinstance(k, str) else k: to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [to_jsonable(v) for v in value]
    if hasattr(value, "tolist"):
        return value.tolist()
    if hasattr(value, "item") and not isinstance(value, (int, float, bool, str)):
        try:
            return value.item()
        except Exception:
            return repr(value)
    if isinstance(value, (int, float, bool, str)) or value is None:
        return value
    return repr(value)


def encode_state(value: Any) -> Any:
    """A checkpoint/state tree → JSON with dtype-preserving array markers.

    Arrays (anything with ``dtype``/``shape``/``tolist`` — numpy and jax
    alike, duck-typed so this module never imports either) become
    ``{"__nd__": "<dtype>", "shape": [...], "data": <nested lists>}``;
    0-d arrays and numpy scalars ride the same marker with ``"shape": []``.
    Everything else passes through :func:`to_jsonable` semantics. The
    decoder (``federation.decode_state``) rebuilds exact-dtype ndarrays, so
    the strict ``load_state_tree`` ladder accepts the round-trip.
    """
    if isinstance(value, dict):
        return {str(k) if not isinstance(k, str) else k: encode_state(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [encode_state(v) for v in value]
    if hasattr(value, "dtype") and hasattr(value, "shape") and hasattr(value, "tolist"):
        return {
            ND_KEY: str(value.dtype),
            "shape": [int(d) for d in value.shape],
            "data": value.tolist(),
        }
    if isinstance(value, bytes):
        return {"__bytes__": value.decode("latin-1")}
    if isinstance(value, (int, float, bool, str)) or value is None:
        return value
    if hasattr(value, "item"):
        try:
            return value.item()
        except Exception:
            return repr(value)
    return repr(value)
