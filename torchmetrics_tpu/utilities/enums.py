# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""String enums used across the framework.

Capability parity with reference ``src/torchmetrics/utilities/enums.py``.
"""
from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Case-insensitive string enum (reference ``enums.py:20``)."""

    @staticmethod
    def _name() -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "Key") -> "EnumStr":
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError as err:
            valid = [str(m.value) for m in cls]
            raise ValueError(
                f"Invalid {cls._name()}: expected one of {valid}, but got {value}."
            ) from err

    def __str__(self) -> str:
        return self.value.lower()

    def __hash__(self) -> int:
        return hash(self.value.lower())

    def __eq__(self, other: object) -> bool:
        other = other.value if isinstance(other, Enum) else str(other)
        return self.value.lower() == other.lower()


class DataType(EnumStr):
    """Input data format (reference ``enums.py:56``)."""

    @staticmethod
    def _name() -> str:
        return "Data type"

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Averaging strategy (reference ``enums.py:74``)."""

    @staticmethod
    def _name() -> str:
        return "Average method"

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = None  # type: ignore[assignment]
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class averaging."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Classification task dispatch key (reference ``enums.py:108``)."""

    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    @staticmethod
    def _name() -> str:
        return "Classification"

    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    @staticmethod
    def _name() -> str:
        return "Classification"

    BINARY = "binary"
    MULTICLASS = "multiclass"


def _allclose_enum(value: Optional[str], enum_cls: type) -> bool:
    return value in [m.value for m in enum_cls]
