# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Rank-zero printing/warning discipline.

Capability parity with reference ``src/torchmetrics/utilities/prints.py:22-68``.
In JAX the analogue of "rank" is the process index (multi-host); within one
process all devices share the Python interpreter, so process 0 is rank zero.
"""
from __future__ import annotations

import logging
import warnings
from functools import partial, wraps
from typing import Any, Callable


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Call ``fn`` only on process 0 of a multi-host run."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_print(*args: Any, **kwargs: Any) -> None:
    print(*args, **kwargs)


@rank_zero_only
def rank_zero_warn(message: str, *args: Any, **kwargs: Any) -> None:
    kwargs.setdefault("stacklevel", 5)
    warnings.warn(message, *args, **kwargs)


_log = logging.getLogger("torchmetrics_tpu")


@rank_zero_only
def rank_zero_debug(*args: Any, **kwargs: Any) -> None:
    """Log at debug level on process 0 only (reference ``utilities/prints.py``)."""
    _log.debug(*args, **kwargs)


@rank_zero_only
def rank_zero_info(*args: Any, **kwargs: Any) -> None:
    """Log at info level on process 0 only (reference ``utilities/prints.py``)."""
    _log.info(*args, **kwargs)


def _deprecation_warn(message: str) -> None:
    rank_zero_warn(message, DeprecationWarning)


rank_zero_deprecation = partial(rank_zero_warn, category=DeprecationWarning)
