# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Cross-process synchronization primitives.

Capability parity with reference ``src/torchmetrics/utilities/distributed.py``,
re-designed for JAX's two distribution regimes:

1. **In-step sharding (primary, TPU-native)** — metric updates run inside
   ``pjit``/``shard_map`` over a ``jax.sharding.Mesh``; per-device partial
   states are merged with XLA collectives (``psum``/``pmax``/``all_gather``)
   over ICI. See ``torchmetrics_tpu.parallel``. This subsumes the reference's
   per-step NCCL path.
2. **Multi-host replica sync (this module)** — the analogue of the reference's
   ``gather_all_tensors`` (``distributed.py:97-147``): each *process* holds a
   local replica of the states; ``Metric.sync()`` gathers them over DCN via
   ``jax.experimental.multihost_utils``. The reference's pad-to-max-then-trim
   protocol for uneven shapes (``:124-147``) is reproduced on the host.
"""
from __future__ import annotations

import struct
import zlib
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.robustness import faults
from torchmetrics_tpu.utilities.exceptions import SyncError

Array = jax.Array


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor by elementwise-mean/sum or identity (reference ``distributed.py:22``)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction is None or reduction == "none":
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Reduce per-class metric scores (reference ``distributed.py:45``)."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights.astype(jnp.float32) / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


def distributed_available() -> bool:
    """True when more than one JAX process participates (multi-host)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def world_size() -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def gather_all_arrays(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather an array from every process, supporting uneven dim sizes.

    Mirrors reference ``gather_all_tensors`` (``distributed.py:97-147``):
    gather shapes first, pad every local tensor to the per-dim max, all-gather,
    then trim each gathered tensor back to its true shape. Runs on host via
    ``multihost_utils`` (DCN); single-process returns ``[result]``.
    ``group`` is accepted for API parity; JAX collectives span all processes.
    """
    if not distributed_available():
        return [result]
    from jax.experimental import multihost_utils

    if faults._ACTIVE:
        faults.fire("gather_arrays.pre")
    result = jnp.asarray(result)
    local_shape = np.asarray(result.shape, dtype=np.int32)
    ndim = np.int32(result.ndim)
    # gather every process's rank FIRST and size the shape buffer from the
    # global max, so arbitrary-ndim arrays gather cleanly (a static max_rank=8
    # buffer used to overflow on ndim > 8 with an opaque broadcast error)
    ranks = np.asarray(multihost_utils.process_allgather(jnp.asarray([ndim])))
    max_rank = max(1, int(ranks.max()))
    shape_buf = np.zeros((max_rank,), dtype=np.int32)
    shape_buf[: local_shape.size] = local_shape
    all_shapes = np.asarray(multihost_utils.process_allgather(jnp.asarray(shape_buf)))
    n_proc = all_shapes.shape[0]
    all_true_shapes = [tuple(int(d) for d in all_shapes[p][: int(ranks[p][0])]) for p in range(n_proc)]
    # fast path: all shapes equal
    if all(s == all_true_shapes[0] for s in all_true_shapes):
        stacked = np.asarray(multihost_utils.process_allgather(result))
        return [jnp.asarray(stacked[p]) for p in range(n_proc)]
    # slow path: pad to per-dim max, gather, trim (reference :124-147)
    max_shape = tuple(int(m) for m in np.max(np.stack([np.array(s + (0,) * (max_rank - len(s))) for s in all_true_shapes]), axis=0)[: result.ndim])
    pad_width = [(0, m - s) for m, s in zip(max_shape, result.shape)]
    padded = jnp.pad(result, pad_width)
    stacked = np.asarray(multihost_utils.process_allgather(padded))
    out: List[Array] = []
    for p in range(n_proc):
        slices = tuple(slice(0, d) for d in all_true_shapes[p])
        out.append(jnp.asarray(stacked[p][slices]))
    return out


def gather_all_objects(obj: Any) -> List[Any]:
    """Gather arbitrary picklable objects from all processes.

    Analogue of ``dist.all_gather_object`` used by mAP RLE masks
    (reference ``detection/mean_ap.py:1043-1061``).
    """
    if not distributed_available():
        return [obj]
    from jax.experimental import multihost_utils

    return list(multihost_utils.broadcast_one_to_all_and_gather(obj)) if hasattr(multihost_utils, "broadcast_one_to_all_and_gather") else _gather_objects_via_bytes(obj)


#: wire header of the object-gather protocol: u64 payload length + u32 CRC32.
#: The CRC turns a corrupt or truncated payload into a :class:`SyncError`
#: naming the offending rank instead of an opaque ``pickle.loads`` failure
#: (or, worse, silently wrong deserialized state).
_OBJ_HEADER = struct.Struct("<QI")


def _gather_objects_via_bytes(obj: Any) -> List[Any]:
    import pickle

    from jax.experimental import multihost_utils

    if faults._ACTIVE:
        faults.fire("gather_bytes.pre")
    payload = pickle.dumps(obj)
    wire = _OBJ_HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload
    if faults._ACTIVE:
        wire = faults.mutate_bytes("gather_bytes.payload", wire, header_len=_OBJ_HEADER.size)
    buf_local = np.frombuffer(wire, dtype=np.uint8)
    size = jnp.asarray([buf_local.size], dtype=jnp.int32)
    sizes = np.asarray(multihost_utils.process_allgather(size)).reshape(-1)
    max_size = int(sizes.max())
    buf = np.zeros((max_size,), dtype=np.uint8)
    buf[: buf_local.size] = buf_local
    # single-process allgather returns the bare (n,) buffer; normalize to the
    # (n_proc, n) layout so the integrity checks below are regime-agnostic
    gathered = np.atleast_2d(np.asarray(multihost_utils.process_allgather(jnp.asarray(buf))))
    out: List[Any] = []
    for p in range(gathered.shape[0]):
        total = int(sizes[p])
        if total < _OBJ_HEADER.size:
            raise SyncError(
                f"object gather: rank {p} sent {total} byte(s), smaller than the {_OBJ_HEADER.size}-byte header —"
                " truncated payload"
            )
        length, crc = _OBJ_HEADER.unpack(gathered[p][: _OBJ_HEADER.size].tobytes())
        data = gathered[p][_OBJ_HEADER.size : _OBJ_HEADER.size + length].tobytes()
        if len(data) != length or _OBJ_HEADER.size + length > total:
            raise SyncError(
                f"object gather: rank {p} declared {length} payload byte(s) but sent {total - _OBJ_HEADER.size} —"
                " truncated payload"
            )
        if zlib.crc32(data) & 0xFFFFFFFF != crc:
            raise SyncError(f"object gather: payload from rank {p} failed its CRC32 integrity check — corrupt payload")
        try:
            out.append(pickle.loads(data))
        except Exception as err:
            raise SyncError(f"object gather: payload from rank {p} passed CRC but failed to unpickle: {err}") from err
    return out
