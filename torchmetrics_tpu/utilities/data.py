# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Array manipulation helpers (the L1 utility layer).

Capability parity with reference ``src/torchmetrics/utilities/data.py``.
Everything here is pure ``jax.numpy`` with static shapes, so it can live
inside ``jit``/``shard_map``-traced code. Notably the reference's
deterministic/XLA ``_bincount`` fallback (``data.py:203-205``) — a one-hot
compare-and-sum — is unnecessary on TPU: ``jnp.bincount(x, length=n)`` lowers
to an XLA scatter-add which is already deterministic; we keep the compare
formulation available as ``_bincount_onehot`` for tiny ``n`` where it fuses
better.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def dim_zero_cat(x: Union[Array, List[Array], Tuple[Array, ...]]) -> Array:
    """Concatenate a (list of) array(s) along dim 0 (reference ``data.py:28``)."""
    if isinstance(x, (jnp.ndarray, np.ndarray)) and not isinstance(x, (list, tuple)):
        return jnp.asarray(x)
    x = [jnp.atleast_1d(jnp.asarray(t)) for t in x]
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten list of lists into one list (reference ``data.py:58``)."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: Dict) -> Tuple[Dict, bool]:
    """Flatten dict of dicts into one level (reference ``data.py:63-77``)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: int) -> Array:
    """Integer labels ``(N, ...)`` -> one-hot ``(N, C, ...)`` (reference ``data.py:80``)."""
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int64 if label_tensor.dtype == jnp.int64 else jnp.int32)
    # one_hot appends the class dim last; reference puts it at dim 1
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim`` (reference ``data.py:125``).

    ``topk=1`` fast path uses argmax (reference ``data.py:145-146``).
    """
    if topk == 1:
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    _, idx = jax.lax.top_k(jnp.moveaxis(prob_tensor, dim, -1), topk)
    mask = jnp.zeros(jnp.moveaxis(prob_tensor, dim, -1).shape, dtype=jnp.int32)
    mask = jnp.put_along_axis(mask, idx, 1, axis=-1, inplace=False)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities -> class index via argmax (reference ``data.py:152``)."""
    return jnp.argmax(x, axis=argmax_dim)


def _squeeze_scalar_element_tensor(x: Array) -> Array:
    return x.squeeze() if x.size == 1 else x


def _squeeze_if_scalar(data: Any) -> Any:
    return jax.tree_util.tree_map(_squeeze_scalar_element_tensor, data)


def _bincount(x: Array, minlength: int) -> Array:
    """Count occurrences of each value in ``[0, minlength)``.

    Reference ``data.py:179-207``. ``jnp.bincount`` with a static ``length``
    is an XLA scatter-add — deterministic and TPU-native; no fallback needed.
    """
    return jnp.bincount(x.reshape(-1), length=minlength)


def _bincount_onehot(x: Array, minlength: int) -> Array:
    """Compare-and-sum bincount — the reference's deterministic fallback
    (``data.py:203-205``); fuses well for small ``minlength``."""
    mesh = jnp.arange(minlength, dtype=x.dtype)
    return (x.reshape(-1, 1) == mesh.reshape(1, -1)).sum(axis=0)


def _cumsum(x: Array, dim: int = 0, dtype=None) -> Array:
    """Cumulative sum (reference ``data.py:210``; no CPU fallback needed on TPU)."""
    return jnp.cumsum(x, axis=dim, dtype=dtype)


def _flexible_bincount(x: Array) -> Array:  # metriclint: disable=ML004 -- unique is inherently dynamic-shape; documented host-only helper
    """Count occurrences of each *unique* value (reference ``data.py:222``).

    Unique is inherently dynamic-shape; runs on host (NumPy). Only used in
    host-side compute paths (e.g. retrieval query splitting).
    """
    x = np.asarray(x)
    _, counts = np.unique(x, return_counts=True)
    return jnp.asarray(counts)


def allclose(tensor1: Array, tensor2: Array, rtol: float = 1e-5, atol: float = 1e-8) -> bool:  # metriclint: disable=ML002 -- returns a Python bool by contract; host-only comparison helper
    """Shape- and dtype-robust allclose (reference ``data.py:241``)."""
    if jnp.shape(tensor1) != jnp.shape(tensor2):
        return False
    return bool(jnp.allclose(jnp.asarray(tensor1, jnp.float32), jnp.asarray(tensor2, jnp.float32), rtol=rtol, atol=atol))
