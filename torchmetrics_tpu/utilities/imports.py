# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Optional-dependency feature flags.

Capability parity with reference ``src/torchmetrics/utilities/imports.py:22-70``
(``RequirementCache`` flags). Implemented with a light importlib probe: no
pkg_resources, evaluated lazily and cached.
"""
from __future__ import annotations

import importlib
import importlib.util
from functools import lru_cache


@lru_cache(maxsize=None)
def _module_available(module: str) -> bool:
    try:
        return importlib.util.find_spec(module) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


class ModuleAvailableCache:
    """Lazy boolean flag for an optional dependency, ``bool(flag)`` probes once."""

    def __init__(self, module: str) -> None:
        self.module = module

    def __bool__(self) -> bool:
        return _module_available(self.module)

    def __repr__(self) -> str:
        return f"ModuleAvailableCache({self.module!r}, available={bool(self)})"


_JAX_AVAILABLE = ModuleAvailableCache("jax")
_FLAX_AVAILABLE = ModuleAvailableCache("flax")
_SCIPY_AVAILABLE = ModuleAvailableCache("scipy")
_MATPLOTLIB_AVAILABLE = ModuleAvailableCache("matplotlib")
_SCIENCEPLOT_AVAILABLE = ModuleAvailableCache("scienceplots")
_TRANSFORMERS_AVAILABLE = ModuleAvailableCache("transformers")
_NLTK_AVAILABLE = ModuleAvailableCache("nltk")
_REGEX_AVAILABLE = ModuleAvailableCache("regex")
_PESQ_AVAILABLE = ModuleAvailableCache("pesq")
_PYSTOI_AVAILABLE = ModuleAvailableCache("pystoi")
_LIBROSA_AVAILABLE = ModuleAvailableCache("librosa")
_ONNXRUNTIME_AVAILABLE = ModuleAvailableCache("onnxruntime")
_GAMMATONE_AVAILABLE = ModuleAvailableCache("gammatone")
_MECAB_AVAILABLE = ModuleAvailableCache("MeCab")
_IPADIC_AVAILABLE = ModuleAvailableCache("ipadic")
_SENTENCEPIECE_AVAILABLE = ModuleAvailableCache("sentencepiece")
_SKLEARN_AVAILABLE = ModuleAvailableCache("sklearn")
_TORCH_AVAILABLE = ModuleAvailableCache("torch")
_PIQ_GREATER_EQUAL_0_8 = ModuleAvailableCache("piq")
