# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Matplotlib-optional plotting renderers.

Capability parity with reference ``src/torchmetrics/utilities/plot.py``
(``plot_single_or_multi_val :64``, ``plot_confusion_matrix :220``,
``plot_curve :296``).
"""
from __future__ import annotations

from math import ceil, floor, sqrt
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utilities.imports import _MATPLOTLIB_AVAILABLE

_error_msg = "matplotlib is required to plot metrics. Install with `pip install matplotlib`."


def _get_plt():
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)
    import matplotlib.pyplot as plt

    return plt


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(_error_msg)


def plot_single_or_multi_val(
    val,
    ax=None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a single/multiple scalar value(s) (reference ``plot.py:64``)."""
    plt = _get_plt()
    fig, ax = (None, ax) if ax is not None else plt.subplots(1, 1)
    if isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            v = np.asarray(v)
            if v.ndim == 0:
                ax.plot([i], [float(v)], marker="o", markersize=10, linestyle="None", label=k)
            else:
                ax.plot(np.ravel(v), label=k)
    elif isinstance(val, Sequence) and not isinstance(val, str):
        arr = np.stack([np.atleast_1d(np.asarray(v)) for v in val])
        if arr.ndim == 2 and arr.shape[1] > 1:
            for c in range(arr.shape[1]):
                ax.plot(arr[:, c], marker="o", label=f"{legend_name or 'class'} {c}")
        else:
            ax.plot(np.ravel(arr), marker="o")
    else:
        arr = np.atleast_1d(np.asarray(val))
        ax.plot(np.arange(arr.size), np.ravel(arr), marker="o", markersize=10, linestyle="None")
    if lower_bound is not None or upper_bound is not None:
        ax.set_ylim(bottom=lower_bound, top=upper_bound)
    if name is not None:
        ax.set_title(name)
    handles, labels = ax.get_legend_handles_labels()
    if labels:
        ax.legend()
    ax.grid(True)
    return fig, ax


def trim_axs(axs, nb: int):
    """Trim a grid of axes to ``nb`` used axes (reference ``plot.py:192``)."""
    if not isinstance(axs, np.ndarray):
        return axs
    axs = axs.flat
    for ax in axs[nb:]:
        ax.remove()
    return axs[:nb]


def plot_confusion_matrix(
    confmat,
    ax=None,
    add_text: bool = True,
    labels: Optional[List[Union[str, int]]] = None,
    cmap=None,
):
    """Render one or several confusion matrices (reference ``plot.py:220``)."""
    plt = _get_plt()
    confmat = np.asarray(confmat)
    if confmat.ndim == 3:  # multilabel
        nb, n_classes = confmat.shape[0], 2
        rows, cols = floor(sqrt(nb)), ceil(nb / floor(sqrt(nb)))
    else:
        nb, n_classes, rows, cols = 1, confmat.shape[0], 1, 1
        confmat = confmat[None]
    if labels is not None and confmat.ndim == 3 and len(labels) != n_classes:
        raise ValueError("Expected number of elements in arg `labels` to match number of labels in confmat")
    labels = labels or np.arange(n_classes).tolist()
    if ax is None:
        fig, axs = plt.subplots(nrows=rows, ncols=cols)
    else:
        fig, axs = None, ax
    axs = trim_axs(axs, nb) if nb > 1 else [axs]
    for i in range(nb):
        ax_i = axs[i] if nb > 1 else axs[0]
        im = ax_i.imshow(confmat[i], cmap=cmap)
        if nb > 1:
            ax_i.set_title(f"Label {i}", fontsize=15)
        ax_i.set_xlabel("Predicted class", fontsize=15)
        ax_i.set_ylabel("True class", fontsize=15)
        ax_i.set_xticks(list(range(n_classes)))
        ax_i.set_yticks(list(range(n_classes)))
        ax_i.set_xticklabels(labels, rotation=45, fontsize=10)
        ax_i.set_yticklabels(labels, rotation=25, fontsize=10)
        if add_text:
            for ii in range(n_classes):
                for jj in range(n_classes):
                    val = confmat[i, ii, jj]
                    ax_i.text(jj, ii, str(round(float(val), 2) if np.issubdtype(confmat.dtype, np.floating) else int(val)), ha="center", va="center", fontsize=15)
    return fig, axs if nb > 1 else axs[0]


def plot_curve(
    curve: Tuple,
    score=None,
    ax=None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a ROC/PR-style curve (reference ``plot.py:296``)."""
    plt = _get_plt()
    x, y = np.asarray(curve[0]), np.asarray(curve[1])
    fig, ax = (None, ax) if ax is not None else plt.subplots(1, 1)
    if y.ndim > x.ndim:  # per-class curves share x
        for c in range(y.shape[0]):
            ax.plot(x, y[c], linestyle="-", linewidth=2, label=f"{legend_name or 'class'} {c}")
    elif x.ndim == 2:
        for c in range(x.shape[0]):
            ax.plot(x[c], y[c], linestyle="-", linewidth=2, label=f"{legend_name or 'class'} {c}")
    else:
        label = f"AUC={float(np.asarray(score)):0.3f}" if score is not None else None
        ax.plot(x, y, linestyle="-", linewidth=2, label=label)
    if label_names is not None:
        ax.set_xlabel(label_names[0], fontsize=12)
        ax.set_ylabel(label_names[1], fontsize=12)
    if name is not None:
        ax.set_title(name)
    handles, labels = ax.get_legend_handles_labels()
    if labels:
        ax.legend()
    ax.grid(True)
    return fig, ax
