# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Input validation helpers.

Capability parity with reference ``src/torchmetrics/utilities/checks.py``.
Validation runs at trace/host time on shapes & dtypes (static under jit);
value-dependent checks (e.g. label range) are only performed when inputs are
concrete (eager), matching the reference's ``validate_args`` contract.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _is_concrete(x) -> bool:
    """True when ``x`` holds real values (not a tracer) so value checks can run."""
    return not isinstance(x, jax.core.Tracer)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if shapes differ (reference ``checks.py:37``)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _check_for_empty_tensors(preds: Array, target: Array) -> bool:
    """True when BOTH inputs are empty (reference ``checks.py:33``)."""
    return preds.size == 0 and target.size == 0


def _input_squeeze(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Drop a singleton trailing/batch axis pair (reference ``checks.py:301``)."""
    if preds.shape[0] == 1:
        preds = jnp.expand_dims(preds.squeeze(), 0)
        target = jnp.expand_dims(target.squeeze(), 0)
    else:
        preds, target = preds.squeeze(), target.squeeze()
    return preds, target


def is_overridden(method_name: str, instance: object, parent: type) -> bool:
    """True when ``instance``'s class overrides ``parent.method_name``
    (reference ``checks.py:739``)."""
    instance_attr = getattr(type(instance), method_name, None)
    parent_attr = getattr(parent, method_name, None)
    if instance_attr is None or parent_attr is None:
        return False
    return instance_attr is not parent_attr


def _check_retrieval_target_and_prediction_types(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Dtype/value checks shared by the retrieval input validators
    (reference ``checks.py:587``): float preds, bool/int/float target,
    binary target values unless explicitly allowed."""
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a array of floats")
    if not (
        jnp.issubdtype(target.dtype, jnp.integer)
        or jnp.issubdtype(target.dtype, jnp.bool_)
        or jnp.issubdtype(target.dtype, jnp.floating)
    ):
        raise ValueError("`target` must be a array of booleans, integers or floats")
    if (
        not allow_non_binary_target
        and _is_concrete(target)
        and target.size
        and bool((target.max() > 1) | (target.min() < 0))  # metriclint: disable=ML002 -- guarded by _is_concrete: a tracer never reaches the coercion
    ):
        # range semantics, not exact-{0,1}: the reference accepts fractional
        # relevance in [0, 1] (checks.py:610)
        raise ValueError("`target` must contain `binary` values")
    dtype = jnp.float32 if not allow_non_binary_target else target.dtype
    return preds.reshape(-1).astype(jnp.float32), target.reshape(-1).astype(dtype)


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    """Check and format retrieval inputs (reference ``checks.py:507``)."""
    if preds.shape != target.shape or preds.ndim == 0 or preds.size == 0:
        raise ValueError("`preds` and `target` must be non-empty and of the same shape")
    return _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)


def _check_retrieval_inputs(
    indexes: Array, preds: Array, target: Array, allow_non_binary_target: bool = False, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """Check and format retrieval class inputs (reference ``checks.py:538``)."""
    if indexes.shape != preds.shape or preds.shape != target.shape or indexes.ndim == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a array of integers")
    if ignore_index is not None:
        valid = np.asarray(target) != ignore_index
        indexes, preds, target = (np.asarray(indexes)[valid], np.asarray(preds)[valid], np.asarray(target)[valid])
        indexes, preds, target = jnp.asarray(indexes), jnp.asarray(preds), jnp.asarray(target)
    # emptiness is checked AFTER ignore_index filtering (reference
    # checks.py:575): an all-ignored batch must raise, not return empties
    if preds.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and of the same shape")
    preds, target = _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)
    return (indexes.reshape(-1).astype(jnp.int32), preds, target)


def _allclose_recursive(res1, res2, atol: float = 1e-6) -> bool:  # metriclint: disable=ML002 -- test-harness comparison helper, host-only
    if isinstance(res1, (list, tuple)):
        return all(_allclose_recursive(r1, r2, atol) for r1, r2 in zip(res1, res2))
    if isinstance(res1, dict):
        return all(_allclose_recursive(res1[k], res2[k], atol) for k in res1)
    return bool(jnp.allclose(jnp.asarray(res1), jnp.asarray(res2), atol=atol))


def check_forward_full_state_property(
    metric_class, init_args: Optional[dict] = None, input_args: Optional[dict] = None, num_update_to_compare=(10, 100, 1000), reps: int = 5
) -> None:
    """Empirically compare full-state vs partial-state ``forward`` (reference ``checks.py:634``)."""
    import time

    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):  # type: ignore[misc, valid-type]
        full_state_update = True

    class PartState(metric_class):  # type: ignore[misc, valid-type]
        full_state_update = False

    fs, ps = FullState(**init_args), PartState(**init_args)
    res1 = fs(**input_args)
    res2 = ps(**input_args)
    if not _allclose_recursive(res1, res2):
        raise RuntimeError(
            "The metric does not give the same result with `full_state_update=False`; it must keep the default."
        )
    for metric, name in [(fs, "full"), (ps, "partial")]:
        for num in num_update_to_compare:
            metric.reset()
            start = time.perf_counter()
            for _ in range(num):
                metric(**input_args)
            jax.block_until_ready(metric.compute())
            print(f"{name} state `forward` x{num}: {time.perf_counter() - start:.4f}s")
