# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Numerically-safe compute helpers.

Capability parity with reference ``src/torchmetrics/utilities/compute.py``.
All functions are pure jnp and jit-safe.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _dim_sum(x: Array, axis: int) -> Array:
    """``x.sum(axis)`` that is a no-op on 0-d arrays (torch-compatible
    semantics: torch allows ``sum(dim=0)`` on scalars, jnp does not)."""
    return x.sum(axis=axis) if jnp.ndim(x) > 0 else x


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul that promotes half precision inputs (reference ``compute.py:20``)."""
    if x.dtype in (jnp.float16, jnp.bfloat16) or y.dtype in (jnp.float16, jnp.bfloat16):
        return (x.astype(jnp.float32) @ y.astype(jnp.float32).T).astype(x.dtype)
    return x @ y.T


def _safe_xlogy(x: Array, y: Array) -> Array:
    """``x * log(y)`` that is 0 whenever ``x == 0`` (reference ``compute.py:31``)."""
    res = jax.scipy.special.xlogy(x, y)
    return jnp.where(x == 0.0, 0.0, res)


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Division with a defined value where ``denom == 0`` (reference ``compute.py:46``)."""
    num = num if jnp.issubdtype(jnp.asarray(num).dtype, jnp.floating) else jnp.asarray(num, jnp.float32)
    denom = denom if jnp.issubdtype(jnp.asarray(denom).dtype, jnp.floating) else jnp.asarray(denom, jnp.float32)
    zero = jnp.asarray(zero_division, dtype=jnp.result_type(num, denom))
    return jnp.where(denom != 0, num / jnp.where(denom != 0, denom, 1.0), zero)


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array, top_k: int = 1
) -> Array:
    """Weighted/macro final averaging of per-class scores (reference ``compute.py:63``)."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = tp + fn
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            # with top_k > 1 a class can collect fp without ever appearing in
            # target; only absent classes (tp+fn==0) are dropped then
            # (reference ``compute.py:70-75``)
            mask = (tp + fn == 0) if top_k != 1 else (tp + fp + fn == 0)
            weights = jnp.where(mask, 0.0, weights)
        weights = jnp.where(jnp.isnan(score), 0.0, weights)
    score = jnp.where(jnp.isnan(score), 0.0, score)
    return _safe_divide(weights * score, weights.sum(-1, keepdims=True)).sum(-1)


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal area under curve (reference ``compute.py:93``)."""
    dx = jnp.diff(x, axis=axis)
    return jnp.sum((jnp.take(y, jnp.arange(1, y.shape[axis]), axis=axis) + jnp.take(y, jnp.arange(0, y.shape[axis] - 1), axis=axis)) / 2.0 * dx, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """AUC with monotonicity handling (reference ``compute.py:99-120``).

    Direction detection is data-dependent; jit-safe via a sign computed with jnp.
    """
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
        direction = jnp.asarray(1.0)
    else:
        dx = jnp.diff(x)
        any_neg = jnp.any(dx < 0)
        all_nonpos = jnp.all(dx <= 0)
        # matches reference semantics: decreasing -> -1, mixed -> nan-free error at
        # trace time is impossible, so emit nan to signal invalid ordering
        direction = jnp.where(any_neg, jnp.where(all_nonpos, -1.0, jnp.nan), 1.0)
    return _auc_compute_without_check(x, y, direction)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve ``y(x)`` using the trapezoidal rule."""
    if x.ndim != 1 or y.ndim != 1:
        raise ValueError(f"Expected 1d arrays, got x.ndim={x.ndim}, y.ndim={y.ndim}")
    if x.shape[0] != y.shape[0]:
        raise ValueError("x and y must have the same length")
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1-D linear interpolation, ``numpy.interp`` semantics (reference ``compute.py:139``)."""
    return jnp.interp(x, xp, fp)


def normalize_logits_if_needed(tensor: Array, normalization: str = "sigmoid") -> Array:
    """Apply sigmoid/softmax only when inputs are outside [0, 1].

    The reference checks ``if not ((preds >= 0) & (preds <= 1)).all(): sigmoid()``
    — a data-dependent branch. Under jit we compute both and select, which XLA
    fuses into a single elementwise kernel.
    """
    if tensor.size == 0:  # empty update (e.g. a data-less rank) — nothing to normalize
        return tensor
    if normalization == "sigmoid":
        in_range = (tensor.min() >= 0) & (tensor.max() <= 1)
        return jnp.where(in_range, tensor, jax.nn.sigmoid(tensor))
    if normalization == "softmax":
        in_range = (tensor.min() >= 0) & (tensor.max() <= 1)
        return jnp.where(in_range, tensor, jax.nn.softmax(tensor, axis=1))
    return tensor
