# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Utility layer (L1): reductions, safe math, checks, distributed primitives."""
from torchmetrics_tpu.utilities.checks import _check_same_shape, check_forward_full_state_property
from torchmetrics_tpu.utilities.data import (
    _bincount,
    _cumsum,
    _flatten,
    _flatten_dict,
    _flexible_bincount,
    _squeeze_if_scalar,
    allclose,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    select_topk,
    to_categorical,
    to_onehot,
)
from torchmetrics_tpu.utilities.distributed import class_reduce, gather_all_arrays, reduce
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from torchmetrics_tpu.utilities.prints import rank_zero_debug, rank_zero_info, rank_zero_print, rank_zero_warn

__all__ = [
    "check_forward_full_state_property",
    "allclose",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "select_topk",
    "to_categorical",
    "to_onehot",
    "class_reduce",
    "gather_all_arrays",
    "reduce",
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_print",
    "rank_zero_warn",
]
