# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Per-object cache of jitted forward functions for embedded towers.

Flax transformers models called eagerly dispatch thousands of individual XLA
ops — one host round-trip each on a remote TPU. Metrics that embed a neural
tower (BERTScore, InfoLM, CLIPScore, CLIP-IQA) route every model call through
here so the whole encoder runs as ONE compiled program per input shape.
"""
from __future__ import annotations

import weakref
from typing import Any, Callable, Dict, Optional, Tuple

import jax

_CACHE: Dict[Tuple[int, str], Callable] = {}
_PARAMS_ON_DEVICE: Dict[int, Tuple[Any, Any]] = {}  # id(obj) -> (source params, device copy)
_FINALIZERS: Dict[int, Any] = {}  # id(obj) -> weakref.finalize handle


def _evict_id(obj_id: int) -> None:
    for key in [k for k in _CACHE if k[0] == obj_id]:
        del _CACHE[key]
    _PARAMS_ON_DEVICE.pop(obj_id, None)
    # detach the finalizer so a manual evict followed by a re-jit of the same
    # live object doesn't accumulate duplicate (idempotent but untracked) ones
    fin = _FINALIZERS.pop(obj_id, None)
    if fin is not None:
        fin.detach()


def _device_params(obj: Any, params_attr: str) -> Any:
    """The model's params resident on the default device, transferred once.

    Towers are initialized on the host CPU backend (eager random init on a
    remote TPU costs one round-trip per op); without this cache every jit
    call would re-upload the full weight pytree (~0.4GB for bert-base) over
    the wire. Re-transfers only when the params attribute is rebound.
    """
    entry = _PARAMS_ON_DEVICE.get(id(obj))
    src = getattr(obj, params_attr)
    if entry is None or entry[0] is not src:
        entry = (src, jax.device_put(src))
        _PARAMS_ON_DEVICE[id(obj)] = entry
    return entry[1]


def jitted_forward(
    obj: Any,
    method: str,
    make_fn: Optional[Callable[[Any], Callable]] = None,
    params_attr: str = "params",
) -> Callable:
    """A jitted callable for ``obj.<method>``, compiled once per (object, tag).

    The model's weights enter the compiled program as jit ARGUMENTS, never as
    captured constants — baking ~100M floats into the HLO multiplies compile
    time several-fold (measured 140s → 18s for a 2-layer BERT on a remote
    TPU). The ``params_attr`` attribute (``.params`` for transformers models,
    ``.variables`` for Flax-module wrappers) is re-read on every call, so
    weight swaps are seen.

    ``make_fn(obj)`` can build a custom closure ``inner(params, *args)``
    instead (e.g. to select an output field) — ``method`` then only serves as
    the cache tag. The default path holds ``obj`` only weakly, and a
    ``weakref.finalize`` evicts the object's cache entries (compiled programs
    + ~0.4GB device weight copy for bert-base) when the tower is garbage
    collected, so cloned/deepcopied metrics don't leak device memory over a
    long process. A ``make_fn`` closure may still pin ``obj`` — callers that
    capture it strongly should ``evict(obj)`` when retiring the tower.
    """
    key = (id(obj), method)
    fn = _CACHE.get(key)
    if fn is None:
        if make_fn is not None:
            inner = make_fn(obj)
        else:
            obj_ref = weakref.ref(obj)
            unbound = getattr(type(obj), method)

            def inner(params, *args):
                target = obj_ref()
                if target is None:  # only reachable on a retrace after GC
                    raise RuntimeError("tower was garbage-collected")
                return unbound(target, *args, params=params)

        fn = _CACHE[key] = jax.jit(inner)
        if id(obj) not in _FINALIZERS:
            try:
                _FINALIZERS[id(obj)] = weakref.finalize(obj, _evict_id, id(obj))
            except TypeError:
                pass  # not weakref-able; manual evict() remains the relief

    def call(*args):
        return fn(_device_params(obj, params_attr), *args)

    return call


def evict(obj: Any = None) -> None:
    """Drop cached programs and device weights — for ``obj``, or all.

    The caches are id-keyed and pin the model, its compiled programs, and a
    device-resident weight copy for process lifetime; long-lived processes
    that construct many towers should evict the ones they retire.
    """
    if obj is None:
        _CACHE.clear()
        _PARAMS_ON_DEVICE.clear()
        for fin in _FINALIZERS.values():
            fin.detach()
        _FINALIZERS.clear()
        return
    _evict_id(id(obj))
