# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Per-object cache of jitted forward functions for embedded towers.

Flax transformers models called eagerly dispatch thousands of individual XLA
ops — one host round-trip each on a remote TPU. Metrics that embed a neural
tower (BERTScore, InfoLM, CLIPScore, CLIP-IQA) route every model call through
here so the whole encoder runs as ONE compiled program per input shape.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax

_CACHE: Dict[Tuple[int, str], Callable] = {}
_PARAMS_ON_DEVICE: Dict[int, Tuple[Any, Any]] = {}  # id(obj) -> (source params, device copy)


def _device_params(obj: Any, params_attr: str) -> Any:
    """The model's params resident on the default device, transferred once.

    Towers are initialized on the host CPU backend (eager random init on a
    remote TPU costs one round-trip per op); without this cache every jit
    call would re-upload the full weight pytree (~0.4GB for bert-base) over
    the wire. Re-transfers only when the params attribute is rebound.
    """
    entry = _PARAMS_ON_DEVICE.get(id(obj))
    src = getattr(obj, params_attr)
    if entry is None or entry[0] is not src:
        entry = (src, jax.device_put(src))
        _PARAMS_ON_DEVICE[id(obj)] = entry
    return entry[1]


def jitted_forward(
    obj: Any,
    method: str,
    make_fn: Optional[Callable[[Any], Callable]] = None,
    params_attr: str = "params",
) -> Callable:
    """A jitted callable for ``obj.<method>``, compiled once per (object, tag).

    The model's weights enter the compiled program as jit ARGUMENTS, never as
    captured constants — baking ~100M floats into the HLO multiplies compile
    time several-fold (measured 140s → 18s for a 2-layer BERT on a remote
    TPU). The ``params_attr`` attribute (``.params`` for transformers models,
    ``.variables`` for Flax-module wrappers) is re-read on every call, so
    weight swaps are seen.

    ``make_fn(obj)`` can build a custom closure ``inner(params, *args)``
    instead (e.g. to select an output field) — ``method`` then only serves as
    the cache tag. Both paths close over ``obj``, pinning it so the id-based
    cache key can never be reused by a different object.
    """
    key = (id(obj), method)
    fn = _CACHE.get(key)
    if fn is None:
        if make_fn is not None:
            inner = make_fn(obj)
        else:
            bound = getattr(obj, method)

            def inner(params, *args):
                return bound(*args, params=params)

        fn = _CACHE[key] = jax.jit(inner)

    def call(*args):
        return fn(_device_params(obj, params_attr), *args)

    return call


def evict(obj: Any = None) -> None:
    """Drop cached programs and device weights — for ``obj``, or all.

    The caches are id-keyed and pin the model, its compiled programs, and a
    device-resident weight copy for process lifetime; long-lived processes
    that construct many towers should evict the ones they retire.
    """
    if obj is None:
        _CACHE.clear()
        _PARAMS_ON_DEVICE.clear()
        return
    for key in [k for k in _CACHE if k[0] == id(obj)]:
        del _CACHE[key]
    _PARAMS_ON_DEVICE.pop(id(obj), None)
