# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""User-facing exception types.

Capability parity with reference ``src/torchmetrics/utilities/exceptions.py``.
"""


class TorchMetricsUserError(Exception):
    """Error raised when a misuse of the metrics API is detected."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised for recoverable misuses of the metrics API."""
