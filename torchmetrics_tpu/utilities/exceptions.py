# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""User-facing exception types.

Capability parity with reference ``src/torchmetrics/utilities/exceptions.py``.
"""


class TorchMetricsUserError(Exception):
    """Error raised when a misuse of the metrics API is detected."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised for recoverable misuses of the metrics API."""


class StateRestoreError(TorchMetricsUserError):
    """A checkpoint / state tree failed validation against the metric's state registry.

    Raised by :meth:`Metric.load_state_tree` (strict mode) and
    :meth:`Metric.load_checkpoint` when a restored pytree carries unknown or
    missing states, a list-vs-array kind mismatch, an incompatible dtype or
    shape (e.g. a ``num_classes=5`` state restored into a ``num_classes=7``
    metric), or a truncated/corrupted checkpoint payload. The message always
    names the offending state and expected-vs-got so the failure is debuggable
    at restore time instead of detonating later inside jit.
    """


class SyncError(TorchMetricsUserError):
    """Multi-host state synchronization failed.

    Raised by :meth:`Metric.sync` when all attempts are exhausted (see
    :class:`~torchmetrics_tpu.robustness.SyncConfig`) and by the object-gather
    protocol in ``utilities/distributed.py`` when a payload arrives truncated
    or fails its CRC32 integrity check — naming the offending rank instead of
    surfacing an opaque ``pickle.loads`` failure.
    """


class SyncWarning(TorchMetricsUserWarning):
    """Warning raised when a sync failure degrades to local-only state
    (``SyncConfig(on_error="local")``)."""


class StallError(TorchMetricsUserError):
    """A watchdogged evaluation step exceeded its wall-clock deadline.

    Raised by :class:`~torchmetrics_tpu.robustness.StreamingEvaluator` when a
    metric ``update`` or final ``compute``/sync outlives
    ``watchdog_timeout_s`` (lost host, wedged collective, deadlocked input
    pipeline). With ``on_stall="snapshot_then_raise"`` the last-good state is
    persisted to the checkpoint store first, so a supervisor can kill the
    process and resume without losing completed batches.
    """


class CheckpointStoreWarning(TorchMetricsUserWarning):
    """Warning raised when ``CheckpointStore.latest()`` skips a torn, corrupt
    or otherwise invalid snapshot and falls back to an older valid one. The
    message names the snapshot step and what was wrong with it — recovery
    proceeds, but the operator should know batches may be replayed."""
