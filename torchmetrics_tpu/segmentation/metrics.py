# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Segmentation module metrics (reference ``src/torchmetrics/segmentation/*.py``)."""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.segmentation.generalized_dice import (
    _generalized_dice_compute,
    _generalized_dice_update,
    _generalized_dice_validate_args,
)
from torchmetrics_tpu.functional.segmentation.mean_iou import (
    _mean_iou_compute,
    _mean_iou_update,
    _mean_iou_validate_args,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class GeneralizedDiceScore(Metric):
    """Generalized dice score (reference ``segmentation/generalized_dice.py:33``).

    State: running sum of per-sample scores + sample count, ``"sum"`` reduce
    (reference ``:134-135``).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = True,
        per_class: bool = False,
        weight_type: str = "square",
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _generalized_dice_validate_args(num_classes, include_background, per_class, weight_type, input_format)
        self.num_classes = num_classes
        self.include_background = include_background
        self.per_class = per_class
        self.weight_type = weight_type
        self.input_format = input_format
        num_scores = num_classes - (0 if include_background else 1) if per_class else 1
        self.add_state("score", jnp.zeros(num_scores), dist_reduce_fx="sum")
        self.add_state("samples", jnp.zeros(1), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Fold per-sample generalized dice into the state (reference ``:137-143``)."""
        preds, target = jnp.asarray(preds), jnp.asarray(target)
        numerator, denominator = _generalized_dice_update(
            preds, target, self.num_classes, self.include_background, self.weight_type, self.input_format
        )
        self.score = self.score + _generalized_dice_compute(numerator, denominator, self.per_class).sum(axis=0)
        self.samples = self.samples + preds.shape[0]

    def compute(self) -> Array:
        """Mean over samples (reference ``:145-147``)."""
        return self.score / self.samples

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MeanIoU(Metric):
    """Mean IoU (reference ``segmentation/mean_iou.py:29``).

    State: running sum of per-batch mean IoU + batch count (reference
    ``:113-114``).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        include_background: bool = True,
        per_class: bool = False,
        input_format: str = "one-hot",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _mean_iou_validate_args(num_classes, include_background, per_class, input_format)
        self.num_classes = num_classes
        self.include_background = include_background
        self.per_class = per_class
        self.input_format = input_format
        num_scores = num_classes - (0 if include_background else 1) if per_class else 1
        self.add_state("score", jnp.zeros(num_scores), dist_reduce_fx="sum")
        self.add_state("num_batches", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Fold the batch mean IoU into the state (reference ``:116-123``)."""
        preds, target = jnp.asarray(preds), jnp.asarray(target)
        intersection, union = _mean_iou_update(
            preds, target, self.num_classes, self.include_background, self.input_format
        )
        score = _mean_iou_compute(intersection, union, per_class=self.per_class)
        self.score = self.score + (score.mean(axis=0) if self.per_class else score.mean())
        self.num_batches = self.num_batches + 1

    def compute(self) -> Array:
        """Mean over batches (reference ``:125-127``)."""
        return self.score / self.num_batches

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)
