# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Segmentation module metrics (reference ``src/torchmetrics/segmentation/``)."""
from torchmetrics_tpu.segmentation.metrics import GeneralizedDiceScore, MeanIoU

__all__ = ["GeneralizedDiceScore", "MeanIoU"]
