# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Retrieval module metrics (reference ``src/torchmetrics/retrieval/*.py``)."""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.retrieval.metrics import (
    _auroc_kernel,
    _average_precision_kernel,
    _fall_out_kernel,
    _hit_rate_kernel,
    _ndcg_kernel,
    _precision_kernel,
    _precision_recall_curve_kernel,
    _r_precision_kernel,
    _recall_kernel,
    _reciprocal_rank_kernel,
    _validate_top_k,
)
from torchmetrics_tpu.retrieval.base import RetrievalMetric, _pack_queries, _retrieval_aggregate
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision (reference ``retrieval/average_precision.py:30``)."""

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _average_precision_kernel(preds, target, valid, self.top_k)


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank (reference ``retrieval/reciprocal_rank.py:30``)."""

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _reciprocal_rank_kernel(preds, target, valid, self.top_k)


class RetrievalPrecision(RetrievalMetric):
    """Precision@k (reference ``retrieval/precision.py:30``)."""

    def __init__(self, top_k: Optional[int] = None, adaptive_k: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.top_k = top_k
        self.adaptive_k = adaptive_k

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _precision_kernel(preds, target, valid, self.top_k, self.adaptive_k)


class RetrievalRecall(RetrievalMetric):
    """Recall@k (reference ``retrieval/recall.py:30``)."""

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _recall_kernel(preds, target, valid, self.top_k)


class RetrievalHitRate(RetrievalMetric):
    """HitRate@k (reference ``retrieval/hit_rate.py:30``)."""

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _hit_rate_kernel(preds, target, valid, self.top_k)


class RetrievalFallOut(RetrievalMetric):
    """Fall-out@k (reference ``retrieval/fall_out.py:30``); empty-target
    policy applies to queries with no NEGATIVE targets (reference ``:116-139``)."""

    higher_is_better = False

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _fall_out_kernel(preds, target, valid, self.top_k)

    def compute(self) -> Array:
        """Same as base but keyed on queries with no negative target."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        preds_grid, target_grid, valid_grid = _pack_queries(indexes, preds, target)
        values = jax.vmap(self._metric_row)(preds_grid, target_grid, valid_grid)
        has_neg = ((target_grid == 0) & valid_grid).sum(axis=1) > 0
        values = self._apply_empty_action(values, has_neg, missing="negative")
        if values.size == 0:
            return jnp.asarray(0.0)
        return _retrieval_aggregate(values, self.aggregation)


class RetrievalRPrecision(RetrievalMetric):
    """R-precision (reference ``retrieval/r_precision.py:30``)."""

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _r_precision_kernel(preds, target, valid)


class RetrievalNormalizedDCG(RetrievalMetric):
    """Normalized DCG (reference ``retrieval/ndcg.py:30``); allows graded
    relevance targets."""

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        self.top_k = top_k
        self.allow_non_binary_target = True

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _ndcg_kernel(preds, target, valid, self.top_k)


class RetrievalAUROC(RetrievalMetric):
    """Mean AUROC over queries (reference ``retrieval/auroc.py:30``)."""

    def __init__(self, top_k: Optional[int] = None, max_fpr: Optional[float] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if top_k is not None:
            _validate_top_k(top_k)
        if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        self.top_k = top_k
        self.max_fpr = max_fpr

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        return _auroc_kernel(preds, target, valid, self.top_k)

    def compute(self) -> Array:
        if self.max_fpr is None:
            return super().compute()
        # partial-AUC path: per-query host loop on the exact binary curve
        from torchmetrics_tpu.functional.retrieval.metrics import retrieval_auroc

        indexes = np.asarray(dim_zero_cat(self.indexes))
        preds = np.asarray(dim_zero_cat(self.preds))
        target = np.asarray(dim_zero_cat(self.target))
        values, has_pos = [], []
        for q in np.unique(indexes):
            m = indexes == q
            has_pos.append(bool(target[m].sum() > 0))
            values.append(float(retrieval_auroc(jnp.asarray(preds[m]), jnp.asarray(target[m]), self.top_k, self.max_fpr)))
        values = self._apply_empty_action(jnp.asarray(values), jnp.asarray(has_pos))
        if values.size == 0:
            return jnp.asarray(0.0)
        return _retrieval_aggregate(values, self.aggregation)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged per-k precision/recall curves (reference
    ``retrieval/precision_recall_curve.py:45``)."""

    def __init__(self, max_k: Optional[int] = None, adaptive_k: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if (max_k is not None) and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:  # pragma: no cover - unused
        raise NotImplementedError

    def compute(self) -> Tuple[Array, Array, Array]:
        """Mean per-k curves over queries (reference ``:169-201``)."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        preds_grid, target_grid, valid_grid = _pack_queries(indexes, preds, target)
        lmax = preds_grid.shape[1]
        max_k = self.max_k or lmax

        prec, rec, topk = jax.vmap(
            lambda p, t, v: _precision_recall_curve_kernel(p, t, v, max_k, self.adaptive_k)
        )(preds_grid, target_grid, valid_grid)
        has_pos = ((target_grid > 0) & valid_grid).sum(axis=1) > 0
        prec = self._apply_empty_action(prec, has_pos)
        rec = self._apply_empty_action(rec, has_pos)
        precision = _retrieval_aggregate(prec, self.aggregation, dim=0) if prec.size else jnp.zeros(max_k)
        recall = _retrieval_aggregate(rec, self.aggregation, dim=0) if rec.size else jnp.zeros(max_k)
        return precision, recall, jnp.arange(1, max_k + 1)


def _retrieval_recall_at_fixed_precision(
    precision: Array, recall: Array, top_k: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Max recall whose precision >= min_precision (reference
    ``retrieval/precision_recall_curve.py:26-42``)."""
    p, r, k = np.asarray(precision), np.asarray(recall), np.asarray(top_k)
    valid = p >= min_precision
    if valid.any():
        cand = [(rr, kk) for pp, rr, kk in zip(p, r, k) if pp >= min_precision]
        max_recall, best_k = max(cand)
    else:
        max_recall, best_k = 0.0, len(k)
    if max_recall == 0.0:
        best_k = len(k)
    return jnp.asarray(max_recall, jnp.float32), jnp.asarray(best_k)


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Highest recall@k at a minimum precision (reference
    ``retrieval/precision_recall_curve.py:204``)."""

    def __init__(self, min_precision: float = 0.0, max_k: Optional[int] = None, adaptive_k: bool = False, **kwargs: Any) -> None:
        super().__init__(max_k=max_k, adaptive_k=adaptive_k, **kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        precision, recall, top_k = super().compute()
        return _retrieval_recall_at_fixed_precision(precision, recall, top_k, self.min_precision)
