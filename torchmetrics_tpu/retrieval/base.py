# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""RetrievalMetric base (reference ``src/torchmetrics/retrieval/base.py``).

TPU-native compute: instead of sorting + splitting + a Python loop over
queries (reference ``base.py:147-182``), queries are packed into a dense
``(Q, Lmax)`` grid (row = query, columns = its documents, padded slots
masked) and the per-query kernel is ``vmap``-ed over rows — one fused XLA
program for the whole compute.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.checks import _check_retrieval_inputs
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


def _retrieval_aggregate(values: Array, aggregation: Union[str, Callable] = "mean", dim: Optional[int] = None) -> Array:
    """Aggregate per-query values (reference ``base.py:26-40``)."""
    if aggregation == "mean":
        return values.mean() if dim is None else values.mean(axis=dim)
    if aggregation == "median":
        # torch.median returns the LOWER of the two middle elements on even
        # counts (the reference's semantics, ``base.py:33``); jnp.median
        # would average them. No dim = flatten, like torch.median().
        v, axis = (values.ravel(), 0) if dim is None else (values, dim)
        k = max((v.shape[axis] - 1) // 2, 0)
        return jnp.sort(v, axis=axis).take(k, axis=axis)
    if aggregation == "min":
        return values.min() if dim is None else values.min(axis=dim)
    if aggregation == "max":
        return values.max() if dim is None else values.max(axis=dim)
    return aggregation(values, dim=dim)


def _pack_queries(indexes: Array, preds: Array, target: Array) -> Tuple[Array, Array, Array]:
    """Pack the flat (index, pred, target) stream into a dense (Q, Lmax) grid.

    Padded slots carry ``valid=False``, ``preds=-inf``, ``target=0`` — the
    contract of the masked row kernels in ``functional/retrieval/metrics.py``.
    """
    idx = np.asarray(indexes)
    order = np.argsort(idx, kind="stable")
    idx_sorted = idx[order]
    # row id per element + position within its query
    uniq, row = np.unique(idx_sorted, return_inverse=True)
    counts = np.bincount(row)
    q, lmax = len(uniq), int(counts.max()) if len(counts) else 0
    col = np.arange(len(idx_sorted)) - np.concatenate([[0], np.cumsum(counts)[:-1]])[row]

    preds_grid = np.full((q, lmax), -np.inf, dtype=np.float32)
    target_grid = np.zeros((q, lmax), dtype=np.float32)
    valid_grid = np.zeros((q, lmax), dtype=bool)
    preds_np = np.asarray(preds)[order]
    target_np = np.asarray(target)[order]
    preds_grid[row, col] = preds_np
    target_grid[row, col] = target_np
    valid_grid[row, col] = True
    return jnp.asarray(preds_grid), jnp.asarray(target_grid), jnp.asarray(valid_grid)


class RetrievalMetric(Metric, ABC):
    """Base for retrieval metrics (reference ``base.py:43``).

    States: ``indexes``/``preds``/``target`` lists with gather-no-reduce
    (reference ``:130-132``). ``compute`` groups by query and evaluates the
    vmapped row kernel.
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation
        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Append flattened (indexes, preds, target) (reference ``:134-145``)."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            jnp.asarray(indexes),
            jnp.asarray(preds),
            jnp.asarray(target),
            allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def _apply_empty_action(self, values: Array, mask: Array, missing: str = "positive") -> Array:
        """Apply the empty-target policy to per-query values (reference ``:160-171``).

        ``mask`` is True for queries that have the required target kind;
        ``values`` may be ``(Q,)`` or ``(Q, K)`` (curve metrics).
        """
        if self.empty_target_action == "error" and bool((~mask).any()):
            raise ValueError(f"`compute` method was provided with a query with no {missing} target.")
        m = mask if values.ndim == 1 else mask[:, None]
        if self.empty_target_action == "pos":
            return jnp.where(m, values, 1.0)
        if self.empty_target_action == "neg":
            return jnp.where(m, values, 0.0)
        if self.empty_target_action == "skip":
            return values[jnp.asarray(np.asarray(mask))]
        return values

    def compute(self) -> Array:
        """Group by query and evaluate the vmapped kernel (reference ``:147-182``)."""
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        preds_grid, target_grid, valid_grid = _pack_queries(indexes, preds, target)

        values = jax.vmap(self._metric_row)(preds_grid, target_grid, valid_grid)  # (Q,)
        has_pos = ((target_grid > 0) & valid_grid).sum(axis=1) > 0
        values = self._apply_empty_action(values, has_pos)
        if values.size == 0:
            return jnp.asarray(0.0)
        return _retrieval_aggregate(values, self.aggregation)

    @abstractmethod
    def _metric_row(self, preds: Array, target: Array, valid: Array) -> Array:
        """Single-query masked-row kernel; vmapped over queries."""

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)
