# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Retrieval module metrics (reference ``src/torchmetrics/retrieval/``)."""
from torchmetrics_tpu.retrieval.base import RetrievalMetric
from torchmetrics_tpu.retrieval.metrics import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)

__all__ = [
    "RetrievalMetric",
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
]
