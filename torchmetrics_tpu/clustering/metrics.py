# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Clustering module metrics (reference ``src/torchmetrics/clustering/*.py``).

Two state machines:

- extrinsic (label-vs-label) metrics keep ``preds``/``target`` as ``cat``
  list states and evaluate the functional kernel on the concatenated stream
  at ``compute`` (cluster ids are arbitrary, so per-batch contingency
  matrices cannot be merged);
- intrinsic (data-vs-label) metrics keep ``data``/``labels`` the same way.
"""
from __future__ import annotations

from typing import Any

import jax

from torchmetrics_tpu.functional.clustering import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    calinski_harabasz_score,
    completeness_score,
    davies_bouldin_score,
    dunn_index,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from torchmetrics_tpu.functional.clustering.utils import _validate_average_method_arg
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class _LabelPairClusteringMetric(Metric):
    """Shared cat-state machine for extrinsic clustering metrics
    (e.g. reference ``clustering/mutual_info_score.py:30``)."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append predicted and target cluster labels."""
        import jax.numpy as jnp

        self.preds.append(jnp.asarray(preds))
        self.target.append(jnp.asarray(target))

    def _compute(self, fn, *args: Any) -> Array:
        return fn(dim_zero_cat(self.preds), dim_zero_cat(self.target), *args)

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class MutualInfoScore(_LabelPairClusteringMetric):
    """Mutual information score (reference ``clustering/mutual_info_score.py:30``)."""

    def compute(self) -> Array:
        return self._compute(mutual_info_score)


class AdjustedMutualInfoScore(_LabelPairClusteringMetric):
    """Adjusted mutual info score (reference ``clustering/adjusted_mutual_info_score.py:31``)."""

    plot_lower_bound = -1.0

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_average_method_arg(average_method)
        self.average_method = average_method

    def compute(self) -> Array:
        return self._compute(adjusted_mutual_info_score, self.average_method)


class NormalizedMutualInfoScore(_LabelPairClusteringMetric):
    """Normalized mutual info score (reference ``clustering/normalized_mutual_info_score.py:31``)."""

    def __init__(self, average_method: str = "arithmetic", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_average_method_arg(average_method)
        self.average_method = average_method

    def compute(self) -> Array:
        return self._compute(normalized_mutual_info_score, self.average_method)


class RandScore(_LabelPairClusteringMetric):
    """Rand score (reference ``clustering/rand_score.py:30``)."""

    def compute(self) -> Array:
        return self._compute(rand_score)


class AdjustedRandScore(_LabelPairClusteringMetric):
    """Adjusted Rand score (reference ``clustering/adjusted_rand_score.py:30``)."""

    plot_lower_bound = -0.5

    def compute(self) -> Array:
        return self._compute(adjusted_rand_score)


class FowlkesMallowsIndex(_LabelPairClusteringMetric):
    """Fowlkes-Mallows index (reference ``clustering/fowlkes_mallows_index.py:30``)."""

    def compute(self) -> Array:
        return self._compute(fowlkes_mallows_index)


class HomogeneityScore(_LabelPairClusteringMetric):
    """Homogeneity score (reference ``clustering/homogeneity_completeness_v_measure.py:31``)."""

    def compute(self) -> Array:
        return self._compute(homogeneity_score)


class CompletenessScore(_LabelPairClusteringMetric):
    """Completeness score (reference ``clustering/homogeneity_completeness_v_measure.py:113``)."""

    def compute(self) -> Array:
        return self._compute(completeness_score)


class VMeasureScore(_LabelPairClusteringMetric):
    """V-measure score (reference ``clustering/homogeneity_completeness_v_measure.py:195``)."""

    def __init__(self, beta: float = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(beta, float) and beta > 0):
            raise ValueError(f"Argument `beta` should be a positive float. Got {beta}.")
        self.beta = beta

    def compute(self) -> Array:
        return self._compute(v_measure_score, self.beta)


class _IntrinsicClusteringMetric(Metric):
    """Shared cat-state machine for intrinsic (embedded-data) metrics
    (e.g. reference ``clustering/calinski_harabasz_score.py:30``)."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("data", [], dist_reduce_fx="cat")
        self.add_state("labels", [], dist_reduce_fx="cat")

    def update(self, data: Array, labels: Array) -> None:
        """Append embedded data and their cluster labels."""
        import jax.numpy as jnp

        self.data.append(jnp.asarray(data))
        self.labels.append(jnp.asarray(labels))

    def plot(self, val=None, ax=None):
        return self._plot(val, ax)


class CalinskiHarabaszScore(_IntrinsicClusteringMetric):
    """Calinski-Harabasz score (reference ``clustering/calinski_harabasz_score.py:30``)."""

    def compute(self) -> Array:
        return calinski_harabasz_score(dim_zero_cat(self.data), dim_zero_cat(self.labels))


class DaviesBouldinScore(_IntrinsicClusteringMetric):
    """Davies-Bouldin score (reference ``clustering/davies_bouldin_score.py:30``)."""

    higher_is_better = False

    def compute(self) -> Array:
        return davies_bouldin_score(dim_zero_cat(self.data), dim_zero_cat(self.labels))


class DunnIndex(_IntrinsicClusteringMetric):
    """Dunn index (reference ``clustering/dunn_index.py:29``)."""

    def __init__(self, p: float = 2, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.p = p

    def compute(self) -> Array:
        return dunn_index(dim_zero_cat(self.data), dim_zero_cat(self.labels), self.p)
