# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Clustering module metrics (reference ``src/torchmetrics/clustering/``)."""
from torchmetrics_tpu.clustering.metrics import (
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    DunnIndex,
    FowlkesMallowsIndex,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)

__all__ = [
    "AdjustedMutualInfoScore",
    "AdjustedRandScore",
    "CalinskiHarabaszScore",
    "CompletenessScore",
    "DaviesBouldinScore",
    "DunnIndex",
    "FowlkesMallowsIndex",
    "HomogeneityScore",
    "MutualInfoScore",
    "NormalizedMutualInfoScore",
    "RandScore",
    "VMeasureScore",
]
