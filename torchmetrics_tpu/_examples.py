# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Runnable usage examples attached to metric class docstrings.

The reference embeds a doctest example in every metric docstring and enforces
them via ``--doctest-plus`` (reference ``Makefile:28-31``). Here the examples
for non-factory classes live in ONE table and are appended to each class's
docstring at import time; ``tests/unittests/test_doctests.py`` walks every
module and executes whatever ``>>>`` blocks it finds, so each entry below is
a continuously-verified usage contract (values are analytic where possible:
perfect predictions, constant offsets, exact ranks).
"""
from __future__ import annotations

_EXAMPLES = {
    # --------------------------------------------------------- classification
    "classification.f_beta.MulticlassF1Score": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import MulticlassF1Score
    >>> metric = MulticlassF1Score(num_classes=3, average='macro')
    >>> metric.update(np.array([0, 1, 2, 1]), np.array([0, 1, 2, 1]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "classification.f_beta.BinaryFBetaScore": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryFBetaScore
    >>> metric = BinaryFBetaScore(beta=2.0)
    >>> metric.update(np.array([0.2, 0.8, 0.9]), np.array([0, 1, 1]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "classification.auroc.BinaryAUROC": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryAUROC
    >>> metric = BinaryAUROC()
    >>> metric.update(np.array([0.1, 0.4, 0.35, 0.8]), np.array([0, 0, 1, 1]))
    >>> round(float(metric.compute()), 4)
    0.75
    """,
    "classification.confusion_matrix.MulticlassConfusionMatrix": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import MulticlassConfusionMatrix
    >>> metric = MulticlassConfusionMatrix(num_classes=2)
    >>> metric.update(np.array([0, 1, 1]), np.array([0, 1, 0]))
    >>> np.asarray(metric.compute()).tolist()
    [[1, 1], [0, 1]]
    """,
    "classification.matthews_corrcoef.BinaryMatthewsCorrCoef": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryMatthewsCorrCoef
    >>> metric = BinaryMatthewsCorrCoef()
    >>> metric.update(np.array([0, 1, 1, 0]), np.array([0, 1, 1, 0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "classification.cohen_kappa.BinaryCohenKappa": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryCohenKappa
    >>> metric = BinaryCohenKappa()
    >>> metric.update(np.array([0, 1, 1, 0]), np.array([0, 1, 1, 0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "classification.jaccard.MulticlassJaccardIndex": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import MulticlassJaccardIndex
    >>> metric = MulticlassJaccardIndex(num_classes=3)
    >>> metric.update(np.array([0, 1, 2, 1]), np.array([0, 1, 2, 1]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    # -------------------------------------------------------------- regression
    "regression.mse.MeanSquaredError": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MeanSquaredError
    >>> metric = MeanSquaredError()
    >>> metric.update(np.array([2.5, 0.0, 2.0, 8.0]), np.array([3.0, -0.5, 2.0, 7.0]))
    >>> round(float(metric.compute()), 4)
    0.375
    """,
    "regression.mae.MeanAbsoluteError": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MeanAbsoluteError
    >>> metric = MeanAbsoluteError()
    >>> metric.update(np.array([2.5, 0.0, 2.0, 8.0]), np.array([3.0, -0.5, 2.0, 7.0]))
    >>> round(float(metric.compute()), 4)
    0.5
    """,
    "regression.pearson.PearsonCorrCoef": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import PearsonCorrCoef
    >>> metric = PearsonCorrCoef()
    >>> metric.update(np.array([1.0, 2.0, 3.0, 4.0]), np.array([2.0, 4.0, 6.0, 8.0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "regression.r2.R2Score": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import R2Score
    >>> metric = R2Score()
    >>> metric.update(np.array([1.0, 2.0, 3.0]), np.array([1.0, 2.0, 3.0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "regression.spearman.SpearmanCorrCoef": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import SpearmanCorrCoef
    >>> metric = SpearmanCorrCoef()
    >>> metric.update(np.array([1.0, 2.0, 3.0, 4.0]), np.array([10.0, 20.0, 30.0, 40.0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    # ------------------------------------------------------------- aggregation
    "aggregation.MeanMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MeanMetric
    >>> metric = MeanMetric()
    >>> metric.update(np.array([1.0, 2.0, 3.0]))
    >>> round(float(metric.compute()), 4)
    2.0
    """,
    "aggregation.SumMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import SumMetric
    >>> metric = SumMetric()
    >>> metric.update(np.array([1.0, 2.0, 3.0]))
    >>> round(float(metric.compute()), 4)
    6.0
    """,
    "aggregation.MaxMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MaxMetric
    >>> metric = MaxMetric()
    >>> metric.update(np.array([1.0, 3.0, 2.0]))
    >>> round(float(metric.compute()), 4)
    3.0
    """,
    "aggregation.MinMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MinMetric
    >>> metric = MinMetric()
    >>> metric.update(np.array([1.0, 3.0, 2.0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "aggregation.CatMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import CatMetric
    >>> metric = CatMetric()
    >>> metric.update(np.array([1.0, 2.0]))
    >>> metric.update(np.array([3.0]))
    >>> np.asarray(metric.compute()).tolist()
    [1.0, 2.0, 3.0]
    """,
    # below the sketch capacity the KLL state is exact: the q-quantile is the
    # ceil(q*n)-th order statistic, so these pins are analytic
    "aggregation.Quantile": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import Quantile
    >>> metric = Quantile(q=[0.25, 0.75])
    >>> metric.update(np.array([1.0, 4.0, 2.0, 3.0]))
    >>> np.asarray(metric.compute()).tolist()
    [1.0, 3.0]
    """,
    "aggregation.Median": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import Median
    >>> metric = Median()
    >>> metric.update(np.array([7.0, 1.0, 3.0]))
    >>> round(float(metric.compute()), 4)
    3.0
    """,
    # -------------------------------------------------------------------- text
    "text.metrics.WordErrorRate": """
    >>> from torchmetrics_tpu import WordErrorRate
    >>> metric = WordErrorRate()
    >>> metric.update(["the cat sat"], ["the cat sat down"])
    >>> round(float(metric.compute()), 4)
    0.25
    """,
    "text.metrics.CharErrorRate": """
    >>> from torchmetrics_tpu import CharErrorRate
    >>> metric = CharErrorRate()
    >>> metric.update(["abc"], ["abcd"])
    >>> round(float(metric.compute()), 4)
    0.25
    """,
    "text.metrics.BLEUScore": """
    >>> from torchmetrics_tpu import BLEUScore
    >>> metric = BLEUScore()
    >>> metric.update(["the cat is on the mat"], [["the cat sat on the mat", "a cat is on the mat"]])
    >>> round(float(metric.compute()), 4)
    0.8409
    """,
    "text.metrics.EditDistance": """
    >>> from torchmetrics_tpu import EditDistance
    >>> metric = EditDistance(reduction='mean')
    >>> metric.update(["abc"], ["abcd"])
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    # ------------------------------------------------------------------- image
    "image.metrics.PeakSignalNoiseRatio": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import PeakSignalNoiseRatio
    >>> metric = PeakSignalNoiseRatio(data_range=1.0)
    >>> metric.update(np.full((1, 1, 8, 8), 0.5), np.full((1, 1, 8, 8), 0.75))
    >>> round(float(metric.compute()), 4)
    12.0412
    """,
    "image.metrics.TotalVariation": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import TotalVariation
    >>> metric = TotalVariation()
    >>> metric.update(np.ones((1, 1, 8, 8), np.float32))
    >>> round(float(metric.compute()), 4)
    0.0
    """,
    "image.metrics.StructuralSimilarityIndexMeasure": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import StructuralSimilarityIndexMeasure
    >>> rng = np.random.RandomState(0)
    >>> img = rng.rand(1, 1, 16, 16).astype(np.float32)
    >>> metric = StructuralSimilarityIndexMeasure(data_range=1.0)
    >>> metric.update(img, img)
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    # ------------------------------------------------------------------- audio
    "audio.metrics.SignalNoiseRatio": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import SignalNoiseRatio
    >>> metric = SignalNoiseRatio()
    >>> target = np.ones(4, np.float32)
    >>> metric.update(target + 1.0, target)  # noise power == signal power
    >>> round(float(metric.compute()), 4)
    0.0
    """,
    "audio.metrics.ScaleInvariantSignalDistortionRatio": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import ScaleInvariantSignalDistortionRatio
    >>> metric = ScaleInvariantSignalDistortionRatio()
    >>> target = np.array([1.0, -1.0, 1.0, -1.0])
    >>> metric.update(2.0 * target, target)  # scaling leaves SI-SDR unchanged
    >>> float(metric.compute()) > 30
    True
    """,
    # --------------------------------------------------------------- retrieval
    "retrieval.metrics.RetrievalMAP": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import RetrievalMAP
    >>> metric = RetrievalMAP()
    >>> metric.update(np.array([0.9, 0.2, 0.7]), np.array([1, 0, 1]), indexes=np.array([0, 0, 0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "retrieval.metrics.RetrievalNormalizedDCG": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import RetrievalNormalizedDCG
    >>> metric = RetrievalNormalizedDCG()
    >>> metric.update(np.array([0.9, 0.2, 0.7]), np.array([1, 0, 1]), indexes=np.array([0, 0, 0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    # -------------------------------------------------------------- clustering
    "clustering.metrics.MutualInfoScore": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MutualInfoScore
    >>> metric = MutualInfoScore()
    >>> metric.update(np.array([0, 1, 0, 1]), np.array([0, 1, 0, 1]))
    >>> round(float(metric.compute()), 4)
    0.6931
    """,
    "clustering.metrics.AdjustedRandScore": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import AdjustedRandScore
    >>> metric = AdjustedRandScore()
    >>> metric.update(np.array([0, 0, 1, 1]), np.array([1, 1, 0, 0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    # ------------------------------------------------------------ segmentation
    "segmentation.metrics.MeanIoU": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MeanIoU
    >>> metric = MeanIoU(num_classes=2, input_format='index')
    >>> seg = np.array([[[0, 1], [1, 0]]])
    >>> metric.update(seg, seg)
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "classification.exact_match.MulticlassExactMatch": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import MulticlassExactMatch
    >>> metric = MulticlassExactMatch(num_classes=3)
    >>> metric.update(np.array([[0, 1], [2, 1]]), np.array([[0, 1], [2, 1]]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "regression.explained_variance.ExplainedVariance": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import ExplainedVariance
    >>> metric = ExplainedVariance()
    >>> metric.update(np.array([1.0, 2.0, 3.0]), np.array([1.0, 2.0, 3.0]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "regression.cosine_similarity.CosineSimilarity": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import CosineSimilarity
    >>> metric = CosineSimilarity(reduction='mean')
    >>> v = np.array([[1.0, 2.0, 3.0]])
    >>> metric.update(2.0 * v, v)  # cosine ignores magnitude
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "regression.mape.MeanAbsolutePercentageError": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MeanAbsolutePercentageError
    >>> metric = MeanAbsolutePercentageError()
    >>> metric.update(np.array([1.0, 2.0, 4.0]), np.array([1.0, 2.0, 4.0]))
    >>> round(float(metric.compute()), 4)
    0.0
    """,
    "image.metrics.UniversalImageQualityIndex": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import UniversalImageQualityIndex
    >>> rng = np.random.RandomState(0)
    >>> img = rng.rand(1, 1, 16, 16).astype(np.float32)
    >>> metric = UniversalImageQualityIndex()
    >>> metric.update(img, img)
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "classification.f_beta.BinaryF1Score": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryF1Score
    >>> metric = BinaryF1Score()
    >>> metric.update(np.array([0.2, 0.8, 0.7, 0.3]), np.array([0, 1, 1, 1]))
    >>> round(float(metric.compute()), 4)
    0.8
    """,
    "classification.jaccard.BinaryJaccardIndex": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryJaccardIndex
    >>> metric = BinaryJaccardIndex()
    >>> metric.update(np.array([0.2, 0.8, 0.7, 0.3]), np.array([0, 1, 1, 1]))
    >>> round(float(metric.compute()), 4)
    0.6667
    """,
    "classification.stat_scores.BinaryStatScores": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryStatScores
    >>> metric = BinaryStatScores()
    >>> metric.update(np.array([0.2, 0.8, 0.7, 0.3]), np.array([0, 1, 1, 1]))
    >>> np.asarray(metric.compute()).tolist()  # [tp, fp, tn, fn, support]
    [2, 0, 1, 1, 3]
    """,
    "classification.stat_scores.MulticlassStatScores": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import MulticlassStatScores
    >>> metric = MulticlassStatScores(num_classes=3, average=None)
    >>> metric.update(np.array([0, 1, 2, 1]), np.array([0, 1, 2, 2]))
    >>> np.asarray(metric.compute()).tolist()
    [[1, 0, 3, 0, 1], [1, 1, 2, 0, 1], [1, 0, 2, 1, 2]]
    """,
    "detection.mean_ap.MeanAveragePrecision": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.detection import MeanAveragePrecision
    >>> metric = MeanAveragePrecision()
    >>> metric.update(
    ...     [{"boxes": np.array([[0.0, 0.0, 10.0, 10.0]]), "scores": np.array([0.9]), "labels": np.array([0])}],
    ...     [{"boxes": np.array([[0.0, 0.0, 10.0, 10.0]]), "labels": np.array([0])}],
    ... )
    >>> round(float(metric.compute()["map"]), 4)
    1.0
    """,
    "wrappers.minmax.MinMaxMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MinMaxMetric, MeanSquaredError
    >>> metric = MinMaxMetric(MeanSquaredError())
    >>> metric.update(np.array([1.0, 2.0]), np.array([1.0, 3.0]))
    >>> {k: round(float(v), 4) for k, v in sorted(metric.compute().items())}
    {'max': 0.5, 'min': 0.5, 'raw': 0.5}
    """,
    "wrappers.multioutput.MultioutputWrapper": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MultioutputWrapper, MeanSquaredError
    >>> metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
    >>> metric.update(np.array([[1.0, 2.0], [2.0, 4.0]]), np.array([[1.0, 3.0], [2.0, 3.0]]))
    >>> [round(float(v), 4) for v in np.asarray(metric.compute()).ravel()]
    [0.0, 1.0]
    """,
    "wrappers.classwise.ClasswiseWrapper": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import ClasswiseWrapper
    >>> from torchmetrics_tpu.classification import MulticlassAccuracy
    >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=2, average=None))
    >>> metric.update(np.array([0, 1, 1]), np.array([0, 1, 0]))
    >>> {k: round(float(v), 4) for k, v in sorted(metric.compute().items())}
    {'multiclassaccuracy_0': 0.5, 'multiclassaccuracy_1': 1.0}
    """,
    "wrappers.multitask.MultitaskWrapper": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MultitaskWrapper, MeanSquaredError, MeanAbsoluteError
    >>> metric = MultitaskWrapper({"mse": MeanSquaredError(), "mae": MeanAbsoluteError()})
    >>> metric.update(
    ...     {"mse": np.array([1.0, 2.0]), "mae": np.array([1.0, 2.0])},
    ...     {"mse": np.array([1.0, 4.0]), "mae": np.array([1.0, 4.0])},
    ... )
    >>> {k: round(float(v), 4) for k, v in sorted(metric.compute().items())}
    {'mae': 1.0, 'mse': 2.0}
    """,
    "audio.metrics.PermutationInvariantTraining": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.audio import PermutationInvariantTraining
    >>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio
    >>> rng = np.random.RandomState(42)
    >>> metric = PermutationInvariantTraining(scale_invariant_signal_noise_ratio)
    >>> metric.update(rng.randn(2, 2, 64).astype(np.float32), rng.randn(2, 2, 64).astype(np.float32))
    >>> round(float(metric.compute()), 4)
    -14.4344
    """,
    # ------------------------------------- bases (subclassing contracts)
    "metric.Metric": """
    >>> import numpy as np
    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu import Metric
    >>> class CountPositives(Metric):
    ...     def __init__(self, **kwargs):
    ...         super().__init__(**kwargs)
    ...         self.add_state("count", default=jnp.asarray(0), dist_reduce_fx="sum")
    ...     def update(self, values):
    ...         self.count = self.count + (jnp.asarray(values) > 0).sum()
    ...     def compute(self):
    ...         return self.count
    >>> metric = CountPositives()
    >>> metric.update(np.array([1.0, -2.0, 3.0]))
    >>> metric.update(np.array([4.0, -5.0]))
    >>> int(metric.compute())
    3
    """,
    "metric.CompositionalMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MeanSquaredError
    >>> metric = MeanSquaredError() * 2  # arithmetic on metrics builds a CompositionalMetric
    >>> metric.update(np.array([2.5, 0.0, 2.0, 8.0]), np.array([3.0, -0.5, 2.0, 7.0]))
    >>> round(float(metric.compute()), 4)
    0.75
    """,
    "retrieval.base.RetrievalMetric": """
    >>> import numpy as np
    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.retrieval import RetrievalMetric
    >>> class RetrievalFirstRelevant(RetrievalMetric):  # rank of first relevant doc
    ...     def _metric_row(self, preds, target, valid):
    ...         # masked-row kernel, vmapped over the padded query grid
    ...         key = jnp.where(valid, preds, -jnp.inf)
    ...         order = jnp.argsort(-key)
    ...         hit = (target[order] > 0) & valid[order]
    ...         return jnp.argmax(hit).astype(jnp.float32) + 1.0
    >>> metric = RetrievalFirstRelevant()
    >>> metric.update(np.array([0.9, 0.2, 0.8]), np.array([0, 0, 1]), indexes=np.array([0, 0, 0]))
    >>> round(float(metric.compute()), 4)
    2.0
    """,
    # ----------------------------------------------------------- wrappers
    "wrappers.abstract.WrapperMetric": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.wrappers import WrapperMetric
    >>> from torchmetrics_tpu import MeanSquaredError
    >>> class NegatedMetric(WrapperMetric):  # wraps any metric, negates compute()
    ...     def __init__(self, base, **kwargs):
    ...         super().__init__(**kwargs)
    ...         self.base = base
    ...     def update(self, *args, **kwargs):
    ...         self.base.update(*args, **kwargs)
    ...     def compute(self):
    ...         return -self.base.compute()
    >>> metric = NegatedMetric(MeanSquaredError())
    >>> metric.update(np.array([2.5, 0.0, 2.0, 8.0]), np.array([3.0, -0.5, 2.0, 7.0]))
    >>> round(float(metric.compute()), 4)
    -0.375
    """,
    "wrappers.bootstrapping.BootStrapper": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.wrappers import BootStrapper
    >>> from torchmetrics_tpu import MeanSquaredError
    >>> metric = BootStrapper(MeanSquaredError(), num_bootstraps=4, seed=7)
    >>> metric.update(np.array([2.5, 0.0, 2.0, 8.0], np.float32), np.array([3.0, -0.5, 2.0, 7.0], np.float32))
    >>> sorted(metric.compute())
    ['mean', 'std']
    """,
    "wrappers.running.Running": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.wrappers import Running
    >>> from torchmetrics_tpu import MeanMetric
    >>> metric = Running(MeanMetric(), window=2)
    >>> for v in (1.0, 2.0, 3.0):
    ...     metric.update(np.array([v], np.float32))
    >>> round(float(metric.compute()), 4)  # mean of the last 2 updates
    2.5
    """,
    "wrappers.transformations.BinaryTargetTransformer": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.wrappers import BinaryTargetTransformer
    >>> from torchmetrics_tpu.classification import BinaryAccuracy
    >>> metric = BinaryTargetTransformer(BinaryAccuracy(), threshold=2)
    >>> metric.update(np.array([1, 0, 1, 1]), np.array([0.0, 1.0, 4.0, 3.0]))  # targets binarize at > 2
    >>> round(float(metric.compute()), 4)
    0.75
    """,
    "wrappers.transformations.LambdaInputTransformer": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.wrappers import LambdaInputTransformer
    >>> from torchmetrics_tpu.classification import BinaryAccuracy
    >>> metric = LambdaInputTransformer(BinaryAccuracy(), transform_pred=lambda p: 1 - p)
    >>> metric.update(np.array([0.9, 0.1, 0.2]), np.array([0, 1, 1]))
    >>> round(float(metric.compute()), 4)
    1.0
    """,
    "wrappers.transformations.MetricInputTransformer": """
    >>> import numpy as np
    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.wrappers import MetricInputTransformer
    >>> from torchmetrics_tpu import MeanSquaredError
    >>> class ClampInputs(MetricInputTransformer):  # subclass the transform hook
    ...     def transform_pred(self, pred):
    ...         return jnp.clip(pred, 0.0, 1.0)
    >>> metric = ClampInputs(MeanSquaredError())
    >>> metric.update(np.array([1.5, 0.5], np.float32), np.array([1.0, 0.5], np.float32))
    >>> round(float(metric.compute()), 4)
    0.0
    """,
    # ------------------------- tower / dep-gated classes (usage contracts;
    # values need pretrained weights or optional deps, so examples are +SKIP
    # like the reference's pretrained-model docstrings)
    "image.fid.FrechetInceptionDistance": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.image import FrechetInceptionDistance
    >>> metric = FrechetInceptionDistance(feature=2048)  # doctest: +SKIP
    >>> imgs = np.random.randint(0, 255, (8, 3, 299, 299), dtype=np.uint8)  # doctest: +SKIP
    >>> metric.update(imgs, real=True)  # doctest: +SKIP
    >>> metric.update(imgs, real=False)  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "image.inception_score.InceptionScore": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.image import InceptionScore
    >>> metric = InceptionScore()  # doctest: +SKIP
    >>> metric.update(np.random.randint(0, 255, (8, 3, 299, 299), dtype=np.uint8))  # doctest: +SKIP
    >>> mean, std = metric.compute()  # doctest: +SKIP
    """,
    "image.kid.KernelInceptionDistance": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.image import KernelInceptionDistance
    >>> metric = KernelInceptionDistance(subset_size=4)  # doctest: +SKIP
    >>> imgs = np.random.randint(0, 255, (8, 3, 299, 299), dtype=np.uint8)  # doctest: +SKIP
    >>> metric.update(imgs, real=True)  # doctest: +SKIP
    >>> metric.update(imgs, real=False)  # doctest: +SKIP
    >>> mean, std = metric.compute()  # doctest: +SKIP
    """,
    "image.lpip.LearnedPerceptualImagePatchSimilarity": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.image import LearnedPerceptualImagePatchSimilarity
    >>> metric = LearnedPerceptualImagePatchSimilarity(net_type='alex')  # doctest: +SKIP
    >>> a = np.random.rand(4, 3, 64, 64).astype(np.float32) * 2 - 1  # doctest: +SKIP
    >>> b = np.random.rand(4, 3, 64, 64).astype(np.float32) * 2 - 1  # doctest: +SKIP
    >>> metric.update(a, b)  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "image.mifid.MemorizationInformedFrechetInceptionDistance": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.image import MemorizationInformedFrechetInceptionDistance
    >>> metric = MemorizationInformedFrechetInceptionDistance(feature=2048)  # doctest: +SKIP
    >>> imgs = np.random.randint(0, 255, (8, 3, 299, 299), dtype=np.uint8)  # doctest: +SKIP
    >>> metric.update(imgs, real=True)  # doctest: +SKIP
    >>> metric.update(imgs, real=False)  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "image.perceptual_path_length.PerceptualPathLength": """
    >>> from torchmetrics_tpu.image import PerceptualPathLength
    >>> metric = PerceptualPathLength(num_samples=8)  # doctest: +SKIP
    >>> metric.update(generator)  # a GeneratorLike with sample()/forward  # doctest: +SKIP
    >>> mean, std, lengths = metric.compute()  # doctest: +SKIP
    """,
    "audio.metrics.DeepNoiseSuppressionMeanOpinionScore": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.audio import DeepNoiseSuppressionMeanOpinionScore
    >>> metric = DeepNoiseSuppressionMeanOpinionScore(fs=16000, personalized=False)  # doctest: +SKIP
    >>> metric.update(np.random.randn(16000).astype(np.float32))  # doctest: +SKIP
    >>> metric.compute()  # p808_mos, sig, bak, ovr  # doctest: +SKIP
    """,
    "audio.metrics.PerceptualEvaluationSpeechQuality": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.audio import PerceptualEvaluationSpeechQuality
    >>> metric = PerceptualEvaluationSpeechQuality(16000, 'wb')  # doctest: +SKIP
    >>> target = np.random.randn(16000).astype(np.float32)  # doctest: +SKIP
    >>> metric.update(target + 0.01 * np.random.randn(16000).astype(np.float32), target)  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "audio.metrics.ShortTimeObjectiveIntelligibility": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.audio import ShortTimeObjectiveIntelligibility
    >>> metric = ShortTimeObjectiveIntelligibility(fs=16000)  # doctest: +SKIP
    >>> target = np.random.randn(16000).astype(np.float32)  # doctest: +SKIP
    >>> metric.update(target + 0.1 * np.random.randn(16000).astype(np.float32), target)  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "audio.metrics.SpeechReverberationModulationEnergyRatio": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.audio import SpeechReverberationModulationEnergyRatio
    >>> metric = SpeechReverberationModulationEnergyRatio(fs=8000)  # doctest: +SKIP
    >>> metric.update(np.random.randn(8000).astype(np.float32))  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "text.bert.BERTScore": """
    >>> from torchmetrics_tpu.text import BERTScore
    >>> metric = BERTScore(model_name_or_path='bert-base-uncased')  # doctest: +SKIP
    >>> metric.update(['the cat sat on the mat'], ['a cat sat on the mat'])  # doctest: +SKIP
    >>> metric.compute()  # {'precision': ..., 'recall': ..., 'f1': ...}  # doctest: +SKIP
    """,
    "text.infolm.InfoLM": """
    >>> from torchmetrics_tpu.text import InfoLM
    >>> metric = InfoLM('google/bert_uncased_L-2_H-128_A-2', idf=False)  # doctest: +SKIP
    >>> metric.update(['the cat sat on the mat'], ['a cat sat on the mat'])  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "multimodal.clip_score.CLIPScore": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.multimodal import CLIPScore
    >>> metric = CLIPScore(model_name_or_path='openai/clip-vit-base-patch16')  # doctest: +SKIP
    >>> imgs = np.random.randint(0, 255, (1, 3, 224, 224), dtype=np.uint8)  # doctest: +SKIP
    >>> metric.update(list(imgs), ['a photo of a cat'])  # doctest: +SKIP
    >>> float(metric.compute())  # doctest: +SKIP
    """,
    "multimodal.clip_iqa.CLIPImageQualityAssessment": """
    >>> import numpy as np
    >>> from torchmetrics_tpu.multimodal import CLIPImageQualityAssessment
    >>> metric = CLIPImageQualityAssessment(prompts=('quality',))  # doctest: +SKIP
    >>> metric.update(np.random.rand(1, 3, 224, 224).astype(np.float32))  # doctest: +SKIP
    >>> metric.compute()  # doctest: +SKIP
    """,
    # ------------------------------------------------------------- collections
    "collections.MetricCollection": """
    >>> import numpy as np
    >>> from torchmetrics_tpu import MetricCollection, MeanSquaredError, MeanAbsoluteError
    >>> col = MetricCollection([MeanSquaredError(), MeanAbsoluteError()])
    >>> col.update(np.array([2.5, 0.0, 2.0, 8.0]), np.array([3.0, -0.5, 2.0, 7.0]))
    >>> {k: round(float(v), 4) for k, v in sorted(col.compute().items())}
    {'MeanAbsoluteError': 0.5, 'MeanSquaredError': 0.375}
    """,
}


def attach_examples() -> None:
    """Append each example to its class docstring (idempotent).

    Two tables feed one loop: the manual ``_EXAMPLES`` above (keys are
    ``module.path.ClassName``) and the generated per-class table from
    ``tools/gen_doctest_examples.py`` (keys are ``subpackage:ClassName``).
    """
    import importlib

    from torchmetrics_tpu._examples_generated import _GENERATED

    pairs = [(*path.rpartition(".")[::2], example) for path, example in _EXAMPLES.items()]
    pairs += [(*key.partition(":")[::2], example) for key, example in _GENERATED.items()]
    for module_path, cls_name, example in pairs:
        module = importlib.import_module(f"torchmetrics_tpu.{module_path}")
        cls = getattr(module, cls_name)
        if cls.__doc__ and ">>>" in cls.__doc__:
            continue
        cls.__doc__ = (cls.__doc__ or "") + "\n\n    Example:" + example
