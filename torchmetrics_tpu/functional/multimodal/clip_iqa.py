# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""CLIP image quality assessment (reference ``functional/multimodal/clip_iqa.py``).

Prompt-pair softmax over CLIP similarities on a Flax CLIP. The ``piq``
``clip_iqa`` checkpoint path of the reference is not replicated — any HF CLIP
checkpoint (or an injected model/processor pair) plays that role.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.multimodal.clip_score import _get_clip_model_and_processor
from torchmetrics_tpu.utilities.jit_cache import jitted_forward

Array = jax.Array

_PROMPTS: Dict[str, Tuple[str, str]] = {
    "quality": ("Good photo.", "Bad photo."),
    "brightness": ("Bright photo.", "Dark photo."),
    "noisiness": ("Clean photo.", "Noisy photo."),
    "colorfullness": ("Colorful photo.", "Dull photo."),
    "sharpness": ("Sharp photo.", "Blurry photo."),
    "contrast": ("High contrast photo.", "Low contrast photo."),
    "complexity": ("Complex photo.", "Simple photo."),
    "natural": ("Natural photo.", "Synthetic photo."),
    "happy": ("Happy photo.", "Sad photo."),
    "scary": ("Scary photo.", "Peaceful photo."),
    "new": ("New photo.", "Old photo."),
    "warm": ("Warm photo.", "Cold photo."),
    "real": ("Real photo.", "Abstract photo."),
    "beautiful": ("Beautiful photo.", "Ugly photo."),
    "lonely": ("Lonely photo.", "Sociable photo."),
    "relaxing": ("Relaxing photo.", "Stressful photo."),
}


def _clip_iqa_format_prompts(prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",)) -> Tuple[List[str], List[str]]:
    """Expand prompt keywords / custom pairs (reference ``clip_iqa.py:92-142``)."""
    if not isinstance(prompts, tuple):
        raise ValueError("Argument `prompts` must be a tuple containing strings or tuples of strings")
    prompts_names: List[str] = []
    prompts_list: List[str] = []
    count = 0
    for p in prompts:
        if not isinstance(p, (str, tuple)):
            raise ValueError("Argument `prompts` must be a tuple containing strings or tuples of strings")
        if isinstance(p, str):
            if p not in _PROMPTS:
                raise ValueError(
                    f"All elements of `prompts` must be one of {_PROMPTS.keys()} if not custom tuple prompts, got {p}."
                )
            prompts_names.append(p)
            prompts_list.extend(_PROMPTS[p])
        else:
            if len(p) != 2:
                raise ValueError("If a tuple is provided in argument `prompts`, it must be of length 2")
            prompts_names.append(f"user_defined_{count}")
            prompts_list.extend(p)
            count += 1
    return prompts_list, prompts_names


def _clip_iqa_get_anchor_vectors(model: Any, processor: Callable, prompts_list: List[str]) -> Array:
    """Unit-norm text anchors (reference ``clip_iqa.py:145-176``)."""
    processed = processor(text=prompts_list, return_tensors="np", padding=True)
    anchors = jnp.asarray(
        jitted_forward(model, "get_text_features")(
            jnp.asarray(processed["input_ids"]), jnp.asarray(processed["attention_mask"])
        )
    )
    return anchors / jnp.linalg.norm(anchors, axis=-1, keepdims=True)


def _clip_iqa_update(
    images: Array, model: Any, processor: Callable, data_range: float
) -> Array:
    """Unit-norm image features (reference ``clip_iqa.py:179-204``)."""
    images = jnp.asarray(images) / float(data_range)
    processed = processor(images=[np.asarray(i) for i in images], return_tensors="np", padding=True)
    img_features = jnp.asarray(jitted_forward(model, "get_image_features")(jnp.asarray(processed["pixel_values"])))
    return img_features / jnp.linalg.norm(img_features, axis=-1, keepdims=True)


def _clip_iqa_compute(
    img_features: Array,
    anchors: Array,
    prompts_names: List[str],
    format_as_dict: bool = True,
) -> Union[Array, Dict[str, Array]]:
    """Positive-prompt probability per pair (reference ``clip_iqa.py:207-219``)."""
    logits_per_image = 100 * img_features @ anchors.T
    probs = jax.nn.softmax(logits_per_image.reshape(logits_per_image.shape[0], -1, 2), axis=-1)[:, :, 0]
    if len(prompts_names) == 1:
        return probs.squeeze()
    if format_as_dict:
        return {p: probs[:, i] for i, p in enumerate(prompts_names)}
    return probs


def clip_image_quality_assessment(
    images: Array,
    model_name_or_path: str = "openai/clip-vit-base-patch16",
    data_range: float = 1.0,
    prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
    model: Optional[Any] = None,
    processor: Optional[Callable] = None,
) -> Union[Array, Dict[str, Array]]:
    """CLIP-IQA (reference ``clip_iqa.py:222-330``)."""
    prompts_list, prompts_names = _clip_iqa_format_prompts(prompts)
    model, processor = _get_clip_model_and_processor(model_name_or_path, model, processor)
    images = jnp.asarray(images)
    if images.ndim != 4 or images.shape[1] != 3:
        raise ValueError(f"Expected 4d image batch in NCHW format, got shape {images.shape}")
    anchors = _clip_iqa_get_anchor_vectors(model, processor, prompts_list)
    img_features = _clip_iqa_update(images, model, processor, data_range)
    return _clip_iqa_compute(img_features, anchors, prompts_names)
