# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""CLIPScore (reference ``functional/multimodal/clip_score.py:44-164``).

Runs a **Flax** CLIP (``transformers.FlaxCLIPModel``) so the image/text
towers execute as jitted XLA programs on the accelerator — the reference uses
the torch ``CLIPModel``. ``model``/``processor`` are injectable for offline
or custom checkpoints.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.jit_cache import jitted_forward
from torchmetrics_tpu.utilities.imports import ModuleAvailableCache
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

_TRANSFORMERS_AVAILABLE = ModuleAvailableCache("transformers")


def _get_clip_model_and_processor(
    model_name_or_path: str = "openai/clip-vit-large-patch14",
    model: Optional[Any] = None,
    processor: Optional[Callable] = None,
) -> Tuple[Any, Callable]:
    """Load a Flax CLIP + processor, or pass through injected ones
    (reference ``clip_score.py:94-110``)."""
    if model is not None and processor is not None:
        return model, processor
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`clip_score` metric requires `transformers` package be installed."
            " Either install with `pip install transformers>=4.10.0` or `pip install torchmetrics[multimodal]`."
        )
    from transformers import CLIPProcessor, FlaxCLIPModel

    model = FlaxCLIPModel.from_pretrained(model_name_or_path)
    processor = CLIPProcessor.from_pretrained(model_name_or_path)
    return model, processor


def _clip_score_update(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    model: Any,
    processor: Callable,
) -> Tuple[Array, int]:
    """Per-pair 100·cosine similarity (reference ``clip_score.py:44-91``)."""
    if not isinstance(images, list):
        images = [images] if jnp.asarray(images).ndim == 3 else list(jnp.asarray(images))
    else:
        images = [jnp.asarray(i) for i in images]
    if not all(jnp.asarray(i).ndim == 3 for i in images):
        raise ValueError("Expected all images to be 3d but found image that has either more or less")
    if not isinstance(text, list):
        text = [text]
    if len(text) != len(images):
        raise ValueError(
            f"Expected the number of images and text examples to be the same but got {len(images)} and {len(text)}"
        )
    processed = processor(text=text, images=[np.asarray(i) for i in images], return_tensors="np", padding=True)

    img_features = jnp.asarray(jitted_forward(model, "get_image_features")(jnp.asarray(processed["pixel_values"])))
    img_features = img_features / jnp.linalg.norm(img_features, axis=-1, keepdims=True)

    max_position_embeddings = model.config.text_config.max_position_embeddings
    input_ids = jnp.asarray(processed["input_ids"])
    attention_mask = jnp.asarray(processed["attention_mask"])
    if attention_mask.shape[-1] > max_position_embeddings:
        rank_zero_warn(
            f"Encountered caption longer than max_position_embeddings={max_position_embeddings}."
            " Will truncate captions to this length."
            " If longer captions are needed, initialize argument `model_name_or_path` with a model that supports"
            " longer sequences",
            UserWarning,
        )
        attention_mask = attention_mask[..., :max_position_embeddings]
        input_ids = input_ids[..., :max_position_embeddings]

    txt_features = jnp.asarray(jitted_forward(model, "get_text_features")(input_ids, attention_mask))
    txt_features = txt_features / jnp.linalg.norm(txt_features, axis=-1, keepdims=True)

    score = 100 * (img_features * txt_features).sum(axis=-1)
    return score, len(text)


def clip_score(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    model_name_or_path: str = "openai/clip-vit-large-patch14",
    model: Optional[Any] = None,
    processor: Optional[Callable] = None,
) -> Array:
    """CLIPScore = max(100·cos(E_I, E_C), 0) (reference ``clip_score.py:117-164``)."""
    model, processor = _get_clip_model_and_processor(model_name_or_path, model, processor)
    score, _ = _clip_score_update(images, text, model, processor)
    return jnp.maximum(score.mean(), 0.0)
