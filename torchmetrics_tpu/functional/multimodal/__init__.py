# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Multimodal functional metrics (reference ``src/torchmetrics/functional/multimodal/__init__.py``)."""
from torchmetrics_tpu.functional.multimodal.clip_iqa import clip_image_quality_assessment
from torchmetrics_tpu.functional.multimodal.clip_score import clip_score

__all__ = ["clip_image_quality_assessment", "clip_score"]
