# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Pairwise similarity/distance kernels (reference
``src/torchmetrics/functional/pairwise/{cosine,euclidean,linear,manhattan,minkowski}.py``).

All five are MXU-shaped: the pairwise matrix comes from one matmul (cosine,
linear, euclidean via the norm expansion) or a broadcasted reduction
(manhattan, minkowski).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.compute import _safe_matmul

Array = jax.Array


def _check_input(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Tuple[Array, Array, bool]:
    """Validate [N,d] / [M,d] inputs (reference ``helpers.py:19-43``)."""
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is not None:
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    """Reduce along the last dim (reference ``helpers.py:46-62``)."""
    if reduction == "mean":
        return distmat.mean(axis=-1)
    if reduction == "sum":
        return distmat.sum(axis=-1)
    if reduction is None or reduction == "none":
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")


def _zero_diagonal(distance: Array, zero_diagonal: bool) -> Array:
    if zero_diagonal:
        n = min(distance.shape)
        distance = distance.at[jnp.arange(n), jnp.arange(n)].set(0)
    return distance


def _pairwise_cosine_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Normalized rows → one matmul (reference ``cosine.py:24-44``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    y = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    distance = _safe_matmul(x, y)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_cosine_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise cosine similarity (reference ``cosine.py:47-91``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y) if y is not None else None
    distance = _pairwise_cosine_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_euclidean_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """||x||^2 + ||y||^2 - 2<x,y> expansion (reference ``euclidean.py:24-44``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x_norm = (x * x).sum(axis=1, keepdims=True)
    y_norm = (y * y).sum(axis=1)
    distance = x_norm + y_norm[None, :] - 2 * _safe_matmul(x, y)
    distance = _zero_diagonal(distance, zero_diagonal)
    return jnp.sqrt(jnp.maximum(distance, 0.0))


def pairwise_euclidean_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise euclidean distance (reference ``euclidean.py:47-87``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y) if y is not None else None
    distance = _pairwise_euclidean_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_linear_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Inner products (reference ``linear.py:24-40``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = _safe_matmul(x, y)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_linear_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise linear similarity (reference ``linear.py:43-83``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y) if y is not None else None
    distance = _pairwise_linear_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_manhattan_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Broadcasted |x - y| sums (reference ``manhattan.py:24-40``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.abs(x[:, None, :] - y[None, :, :]).sum(axis=-1)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_manhattan_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise manhattan distance (reference ``manhattan.py:43-83``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y) if y is not None else None
    distance = _pairwise_manhattan_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_minkowski_distance_update(
    x: Array, y: Optional[Array] = None, exponent: float = 2, zero_diagonal: Optional[bool] = None
) -> Array:
    """Broadcasted p-norm (reference ``minkowski.py:25-46``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if not (isinstance(exponent, (float, int)) and exponent >= 1):
        raise ValueError(f"Argument `exponent` must be a float larger than 1, but got {exponent}")
    distance = (jnp.abs(x[:, None, :] - y[None, :, :]) ** exponent).sum(axis=-1) ** (1.0 / exponent)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_minkowski_distance(
    x: Array,
    y: Optional[Array] = None,
    exponent: float = 2,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise minkowski distance (reference ``minkowski.py:49-91``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y) if y is not None else None
    distance = _pairwise_minkowski_distance_update(x, y, exponent, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
