# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Pairwise metrics (reference ``src/torchmetrics/functional/pairwise/``)."""
from torchmetrics_tpu.functional.pairwise.metrics import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
    pairwise_minkowski_distance,
)

__all__ = [
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhattan_distance",
    "pairwise_minkowski_distance",
]
