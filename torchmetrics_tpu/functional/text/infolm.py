# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""InfoLM (reference ``functional/text/infolm.py:545``).

Information measures between masked-language-model token distributions of
candidate and reference sentences (Staerman et al., 2021). TPU-first detail:
the reference masks one position at a time and runs ``seq_len`` separate
forward passes (``infolm.py:367-421``); here all masked variants are stacked
into one ``(L·B, S)`` batch so the MLM forward is a single large XLA program.
The model is a **Flax** masked LM; ``model``/``user_tokenizer`` are
injectable for offline use.
"""
from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.imports import ModuleAvailableCache
from torchmetrics_tpu.utilities.jit_cache import jitted_forward

Array = jax.Array

_TRANSFORMERS_AVAILABLE = ModuleAvailableCache("transformers")

ALLOWED_INFORMATION_MEASURES = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)


class _InformationMeasure:
    """Dispatch + validation for the nine measures (reference ``infolm.py:72-295``)."""

    def __init__(self, information_measure: str, alpha: Optional[float] = None, beta: Optional[float] = None) -> None:
        if information_measure not in ALLOWED_INFORMATION_MEASURES:
            raise ValueError(
                f"Argument `information_measure` expected to be one of {ALLOWED_INFORMATION_MEASURES},"
                f" but got {information_measure}."
            )
        self.information_measure = information_measure
        if information_measure in ("alpha_divergence", "ab_divergence", "renyi_divergence"):
            if not isinstance(alpha, float) or alpha in (0, 1):
                raise ValueError(f"Parameter `alpha` is expected to be a float differing from 0 and 1, got {alpha}.")
        if information_measure in ("beta_divergence", "ab_divergence"):
            if not isinstance(beta, float) or beta in (0, -1):
                raise ValueError(f"Parameter `beta` is expected to be a float differing from 0 and -1, got {beta}.")
        if information_measure == "ab_divergence" and (alpha is not None and beta is not None and alpha + beta == 0):
            raise ValueError(f"Parameters `alpha` and `beta` cannot sum to 0, got {alpha} and {beta}.")
        self.alpha = alpha
        self.beta = beta

    def __call__(self, preds_distribution: Array, target_distribution: Array) -> Array:
        fn = getattr(self, f"_calculate_{self.information_measure}")
        return jnp.nan_to_num(fn(preds_distribution, target_distribution))

    @staticmethod
    def _calculate_kl_divergence(p: Array, t: Array) -> Array:
        return jnp.sum(t * jnp.log(p / t), axis=-1)

    def _calculate_alpha_divergence(self, p: Array, t: Array) -> Array:
        denom = self.alpha * (self.alpha - 1)
        return (1 - jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / denom

    def _calculate_ab_divergence(self, p: Array, t: Array) -> Array:
        a = jnp.log(jnp.sum(t ** (self.beta + self.alpha), axis=-1)) / (self.beta * (self.beta + self.alpha))
        b = jnp.log(jnp.sum(p ** (self.beta + self.alpha), axis=-1)) / (self.alpha * (self.beta + self.alpha))
        c = jnp.log(jnp.sum(t**self.alpha * p**self.beta, axis=-1)) / (self.alpha * self.beta)
        return a + b - c

    def _calculate_beta_divergence(self, p: Array, t: Array) -> Array:
        self.alpha = 1.0
        return self._calculate_ab_divergence(p, t)

    def _calculate_renyi_divergence(self, p: Array, t: Array) -> Array:
        return jnp.log(jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / (self.alpha - 1)

    @staticmethod
    def _calculate_l1_distance(p: Array, t: Array) -> Array:
        return jnp.sum(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _calculate_l2_distance(p: Array, t: Array) -> Array:
        return jnp.sqrt(jnp.sum((t - p) ** 2, axis=-1))

    @staticmethod
    def _calculate_l_infinity_distance(p: Array, t: Array) -> Array:
        return jnp.max(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _calculate_fisher_rao_distance(p: Array, t: Array) -> Array:
        return 2 * jnp.arccos(jnp.clip(jnp.sqrt(p * t).sum(-1), 0, 1))


def _get_special_tokens_map(tokenizer: Any) -> Dict[str, int]:
    """Special token ids needed for masking (reference ``infolm.py:323-339``)."""
    return {
        "mask_token_id": tokenizer.mask_token_id,
        "pad_token_id": tokenizer.pad_token_id,
        "sep_token_id": tokenizer.sep_token_id,
        "cls_token_id": tokenizer.cls_token_id,
    }


def _get_token_mask(input_ids: np.ndarray, special_tokens_map: Dict[str, int]) -> np.ndarray:
    """True for real (non-special) tokens (reference ``infolm.py:342-364``)."""
    mask = np.ones_like(input_ids, dtype=bool)
    for key in ("pad_token_id", "sep_token_id", "cls_token_id"):
        mask &= input_ids != special_tokens_map[key]
    return mask


def _get_tokens_idf(input_ids: np.ndarray, token_mask: np.ndarray) -> np.ndarray:
    """Per-position plus-one-smoothed idf weights."""
    num_sentences = input_ids.shape[0]
    counter: Counter = Counter()
    for row, mask in zip(input_ids, token_mask):
        counter.update(set(row[mask].tolist()))
    idf: Dict[int, float] = defaultdict(lambda: math.log((num_sentences + 1) / 1))
    idf.update({idx: math.log((num_sentences + 1) / (count + 1)) for idx, count in counter.items()})
    return np.vectorize(lambda t: idf[int(t)])(input_ids).astype(np.float64)


def _get_data_distribution(
    model: Any,
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    temperature: float,
    idf: bool,
    special_tokens_map: Dict[str, int],
    batch_size: int = 8,
) -> Array:
    """Per-sentence vocab distribution: average the MLM distribution at each
    masked position over real tokens (reference ``infolm.py:367-462``), with
    all masked variants batched into one forward per input batch."""
    token_mask = _get_token_mask(input_ids, special_tokens_map)
    idf_weights = (
        _get_tokens_idf(input_ids, token_mask) if idf else np.ones_like(token_mask, dtype=np.float64)
    )
    mask_token_id = int(special_tokens_map["mask_token_id"])

    # ONE compiled program per batch: variant construction, MLM forward,
    # masked-position softmax, and the weighted average all fuse — on a
    # remote TPU each extra eager dispatch is a multi-second host round-trip
    def make_fn(m):
        def fwd(params, temp, ids, att, tmask, w_idf):
            b, s = ids.shape
            # (L, B, S): variant l has position l replaced with [MASK]
            eye = jnp.eye(s, dtype=bool)[:, None, :]
            ids_rep = jnp.where(eye, mask_token_id, jnp.broadcast_to(ids[None], (s, b, s)))
            att_rep = jnp.broadcast_to(att[None], (s, b, s))
            logits = m(ids_rep.reshape(s * b, s), att_rep.reshape(s * b, s), params=params).logits
            logits = logits.reshape(s, b, s, -1)
            # distribution at the masked position of each variant -> (B, S, V)
            probs = jax.nn.softmax(logits[jnp.arange(s), :, jnp.arange(s)] / temp, axis=-1)
            probs = jnp.moveaxis(probs, 0, 1)
            tmask_f = tmask.astype(jnp.float32)
            weights = tmask_f * w_idf
            probs = probs * (w_idf * tmask_f)[:, :, None]
            return probs.sum(axis=1) / weights.sum(axis=1, keepdims=True)

        return fwd

    # temperature rides as a traced scalar — sweeping it must not recompile
    fn = jitted_forward(model, f"mlm_probs:{mask_token_id}", make_fn)
    out = [
        fn(
            np.float32(temperature),
            input_ids[start : start + batch_size],
            attention_mask[start : start + batch_size],
            token_mask[start : start + batch_size],
            idf_weights[start : start + batch_size].astype(np.float32),
        )
        for start in range(0, input_ids.shape[0], batch_size)
    ]
    return jnp.concatenate(out) if len(out) > 1 else out[0]


def _load_default_mlm(model_name_or_path: str):
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`infolm` metric with default models requires `transformers` package be installed."
            " Either install with `pip install transformers>=4.4` or `pip install torchmetrics[text]`."
        )
    from transformers import AutoTokenizer, FlaxAutoModelForMaskedLM

    tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
    model = FlaxAutoModelForMaskedLM.from_pretrained(model_name_or_path)
    return tokenizer, model


def infolm(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    model_name_or_path: str = "bert-base-uncased",
    temperature: float = 0.25,
    information_measure: str = "kl_divergence",
    idf: bool = True,
    alpha: Optional[float] = None,
    beta: Optional[float] = None,
    device: Optional[Any] = None,
    max_length: Optional[int] = None,
    batch_size: int = 64,
    num_threads: int = 0,
    verbose: bool = True,
    return_sentence_level_score: bool = False,
    model: Optional[Any] = None,
    user_tokenizer: Optional[Any] = None,
):
    """InfoLM (reference ``infolm.py:545-…``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("Number of predicted and reference sententes must be the same!")
    if not (isinstance(temperature, float) and temperature > 0):
        raise ValueError(f"Argument `temperature` is expected to be a positive float, got {temperature}.")
    measure = _InformationMeasure(information_measure, alpha, beta)
    tokenizer = user_tokenizer
    if model is None:
        tokenizer, model = _load_default_mlm(model_name_or_path)
    max_length = max_length or getattr(getattr(model, "config", None), "max_position_embeddings", 512)
    special_tokens_map = _get_special_tokens_map(tokenizer)

    enc_p = tokenizer(list(preds), padding=True, truncation=True, max_length=max_length, return_tensors="np")
    enc_t = tokenizer(list(target), padding=True, truncation=True, max_length=max_length, return_tensors="np")
    preds_distribution = _get_data_distribution(
        model, np.asarray(enc_p["input_ids"]), np.asarray(enc_p["attention_mask"]), temperature, idf,
        special_tokens_map, batch_size=min(batch_size, 8),
    )
    target_distribution = _get_data_distribution(
        model, np.asarray(enc_t["input_ids"]), np.asarray(enc_t["attention_mask"]), temperature, idf,
        special_tokens_map, batch_size=min(batch_size, 8),
    )
    # pad to a common vocab axis is unnecessary (same model); compute measure
    info_lm_score = measure(preds_distribution, target_distribution)
    if return_sentence_level_score:
        return info_lm_score.mean(), info_lm_score
    return info_lm_score.mean()
