# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Word/char/match error rates and word-information metrics (reference
``src/torchmetrics/functional/text/{wer,cer,mer,wil,wip}.py``)."""
from __future__ import annotations

from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.helper import _batch_edit_distance, _normalize_inputs

Array = jax.Array


def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Summed edit ops + reference word count (reference ``wer.py:22-47``)."""
    preds, target = _normalize_inputs(preds, target)
    pred_tokens = [p.split() for p in preds]
    tgt_tokens = [t.split() for t in target]
    errors = int(_batch_edit_distance(pred_tokens, tgt_tokens).sum())
    total = sum(len(t) for t in tgt_tokens)
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _wer_compute(errors: Array, total: Array) -> Array:
    """errors / total (reference ``wer.py:50-59``)."""
    return errors / total


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word error rate (reference ``wer.py:62-84``)."""
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)


def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Summed char edit ops + reference char count (reference ``cer.py:22-48``)."""
    preds, target = _normalize_inputs(preds, target)
    errors = int(_batch_edit_distance([list(p) for p in preds], [list(t) for t in target]).sum())
    total = sum(len(t) for t in target)
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _cer_compute(errors: Array, total: Array) -> Array:
    """errors / total (reference ``cer.py:51-60``)."""
    return errors / total


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Character error rate (reference ``cer.py:63-85``)."""
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)


def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Summed edit ops + max(len) count (reference ``mer.py:22-48``)."""
    preds, target = _normalize_inputs(preds, target)
    pred_tokens = [p.split() for p in preds]
    tgt_tokens = [t.split() for t in target]
    errors = int(_batch_edit_distance(pred_tokens, tgt_tokens).sum())
    total = sum(max(len(t), len(p)) for p, t in zip(pred_tokens, tgt_tokens))
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _mer_compute(errors: Array, total: Array) -> Array:
    """errors / total (reference ``mer.py:51-60``)."""
    return errors / total


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Match error rate (reference ``mer.py:63-86``)."""
    errors, total = _mer_update(preds, target)
    return _mer_compute(errors, total)


def _wil_wip_update(
    preds: Union[str, List[str]], target: Union[str, List[str]]
) -> Tuple[Array, Array, Array]:
    """Shared accumulation of WIL/WIP (reference ``wil.py:21-52``, ``wip.py:21-52``)."""
    preds, target = _normalize_inputs(preds, target)
    pred_tokens = [p.split() for p in preds]
    tgt_tokens = [t.split() for t in target]
    errors = int(_batch_edit_distance(pred_tokens, tgt_tokens).sum())
    target_total = sum(len(t) for t in tgt_tokens)
    preds_total = sum(len(p) for p in pred_tokens)
    total = sum(max(len(t), len(p)) for p, t in zip(pred_tokens, tgt_tokens))
    return (
        jnp.asarray(float(errors - total)),
        jnp.asarray(float(target_total)),
        jnp.asarray(float(preds_total)),
    )


def _word_info_lost_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    """1 - (C/N_t)(C/N_p) (reference ``wil.py:55-66``)."""
    return 1 - ((errors / target_total) * (errors / preds_total))


def word_information_lost(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information lost (reference ``wil.py:69-90``)."""
    errors, target_total, preds_total = _wil_wip_update(preds, target)
    return _word_info_lost_compute(errors, target_total, preds_total)


def _wip_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    """(C/N_t)(C/N_p) (reference ``wip.py:55-66``)."""
    return (errors / target_total) * (errors / preds_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information preserved (reference ``wip.py:69-90``)."""
    errors, target_total, preds_total = _wil_wip_update(preds, target)
    return _wip_compute(errors, target_total, preds_total)
