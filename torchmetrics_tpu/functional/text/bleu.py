# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""BLEU score (reference ``src/torchmetrics/functional/text/bleu.py``)."""
from __future__ import annotations

from collections import Counter
from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.helper import _count_ngram

Array = jax.Array


def _tokenize_fn(sentence: str) -> Sequence[str]:
    """Whitespace tokenizer (reference ``bleu.py:44-51``)."""
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    numerator: Array,
    denominator: Array,
    preds_len: Array,
    target_len: Array,
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[Array, Array, Array, Array]:
    """Clipped n-gram counts + closest-length bookkeeping (reference ``bleu.py:54-101``).

    Differs from the reference in that the accumulators are returned
    functionally (immutable arrays) instead of mutated in place.
    """
    target_tok: Sequence[Sequence[Sequence[str]]] = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tok: Sequence[Sequence[str]] = [tokenizer(line) if line else [] for line in preds]
    numerator_np = jnp.asarray(numerator).tolist()
    denominator_np = jnp.asarray(denominator).tolist()
    preds_len_acc = float(preds_len)
    target_len_acc = float(target_len)
    for pred, targets in zip(preds_tok, target_tok):
        preds_len_acc += len(pred)
        target_len_list = [len(tgt) for tgt in targets]
        target_len_diff = [abs(len(pred) - x) for x in target_len_list]
        target_len_acc += target_len_list[target_len_diff.index(min(target_len_diff))]
        preds_counter: Counter = _count_ngram(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngram(tgt, n_gram)
        ngram_counter_clip = preds_counter & target_counter
        for counter_clip in ngram_counter_clip:
            numerator_np[len(counter_clip) - 1] += ngram_counter_clip[counter_clip]
        for counter in preds_counter:
            denominator_np[len(counter) - 1] += preds_counter[counter]
    return (
        jnp.asarray(numerator_np),
        jnp.asarray(denominator_np),
        jnp.asarray(preds_len_acc),
        jnp.asarray(target_len_acc),
    )


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int,
    weights: Sequence[float],
    smooth: bool,
) -> Array:
    """Geometric-mean precision with brevity penalty (reference ``bleu.py:104-137``).

    Fully traceable: the reference's zero-numerator early return is a
    ``jnp.where`` select, so the whole compute can run under ``jit``.
    """
    if smooth:
        precision_scores = (numerator + jnp.ones(n_gram)) / (denominator + jnp.ones(n_gram))
        precision_scores = precision_scores.at[0].set(numerator[0] / denominator[0])
    else:
        precision_scores = numerator / denominator
    # guard the log against 0/0 lanes; any zero numerator zeroes the result below
    safe_precision = jnp.where(numerator > 0, precision_scores, 1.0)
    log_precision_scores = jnp.asarray(weights) * jnp.log(safe_precision)
    geometric_mean = jnp.exp(jnp.sum(log_precision_scores))
    brevity_penalty = jnp.where(preds_len > target_len, 1.0, jnp.exp(1 - target_len / preds_len))
    return jnp.where(jnp.min(numerator) == 0.0, 0.0, brevity_penalty * geometric_mean)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """BLEU score of a translated corpus (reference ``bleu.py:140-192``)."""
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    preds_len = jnp.asarray(0.0)
    target_len = jnp.asarray(0.0)
    numerator, denominator, preds_len, target_len = _bleu_score_update(
        preds_, target_, numerator, denominator, preds_len, target_len, n_gram
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
