# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Extended edit distance (reference ``functional/text/eed.py:364``).

Implements the published EED measure (Stanchev, Wang, Ney, WMT 2019): a
CDER-style character-level alignment grid with jump penalties and a coverage
cost. The per-reference-character row update is a vectorized numpy recurrence
(the deletion term is a prefix-min scan) instead of the original's per-cell
Python loops.
"""
from __future__ import annotations

import re
import unicodedata
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """EED score for one (hyp, ref) string pair (reference ``eed.py:116-171``;
    algorithm from rwth-i6/ExtendedEditDistance).

    ``row[i]`` holds the cheapest path cost from (0,0) to (i, w) in the CDER
    grid; each reference character triggers one vectorized row update.
    """
    n_h = len(hyp)
    hyp_arr = np.array(list(hyp)) if n_h else np.zeros(0, dtype="<U1")
    number_of_visits = np.full(n_h + 1, -1, dtype=np.int64)
    row = np.ones(n_h + 1, dtype=np.float64)
    row[0] = 0.0
    offsets = np.arange(n_h + 1) * deletion

    for w in range(len(ref)):
        ref_char = ref[w]
        next_row = np.empty(n_h + 1, dtype=np.float64)
        next_row[0] = row[0] + 1.0
        if n_h:
            sub = row[:-1] + (hyp_arr != ref_char)
            ins = row[1:] + insertion
            base = np.minimum(sub, ins)
            # deletion chains: next_row[i] = min over j<=i of b[j] + (i-j)*del
            b = np.concatenate([[next_row[0]], base])
            next_row = offsets + np.minimum.accumulate(b - offsets)
        min_index = int(np.argmin(next_row))
        number_of_visits[min_index] += 1
        # long jumps are allowed at word boundaries of the reference
        if ref_char == " ":
            next_row = np.minimum(next_row, alpha + next_row[min_index])
        row = next_row

    coverage = rho * float(np.where(number_of_visits >= 0, number_of_visits, 1).sum())
    return min(1.0, (float(row[-1]) + coverage) / (float(len(ref)) + coverage))


def _preprocess_en(sentence: str) -> str:
    """English preprocessing (reference ``eed.py:174-215``; rules from the
    published EED utility)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for pattern, replacement in ((".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")):
        sentence = sentence.replace(pattern, replacement)
    for pattern, replacement in (
        (r"\s+", r" "),
        (r"(\d) ([.,]) (\d)", r"\1\2\3"),
        (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),
    ):
        sentence = re.sub(pattern, replacement, sentence)
    for pattern, replacement in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(pattern, replacement)
    return " " + sentence + " "


def _preprocess_ja(sentence: str) -> str:
    """Japanese preprocessing: NFKC normalization (reference ``eed.py:219-233``)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_compute(sentence_level_scores: List[float]) -> Array:
    """Average of sentence scores (reference ``eed.py:236-249``)."""
    if len(sentence_level_scores) == 0:
        return jnp.asarray(0.0)
    return jnp.asarray(sum(sentence_level_scores) / len(sentence_level_scores))


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> List[float]:
    """Sentence-level EED scores (reference ``eed.py:322-361``)."""
    if language not in ("en", "ja"):
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
    preprocess = _preprocess_en if language == "en" else _preprocess_ja
    if isinstance(preds, str):
        preds = [preds]
    target = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    preds = [preprocess(p) for p in preds]
    target = [[preprocess(t) for t in tgt] for tgt in target]
    if 0 in (len(preds), len(target[0]) if target else 0):
        return []
    scores: List[float] = []
    for hyp, refs in zip(preds, target):
        scores.append(min(_eed_function(hyp, ref, alpha, rho, deletion, insertion) for ref in refs))
    return scores


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
):
    """EED (reference ``eed.py:364-414``)."""
    for param_name, param in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
        if not isinstance(param, float) or param < 0:
            raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")
    sentence_eed = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_eed)
    if return_sentence_level_score:
        return average, jnp.asarray(sentence_eed)
    return average
