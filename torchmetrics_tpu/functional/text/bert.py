# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""BERTScore (reference ``functional/text/bert.py:69-257``).

The embedding model is a **Flax** transformer (``transformers.FlaxAutoModel``)
so the forward passes are jitted XLA programs; pairwise token cosine and the
greedy max-matching are one batched einsum + max-reduce. ``model``/
``user_tokenizer``/``user_forward_fn`` are injectable exactly like the
reference's user-model path (``bert.py:259-…``), which keeps the metric
usable offline and with custom towers.

Deliberate divergence: scores return in INPUT order. The reference sorts
inputs by length (``helper_embedding_metric.py:79-84``, permutation ``p``)
and "restores" with ``emb[p]`` instead of the inverse permutation
(``bert.py:444-448``), so its per-sentence outputs are permuted whenever
input lengths aren't pre-sorted; corpus means agree. Verified with shared
weights in ``tests/unittests/tower_parity/test_shared_weight_parity.py``.
"""
from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.imports import ModuleAvailableCache

Array = jax.Array

_TRANSFORMERS_AVAILABLE = ModuleAvailableCache("transformers")

_DEFAULT_MODEL = "roberta-large"


def _process_attention_mask_for_special_tokens(attention_mask: np.ndarray) -> np.ndarray:
    """Zero out [CLS]/[SEP] positions (reference ``helper_embedding_metric.py:33-49``)."""
    attention_mask = attention_mask.copy()
    attention_mask[:, 0] = 0
    sep_pos = np.cumsum(attention_mask - 0.1, axis=-1).argmax(-1)
    attention_mask[np.arange(attention_mask.shape[0]), sep_pos] = 0
    return attention_mask


def _get_tokens_idf(input_ids: np.ndarray, attention_mask: np.ndarray) -> Dict[int, float]:
    """Plus-one-smoothed log inverse document frequencies (reference
    ``helper_embedding_metric.py:240-259``)."""
    num_sentences = input_ids.shape[0]
    counter: Counter = Counter()
    for row, mask in zip(input_ids, attention_mask):
        counter.update(set(row[mask > 0].tolist()))
    idf: Dict[int, float] = defaultdict(lambda: math.log((num_sentences + 1) / 1))
    idf.update({idx: math.log((num_sentences + 1) / (count + 1)) for idx, count in counter.items()})
    return idf


def _embed(
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    model: Any,
    user_forward_fn: Callable,
    idf: bool,
    tokens_idf: Optional[Dict[int, float]],
    batch_size: int,
) -> Tuple[Array, Array]:
    """Unit-norm token embeddings masked for special tokens + per-sentence
    normalized idf scales, via a user-supplied forward (reference
    ``bert.py:69-149``). The default Flax path runs the fused corpus program
    (:func:`_fused_score_forward`) instead."""
    # trim to the longest real sequence (reference _input_data_collator)
    real_len = int(attention_mask.sum(1).max())
    input_ids = input_ids[:, :real_len]
    attention_mask = attention_mask[:, :real_len]
    embeddings_list = []
    for start in range(0, input_ids.shape[0], batch_size):
        ids = jnp.asarray(input_ids[start : start + batch_size])
        mask = jnp.asarray(attention_mask[start : start + batch_size])
        out = user_forward_fn(model, {"input_ids": ids, "attention_mask": mask})
        out = jnp.asarray(out)[:, None]  # (B, 1, S, D)
        out = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-12)
        embeddings_list.append(out)
    embeddings = jnp.concatenate(embeddings_list)  # (B, L, S, D); L == 1 unless all_layers

    processed_mask = _process_attention_mask_for_special_tokens(attention_mask)
    embeddings = embeddings * jnp.asarray(processed_mask)[:, None, :, None]

    if idf:
        assert tokens_idf is not None
        idf_weights = np.vectorize(lambda t: tokens_idf[int(t)])(input_ids).astype(np.float64)
        idf_weights = idf_weights * processed_mask
    else:
        idf_weights = processed_mask.astype(np.float64)
    idf_scale = idf_weights / idf_weights.sum(-1, keepdims=True)
    return embeddings, jnp.asarray(idf_scale, jnp.float32)


def _pairwise_prf(
    preds_embeddings: Array,
    target_embeddings: Array,
    preds_idf_scale: Array,
    target_idf_scale: Array,
) -> Tuple[Array, Array, Array]:
    """Greedy-matching P/R/F1 over ``(B, L, S, D)`` embeddings as ``(B, L)``
    (reference ``bert.py:150-184``); the layer axis L is 1 unless
    ``all_layers``. Traced into the fused score program."""
    cos_sim = jnp.einsum("blpd, blrd -> blpr", preds_embeddings, target_embeddings)
    precision = (cos_sim.max(axis=3) * preds_idf_scale[:, None, :]).sum(-1)  # (B, L)
    recall = (cos_sim.max(axis=2) * target_idf_scale[:, None, :]).sum(-1)
    f1 = 2 * precision * recall / (precision + recall)
    return precision, recall, jnp.nan_to_num(f1)


def _flatten_layerwise(t: Array) -> Array:
    """Reference output layout: (L, B) squeezed to (B,) for L == 1."""
    return jnp.squeeze(t.T, 0) if t.shape[1] == 1 else t.T.reshape(-1)


@jax.jit
def _get_precision_recall_f1(
    preds_embeddings: Array,
    target_embeddings: Array,
    preds_idf_scale: Array,
    target_idf_scale: Array,
) -> Tuple[Array, Array, Array]:
    """Standalone jitted matching (the ``user_forward_fn`` path)."""
    precision, recall, f1 = _pairwise_prf(
        preds_embeddings, target_embeddings, preds_idf_scale, target_idf_scale
    )
    return _flatten_layerwise(precision), _flatten_layerwise(recall), _flatten_layerwise(f1)


def _make_fused_score_fn(m: Any, num_layers: Optional[int], all_layers: bool) -> Callable:
    """The fused corpus program body: a ``lax.map`` over chunks, each chunk
    running encoder forward for BOTH sides + special-token masking + idf
    scaling + greedy matching. Shared by the metric path and the bench's
    repeat harness."""

    def encode(params, ids, mask, pmask):
        hidden = m(ids, mask, params=params, output_hidden_states=True).hidden_states
        if all_layers:
            out = jnp.stack(hidden, axis=1)  # (bs, L, S, D)
        else:
            out = hidden[num_layers if num_layers is not None else -1][:, None]
        out = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-12)
        return out * pmask[:, None, :, None]

    def fwd(params, ids_p, am_p, pm_p, sc_p, ids_t, am_t, pm_t, sc_t):
        def body(chunk):
            i_p, a_p, p_p, s_p, i_t, a_t, p_t, s_t = chunk
            emb_p = encode(params, i_p, a_p, p_p)
            emb_t = encode(params, i_t, a_t, p_t)
            return jnp.stack(_pairwise_prf(emb_p, emb_t, s_p, s_t))  # (3, bs, L)

        return jax.lax.map(body, (ids_p, am_p, pm_p, sc_p, ids_t, am_t, pm_t, sc_t))

    return fwd


def _fused_score_forward(model: Any, num_layers: Optional[int], all_layers: bool) -> Callable:
    """ONE compiled program for the whole corpus (``_make_fused_score_fn``).

    One dispatch per *evaluation*, not per chunk: a remote TPU charges a
    large, variable per-execution constant (measured 0.1-60s over the axon
    tunnel), so the whole corpus must ride a single call — inputs go up
    once, one small ``(C, 3, bs, L)`` score tensor comes down."""
    from torchmetrics_tpu.utilities.jit_cache import jitted_forward

    def make_fn(m):
        return _make_fused_score_fn(m, num_layers, all_layers)

    return jitted_forward(model, f"fused_score:{num_layers}:{all_layers}", make_fn)


def _fused_score_repeated_forward(
    model: Any, num_layers: Optional[int], all_layers: bool, repeats: int
) -> Callable:
    """Bench harness: the fused corpus program executed ``repeats`` times
    inside ONE dispatch, input ids perturbed per repetition (so XLA cannot
    CSE the iterations) and score tensors summed (so it cannot DCE them).

    Exists to measure marginal device throughput — the per-execution tunnel
    constant amortizes over ``repeats`` corpus passes within a single
    execution. Not part of the metric API."""
    from torchmetrics_tpu.utilities.jit_cache import jitted_forward

    def make_fn(m):
        fwd = _make_fused_score_fn(m, num_layers, all_layers)

        def repeated(params, ids_p, am_p, pm_p, sc_p, ids_t, am_t, pm_t, sc_t):
            out0 = fwd(params, ids_p, am_p, pm_p, sc_p, ids_t, am_t, pm_t, sc_t)

            def step(acc, r):
                out = fwd(params, (ids_p + r) % 30000, am_p, pm_p, sc_p,
                          (ids_t + r) % 30000, am_t, pm_t, sc_t)
                return acc + out, None

            acc, _ = jax.lax.scan(step, out0, jnp.arange(1, repeats, dtype=jnp.int32))
            return acc

        return repeated

    return jitted_forward(model, f"fused_score_rep:{num_layers}:{all_layers}:{repeats}", make_fn)


def _fused_score_dynamic_repeat_forward(model: Any, num_layers: Optional[int], all_layers: bool) -> Callable:
    """Bench harness: like :func:`_fused_score_repeated_forward` but the
    repeat count is a RUNTIME argument (``lax.fori_loop`` with a traced
    bound), so every repeat level executes the SAME compiled program.

    This is what makes the marginal-throughput slope robust on a remote
    tunnel: the per-execution service constant differs wildly BETWEEN
    programs (measured 28s vs 70s for two same-size programs in one session)
    but only by a few seconds between executions of one program — a
    same-program ``T(R_big) - T(R_small)`` difference cancels it. Not part
    of the metric API."""
    from torchmetrics_tpu.utilities.jit_cache import jitted_forward

    def make_fn(m):
        fwd = _make_fused_score_fn(m, num_layers, all_layers)

        def repeated(params, repeats, ids_p, am_p, pm_p, sc_p, ids_t, am_t, pm_t, sc_t):
            out0 = fwd(params, ids_p, am_p, pm_p, sc_p, ids_t, am_t, pm_t, sc_t)

            def body(r, acc):
                out = fwd(params, (ids_p + r) % 30000, am_p, pm_p, sc_p,
                          (ids_t + r) % 30000, am_t, pm_t, sc_t)
                return acc + out

            return jax.lax.fori_loop(1, repeats, body, out0)

        return repeated

    return jitted_forward(model, f"fused_score_dynrep:{num_layers}:{all_layers}", make_fn)


def _host_side_inputs(
    input_ids: np.ndarray, attention_mask: np.ndarray, idf: bool, tokens_idf: Optional[Dict[int, float]]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Trim to the longest real sequence + special-token mask + idf scale
    (the cheap host-side prep of reference ``bert.py:69-149``)."""
    real_len = int(attention_mask.sum(1).max())
    input_ids = input_ids[:, :real_len]
    attention_mask = attention_mask[:, :real_len]
    pmask = _process_attention_mask_for_special_tokens(attention_mask)
    if idf:
        assert tokens_idf is not None
        weights = np.vectorize(lambda t: tokens_idf[int(t)])(input_ids).astype(np.float64) * pmask
    else:
        weights = pmask.astype(np.float64)
    scale = weights / weights.sum(-1, keepdims=True)
    return input_ids, attention_mask, pmask, scale.astype(np.float32)


def _chunked_fused_score(
    preds_ids: np.ndarray,
    preds_mask: np.ndarray,
    target_ids: np.ndarray,
    target_mask: np.ndarray,
    model: Any,
    num_layers: Optional[int],
    all_layers: bool,
    idf: bool,
    tokens_idf: Optional[Dict[int, float]],
    batch_size: int,
) -> Tuple[Array, Array, Array]:
    """Run the fused corpus program: ONE device dispatch for all pairs,
    nothing but ``(C, 3, bs, L)`` scores crossing the wire back."""
    ids_p, am_p, pm_p, sc_p = _host_side_inputs(preds_ids, preds_mask, idf, tokens_idf)
    ids_t, am_t, pm_t, sc_t = _host_side_inputs(target_ids, target_mask, idf, tokens_idf)
    n = ids_p.shape[0]
    fn = _fused_score_forward(model, num_layers, all_layers)
    # pad to full chunks; padded rows have zero masks/scales and are trimmed
    # before returning
    n_pad = (-n) % batch_size

    def chunked(x):
        if n_pad:
            x = np.pad(x, ((0, n_pad),) + ((0, 0),) * (x.ndim - 1))
        return x.reshape(-1, batch_size, *x.shape[1:])

    out = np.asarray(fn(*(chunked(a) for a in (ids_p, am_p, pm_p, sc_p, ids_t, am_t, pm_t, sc_t))))
    prf = np.moveaxis(out, 1, 0).reshape(3, n + n_pad, -1)[:, :n]  # (3, B, L)

    def flat(t: np.ndarray) -> np.ndarray:
        return t.T.squeeze(0) if t.shape[1] == 1 else t.T.reshape(-1)

    return flat(prf[0]), flat(prf[1]), flat(prf[2])


def _load_default_model(model_name_or_path: str):
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`bert_score` metric with default models requires `transformers` package be installed."
            " Either install with `pip install transformers>=4.4` or `pip install torchmetrics[text]`."
        )
    from transformers import AutoTokenizer, FlaxAutoModel

    tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
    model = FlaxAutoModel.from_pretrained(model_name_or_path)
    return model, tokenizer


def bert_score(
    preds: Union[str, Sequence[str], Dict[str, np.ndarray]],
    target: Union[str, Sequence[str], Dict[str, np.ndarray]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Any] = None,
    user_tokenizer: Optional[Any] = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[Any] = None,
    max_length: int = 512,
    batch_size: int = 64,
    num_threads: int = 0,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
) -> Dict[str, Union[Array, List[float], str]]:
    """BERTScore (reference ``bert.py:259-…``).

    ``preds``/``target`` are raw strings or pre-tokenized dicts with
    ``input_ids``/``attention_mask``. ``all_layers``/baseline rescaling of the
    reference are supported except for downloading baselines (no egress);
    pass ``baseline_path`` with a local CSV for rescaling.
    """
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if not isinstance(preds, dict) and not isinstance(target, dict) and len(preds) != len(target):
        raise ValueError("Number of predicted and reference sententes must be the same!")
    if all_layers and user_forward_fn is not None:
        raise ValueError("The option `all_layers=True` can be used only with default `transformers` models.")
    if rescale_with_baseline and baseline_path is None and baseline_url is None:
        raise ValueError(
            "Baseline rescaling requires a local `baseline_path` (downloading baselines needs network egress)."
        )

    tokenizer = user_tokenizer
    if model is None:
        model, tokenizer = _load_default_model(model_name_or_path or _DEFAULT_MODEL)

    def tokenize(texts):
        if isinstance(texts, dict):
            return np.asarray(texts["input_ids"]), np.asarray(texts["attention_mask"])
        enc = tokenizer(list(texts), padding=True, truncation=True, max_length=max_length, return_tensors="np")
        return np.asarray(enc["input_ids"]), np.asarray(enc["attention_mask"])

    preds_ids, preds_mask = tokenize(preds)
    target_ids, target_mask = tokenize(target)

    tokens_idf = _get_tokens_idf(target_ids, target_mask) if idf else None

    if user_forward_fn is not None:
        preds_emb, preds_scale = _embed(
            preds_ids, preds_mask, model, user_forward_fn, idf, tokens_idf, batch_size
        )
        target_emb, target_scale = _embed(
            target_ids, target_mask, model, user_forward_fn, idf, tokens_idf, batch_size
        )

        # pad both sides to a common sequence length for one batched einsum
        max_len = max(preds_emb.shape[2], target_emb.shape[2])

        def pad_to(x, scale):
            pad = max_len - x.shape[2]
            if pad:
                x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
                scale = jnp.pad(scale, ((0, 0), (0, pad)))
            return x, scale

        preds_emb, preds_scale = pad_to(preds_emb, preds_scale)
        target_emb, target_scale = pad_to(target_emb, target_scale)

        precision, recall, f1 = _get_precision_recall_f1(preds_emb, target_emb, preds_scale, target_scale)
    else:
        precision, recall, f1 = _chunked_fused_score(
            preds_ids, preds_mask, target_ids, target_mask,
            model, num_layers, all_layers, idf, tokens_idf, batch_size,
        )

    if rescale_with_baseline and baseline_path is not None:
        import csv

        with open(baseline_path) as fname:
            rows = [[float(v) for v in row] for i, row in enumerate(csv.reader(fname)) if i > 0]
        baseline = np.asarray(rows)[:, 1:]
        if all_layers:
            # per-layer baselines over the (L, B)-flattened scores
            n_b = precision.shape[0] // baseline.shape[0]
            scale = jnp.asarray(np.repeat(baseline, n_b, axis=0))  # (L*B, 3)
            precision = (precision - scale[:, 0]) / (1 - scale[:, 0])
            recall = (recall - scale[:, 1]) / (1 - scale[:, 1])
            f1 = (f1 - scale[:, 2]) / (1 - scale[:, 2])
        else:
            scale = jnp.asarray(baseline[num_layers if num_layers is not None else -1])
            precision = (precision - scale[0]) / (1 - scale[0])
            recall = (recall - scale[1]) / (1 - scale[1])
            f1 = (f1 - scale[2]) / (1 - scale[2])

    output = {"precision": precision, "recall": recall, "f1": f1}
    if return_hash:
        output["hash"] = f"{model_name_or_path}_L{num_layers}{'_idf' if idf else '_no-idf'}"
    return output
