# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""SacreBLEU (reference ``src/torchmetrics/functional/text/sacre_bleu.py``).

Implements the sacrebleu tokenizers ``none``/``13a``/``zh``/``intl``/``char``;
the mecab/flores tokenizers require optional native deps and raise a clear
error when unavailable.
"""
from __future__ import annotations

import re
from typing import ClassVar, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update
from torchmetrics_tpu.utilities.imports import _REGEX_AVAILABLE

Array = jax.Array

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char", "ja-mecab", "ko-mecab")

# CJK codepoint ranges used by the sacrebleu `zh` tokenizer
_UCODE_RANGES = (
    (0x3400, 0x4DB5), (0x4E00, 0x9FA5), (0x9FA6, 0x9FBB), (0xF900, 0xFA2D),
    (0xFA30, 0xFA6A), (0xFA70, 0xFAD9), (0x20000, 0x2A6D6), (0x2F800, 0x2FA1D),
    (0xFF00, 0xFFEF), (0x2E80, 0x2EFF), (0x3000, 0x303F), (0x31C0, 0x31EF),
    (0x2F00, 0x2FDF), (0x2FF0, 0x2FFF), (0x3100, 0x312F), (0x31A0, 0x31BF),
    (0xFE10, 0xFE1F), (0xFE30, 0xFE4F), (0x2600, 0x26FF), (0x2700, 0x27BF),
    (0x3200, 0x32FF), (0x3300, 0x33FF),
)


class _SacreBLEUTokenizer:
    """Sacrebleu-compatible tokenizers (reference ``sacre_bleu.py:98-431``)."""

    _REGEX = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )

    if _REGEX_AVAILABLE:
        import regex

        _INT_REGEX = (
            (regex.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
            (regex.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
            (regex.compile(r"(\p{S})"), r" \1 "),
        )

    _TOKENIZE_FN: ClassVar[dict] = {
        "none": "_tokenize_base",
        "13a": "_tokenize_13a",
        "zh": "_tokenize_zh",
        "intl": "_tokenize_international",
        "char": "_tokenize_char",
        "ja-mecab": "_tokenize_ja_mecab",
        "ko-mecab": "_tokenize_ko_mecab",
    }

    def __init__(self, tokenize: str, lowercase: bool = False) -> None:
        self._check_tokenizers_validity(tokenize)
        self.tokenize_fn = getattr(self, self._TOKENIZE_FN[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized_line = self.tokenize_fn(line)
        return self._lower(tokenized_line, self.lowercase).split()

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        cls._check_tokenizers_validity(tokenize)
        tokenize_fn = getattr(cls, cls._TOKENIZE_FN[tokenize])
        return cls._lower(tokenize_fn(line), lowercase).split()

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for _re, repl in cls._REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        cp = ord(uchar)
        return any(start <= cp <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        line = line.replace("<skipped>", "")
        line = line.replace("-\n", "")
        line = line.replace("\n", " ")
        if "&" in line:
            line = line.replace("&quot;", '"')
            line = line.replace("&amp;", "&")
            line = line.replace("&lt;", "<")
            line = line.replace("&gt;", ">")
        return cls._tokenize_regex(f" {line} ")

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        line = line.strip()
        line_in_chars = ""
        for char in line:
            if cls._is_chinese_char(char):
                line_in_chars += f" {char} "
            else:
                line_in_chars += char
        return cls._tokenize_regex(line_in_chars)

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        if not _REGEX_AVAILABLE:
            raise ModuleNotFoundError("`intl` tokenizer requires the `regex` package: pip install regex")
        for _re, repl in cls._INT_REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(char for char in line)

    @classmethod
    def _tokenize_ja_mecab(cls, line: str) -> str:
        try:
            import ipadic
            import MeCab
        except ImportError as err:
            raise ModuleNotFoundError("`ja-mecab` tokenizer requires mecab-python3 and ipadic.") from err
        tagger = MeCab.Tagger(ipadic.MECAB_ARGS + " -Owakati")
        return tagger.parse(line.strip()).strip()

    @classmethod
    def _tokenize_ko_mecab(cls, line: str) -> str:
        try:
            import mecab_ko
            import mecab_ko_dic
        except ImportError as err:
            raise ModuleNotFoundError("`ko-mecab` tokenizer requires mecab_ko and mecab_ko_dic.") from err
        tagger = mecab_ko.Tagger(mecab_ko_dic.MECAB_ARGS + " -Owakati")
        return tagger.parse(line.strip()).strip()

    @staticmethod
    def _lower(line: str, lowercase: bool) -> str:
        return line.lower() if lowercase else line

    @classmethod
    def _check_tokenizers_validity(cls, tokenize: str) -> None:
        if tokenize not in cls._TOKENIZE_FN:
            raise ValueError(f"Argument `tokenize` expected to be one of {list(cls._TOKENIZE_FN)} but got {tokenize}.")


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """SacreBLEU score (reference ``sacre_bleu.py:434-532``)."""
    if tokenize not in AVAILABLE_TOKENIZERS:
        raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    preds_len = jnp.asarray(0.0)
    target_len = jnp.asarray(0.0)
    tokenize_fn = _SacreBLEUTokenizer(tokenize, lowercase)
    numerator, denominator, preds_len, target_len = _bleu_score_update(
        preds, target, numerator, denominator, preds_len, target_len, n_gram, tokenize_fn
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
