# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Perplexity (reference ``src/torchmetrics/functional/text/perplexity.py``).

Fully jnp — the one text metric whose hot path belongs on the TPU (log-probs
over a [B, T, V] logits tensor).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _check_shape_and_type_consistency(preds: Array, target: Array) -> None:
    """Validate [B, T, V] logits vs [B, T] targets (reference ``:21-60``)."""
    if preds.ndim != 3:
        raise ValueError(
            "Input tensor `preds` is expected to have 3 dimensions, [batch_size, seq_len, vocab_size],"
            f" but got {preds.ndim}."
        )
    if target.ndim != 2:
        raise ValueError(
            f"Input tensor `target` is expected to have 2 dimensions, [batch_size, seq_len], but got {target.ndim}."
        )
    if preds.shape[:2] != target.shape:
        raise ValueError(
            "Input tensors `preds` and `target` are expected to have equaling first two dimensions,"
            f" [batch_size, seq_len], but got {preds.shape[:2]} and {target.shape}."
        )
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise TypeError(f"Input tensor `preds` is expected to be of floating point type but got {preds.dtype}.")
    if not jnp.issubdtype(target.dtype, jnp.integer):
        raise TypeError(f"Input tensor `target` is expected to be of integer type but got {target.dtype}.")


def _perplexity_update(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Tuple[Array, Array]:
    """Summed -log p(target) + token count (reference ``:63-96``), via
    log-softmax gather (no explicit softmax materialization)."""
    _check_shape_and_type_consistency(preds, target)
    log_probs = jax.nn.log_softmax(preds.reshape(-1, preds.shape[-1]), axis=-1)
    target = target.reshape(-1)
    if ignore_index is not None:
        mask = target != ignore_index
        target = jnp.where(mask, target, 0)
    else:
        mask = jnp.ones_like(target, dtype=bool)
    token_log_probs = jnp.take_along_axis(log_probs, target[:, None], axis=1).squeeze(1)
    total_log_probs = -jnp.where(mask, token_log_probs, 0.0).sum()
    count = mask.sum()
    return total_log_probs, count


def _perplexity_compute(total: Array, count: Array) -> Array:
    """exp(mean -log p) (reference ``:99-110``)."""
    return jnp.exp(total / count)


def perplexity(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Array:
    """Perplexity of a language model (reference ``:113-140``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    total, count = _perplexity_update(preds, target, ignore_index)
    return _perplexity_compute(total, count)
