# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Translation edit rate (reference ``functional/text/ter.py:531``).

Implements the published Tercom algorithm (Snover et al. 2006) the way
sacrebleu's ``lib_ter`` specifies it: greedy best-shift search on the
hypothesis over a cached word-level Levenshtein distance against the
reference, ``TER = (shifts + edits) / avg reference length``. The inner
Levenshtein rows are computed with vectorized numpy recurrences rather than
the reference's per-cell Python loops; the trace/alignment semantics (op
preference sub > hyp-deletion > insertion on ties) match Tercom so shift
candidates rank identically.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# Tercom-inspired limits (same constants as sacrebleu / reference ``ter.py:20-25``)
_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000

_ASIAN_PUNCT = r"([、。〈-】〔-〟｡-･・])"
_FULL_WIDTH_PUNCT = r"([．，？：；！＂（）])"


class _TercomTokenizer:
    """Tercom normalizer (reference ``ter.py:57-188``; rules from
    jhclark/tercom ``Normalizer.java`` as published via sacrebleu)."""

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general_and_western(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)
            if self.asian_support:
                sentence = re.sub(_ASIAN_PUNCT, "", sentence)
                sentence = re.sub(_FULL_WIDTH_PUNCT, "", sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general_and_western(sentence: str) -> str:
        sentence = re.sub(r"\n-", "", sentence)
        sentence = re.sub(r"\n", " ", sentence)
        sentence = re.sub(r"&quot;", '"', sentence)
        sentence = re.sub(r"&amp;", "&", sentence)
        sentence = re.sub(r"&lt;", "<", sentence)
        sentence = re.sub(r"&gt;", ">", sentence)
        sentence = f" {sentence} "
        sentence = re.sub(r"([{-~[-` -&(-+:-@/])", r" \1 ", sentence)
        sentence = re.sub(r"'s ", r" 's ", sentence)
        sentence = re.sub(r"'s$", r" 's", sentence)
        sentence = re.sub(r"([^0-9])([\.,])", r"\1 \2 ", sentence)
        sentence = re.sub(r"([\.,])([^0-9])", r" \1 \2", sentence)
        sentence = re.sub(r"([0-9])(-)", r"\1 \2 ", sentence)
        return sentence

    @staticmethod
    def _normalize_asian(sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(_ASIAN_PUNCT, r" \1 ", sentence)
        sentence = re.sub(_FULL_WIDTH_PUNCT, r" \1 ", sentence)
        return sentence


# DP op codes: 0 = match/sub (diagonal), 1 = hyp word dropped (up),
# 2 = ref word inserted (left). Tie preference follows Tercom: diag, up, left.
_OP_DIAG, _OP_UP, _OP_LEFT = 0, 1, 2


def _levenshtein_with_alignment(
    hyp: List[str], ref: List[str]
) -> Tuple[int, Dict[int, int], List[int], List[int]]:
    """Word Levenshtein + Tercom-style alignment of ref positions to hyp.

    Returns ``(distance, align, ref_errors, hyp_errors)`` where ``align``
    maps each reference index to the hyp index it is aligned with (the
    current hyp position for insertions), matching sacrebleu's
    ``trace_to_alignment`` of the flipped trace.
    """
    n_h, n_r = len(hyp), len(ref)
    # cost matrix computed row-wise with numpy; ops tracked for backtrace
    dist = np.zeros((n_h + 1, n_r + 1), dtype=np.int64)
    ops = np.zeros((n_h + 1, n_r + 1), dtype=np.int8)
    dist[0, :] = np.arange(n_r + 1)
    ops[0, 1:] = _OP_LEFT
    dist[1:, 0] = np.arange(1, n_h + 1)
    ops[1:, 0] = _OP_UP
    ref_arr = np.asarray(ref, dtype=object)
    offsets = np.arange(n_r + 1)
    for i in range(1, n_h + 1):
        sub_cost = (ref_arr != hyp[i - 1]).astype(np.int64)
        prev = dist[i - 1]
        # strictly-better preference order: diagonal, up, left (Tercom)
        base = prev[:-1] + sub_cost
        op_row = np.zeros(n_r, dtype=np.int8)
        up = prev[1:] + 1
        better_up = up < base
        base = np.where(better_up, up, base)
        op_row = np.where(better_up, _OP_UP, op_row)
        # the left-neighbour dependency row[j] = min(b[j], row[j-1] + 1) is a
        # prefix scan: row[j] = j + cummin(b[k] - k), with b[0] = boundary i
        b = np.concatenate([[i], base])
        row_full = offsets + np.minimum.accumulate(b - offsets)
        from_left = row_full[1:] < base
        op_row = np.where(from_left, _OP_LEFT, op_row)
        dist[i] = row_full
        ops[i, 1:] = op_row
    # backtrace -> alignment
    align: Dict[int, int] = {}
    ref_err: List[int] = []
    hyp_err: List[int] = []
    trace: List[int] = []
    i, j = n_h, n_r
    while i > 0 or j > 0:
        op = ops[i, j]
        trace.append(op)
        if op == _OP_DIAG:
            i -= 1
            j -= 1
        elif op == _OP_UP:
            i -= 1
        else:
            j -= 1
    pos_hyp, pos_ref = -1, -1
    for op in reversed(trace):
        if op == _OP_DIAG:
            pos_hyp += 1
            pos_ref += 1
            align[pos_ref] = pos_hyp
            err = int(hyp[pos_hyp] != ref[pos_ref])
            hyp_err.append(err)
            ref_err.append(err)
        elif op == _OP_UP:
            pos_hyp += 1
            hyp_err.append(1)
        else:
            pos_ref += 1
            align[pos_ref] = pos_hyp
            ref_err.append(1)
    return int(dist[n_h, n_r]), align, ref_err, hyp_err


def _edit_distance_only(hyp: List[str], ref: List[str]) -> int:
    """Plain word-level Levenshtein distance (vectorized rows)."""
    n_r = len(ref)
    prev = np.arange(n_r + 1, dtype=np.int64)
    ref_arr = np.asarray(ref, dtype=object)
    offsets = np.arange(n_r + 1)
    for i, h in enumerate(hyp, start=1):
        base = np.minimum(prev[:-1] + (ref_arr != h), prev[1:] + 1)
        b = np.concatenate([[i], base])
        prev = offsets + np.minimum.accumulate(b - offsets)
    return int(prev[-1])


def _find_shifted_pairs(hyp: List[str], ref: List[str]):
    """Matching word sub-sequences eligible for shifting (reference
    ``ter.py:205-241``)."""
    for hyp_start in range(len(hyp)):
        for ref_start in range(len(ref)):
            if abs(ref_start - hyp_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if hyp[hyp_start + length - 1] != ref[ref_start + length - 1]:
                    break
                yield hyp_start, ref_start, length
                if len(hyp) == hyp_start + length or len(ref) == ref_start + length:
                    break


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move ``words[start:start+length]`` to position ``target`` (reference
    ``ter.py:278-309``)."""
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    return (
        words[:start]
        + words[start + length : length + target]
        + words[start : start + length]
        + words[length + target :]
    )


def _best_shift(
    hyp: List[str], ref: List[str], base_distance: int, checked_candidates: int
) -> Tuple[int, List[str], int]:
    """One round of greedy shift search (reference ``ter.py:312-391``)."""
    _, align, ref_err, hyp_err = _levenshtein_with_alignment(hyp, ref)
    best: Optional[Tuple] = None
    for hyp_start, ref_start, length in _find_shifted_pairs(hyp, ref):
        # skip if the hypothesis span is already correct, the reference span
        # already matches, or the shift would land within the span itself
        if sum(hyp_err[hyp_start : hyp_start + length]) == 0:
            continue
        if sum(ref_err[ref_start : ref_start + length]) == 0:
            continue
        if hyp_start <= align[ref_start] < hyp_start + length:
            continue
        prev_idx = -1
        for offset in range(-1, length):
            if ref_start + offset == -1:
                idx = 0
            elif ref_start + offset in align:
                idx = align[ref_start + offset] + 1
            else:
                break
            if idx == prev_idx:
                continue
            prev_idx = idx
            shifted = _perform_shift(hyp, hyp_start, length, idx)
            candidate = (
                base_distance - _edit_distance_only(shifted, ref),
                length,
                -hyp_start,
                -idx,
                shifted,
            )
            checked_candidates += 1
            if best is None or candidate > best:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break
    if best is None:
        return 0, hyp, checked_candidates
    return best[0], best[4], checked_candidates


def _sentence_num_edits(hyp: List[str], ref: List[str]) -> int:
    """Shifts + residual edit distance for one (hyp, ref) pair (reference
    ``ter.py:393-425``; sacrebleu ``translation_edit_rate``)."""
    if len(ref) == 0:
        return len(hyp)
    num_shifts = 0
    checked_candidates = 0
    words = list(hyp)
    while True:
        base_distance = _edit_distance_only(words, ref)
        delta, new_words, checked_candidates = _best_shift(words, ref, base_distance, checked_candidates)
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        words = new_words
    return num_shifts + _edit_distance_only(words, ref)


def _compute_sentence_statistics(
    hyp_words: List[str], ref_words: List[List[str]]
) -> Tuple[float, float]:
    """Best edit count over references + average reference length (reference
    ``ter.py:428-452``; hypothesis/reference order follows sacrebleu)."""
    total_ref_len = 0.0
    best_num_edits = float("inf")
    for ref in ref_words:
        total_ref_len += len(ref)
        num_edits = _sentence_num_edits(hyp_words, ref)
        if num_edits < best_num_edits:
            best_num_edits = num_edits
    return best_num_edits, total_ref_len / len(ref_words)


def _compute_ter_score_from_statistics(num_edits, tgt_length):
    """Score with empty-reference conventions (reference ``ter.py:455-470``)."""
    num_edits = jnp.asarray(num_edits, jnp.float32)
    tgt_length = jnp.asarray(tgt_length, jnp.float32)
    return jnp.where(
        (tgt_length > 0) & (num_edits > 0),
        num_edits / jnp.maximum(tgt_length, 1e-16),
        jnp.where((tgt_length == 0) & (num_edits > 0), 1.0, 0.0),
    )


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
) -> Tuple[float, float, List[float]]:
    """Corpus statistics + sentence scores (reference ``ter.py:473-515``)."""
    if isinstance(preds, str):
        preds = [preds]
    target = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    total_num_edits = 0.0
    total_tgt_length = 0.0
    sentence_ter: List[float] = []
    for pred, tgt in zip(preds, target):
        tgt_words = [tokenizer(t).split() for t in tgt]
        pred_words = tokenizer(pred).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words, tgt_words)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        sentence_ter.append(float(_compute_ter_score_from_statistics(num_edits, tgt_length)))
    return total_num_edits, total_tgt_length, sentence_ter


def _ter_compute(total_num_edits, total_tgt_length) -> Array:
    """Corpus TER (reference ``ter.py:517-528``)."""
    return _compute_ter_score_from_statistics(total_num_edits, total_tgt_length)


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
):
    """Translation edit rate (reference ``ter.py:531-597``)."""
    if not isinstance(normalize, bool):
        raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
    if not isinstance(no_punctuation, bool):
        raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
    if not isinstance(lowercase, bool):
        raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
    if not isinstance(asian_support, bool):
        raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")
    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(preds, target, tokenizer)
    ter = _ter_compute(total_num_edits, total_tgt_length)
    if return_sentence_level_score:
        return ter, jnp.asarray(sentence_ter)
    return ter
