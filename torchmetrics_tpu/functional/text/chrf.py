# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""chrF / chrF++ score (reference ``src/torchmetrics/functional/text/chrf.py``).

Counting runs host-side (string work); the accumulated totals are per-order
count vectors — clean ``"sum"``-reducible metric states.
"""
from __future__ import annotations

from collections import Counter
from itertools import chain
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_EPS_SMOOTHING = 1e-16
_PUNCTUATIONS = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    """Characters of the sentence (reference ``chrf.py:70-83``)."""
    if whitespace:
        return list(sentence)
    return list(sentence.strip().replace(" ", ""))


def _separate_word_and_punctuation(word: str) -> List[str]:
    """Split leading/trailing punctuation (reference ``chrf.py:86-106``)."""
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _get_words_and_punctuation(sentence: str) -> List[str]:
    """Words with separated punctuation (reference ``chrf.py:109-119``)."""
    return list(chain.from_iterable(_separate_word_and_punctuation(word) for word in sentence.strip().split()))


def _ngram_counts(char_or_word_list: List[str], n_gram_order: int) -> Dict[int, Counter]:
    """Counter of n-grams per order (reference ``chrf.py:122-137``)."""
    ngrams: Dict[int, Counter] = {}
    for n in range(1, n_gram_order + 1):
        ngrams[n] = Counter(tuple(char_or_word_list[i : i + n]) for i in range(len(char_or_word_list) - n + 1))
    return ngrams


def _sentence_counts(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[Dict[int, Counter], Dict[int, Counter]]:
    """Char and word n-gram counters of one sentence (reference ``chrf.py:140-188``)."""
    if lowercase:
        sentence = sentence.lower()
    char_counts = _ngram_counts(_get_characters(sentence, whitespace), n_char_order)
    word_counts = _ngram_counts(_get_words_and_punctuation(sentence), n_word_order)
    return char_counts, word_counts


def _matching_counts(pred: Dict[int, Counter], ref: Dict[int, Counter]) -> Dict[int, float]:
    """Clipped matches per order (reference ``chrf.py:191-211``)."""
    return {n: float(sum((pred.get(n, Counter()) & ref.get(n, Counter())).values())) for n in pred}


def _totals(counts: Dict[int, Counter]) -> Dict[int, float]:
    return {n: float(sum(c.values())) for n, c in counts.items()}


def _fscore_from_totals(
    matching: np.ndarray, ref_total: np.ndarray, hyp_total: np.ndarray, beta: float
) -> np.ndarray:
    """Per-order F-beta with eps smoothing (reference ``chrf.py:230-284``)."""
    precision = np.where(hyp_total > 0, matching / np.maximum(hyp_total, 1), 0.0)
    recall = np.where(ref_total > 0, matching / np.maximum(ref_total, 1), 0.0)
    denominator = np.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
    return (1 + beta**2) * precision * recall / denominator


def _sentence_chrf(
    pred_char: Dict[int, Counter],
    pred_word: Dict[int, Counter],
    targets: Sequence[str],
    n_char_order: int,
    n_word_order: int,
    beta: float,
    lowercase: bool,
    whitespace: bool,
) -> Tuple[float, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Best-reference sentence chrF + that reference's counts (reference
    ``chrf.py:287-370``)."""
    n_order = float(n_char_order + n_word_order)
    pred_char_total = np.array([_totals(pred_char).get(n, 0.0) for n in range(1, n_char_order + 1)])
    pred_word_total = np.array([_totals(pred_word).get(n, 0.0) for n in range(1, n_word_order + 1)])

    best = (-1.0, None)
    for tgt in targets:
        t_char, t_word = _sentence_counts(tgt, n_char_order, n_word_order, lowercase, whitespace)
        m_char = np.array([_matching_counts(pred_char, t_char).get(n, 0.0) for n in range(1, n_char_order + 1)])
        m_word = np.array([_matching_counts(pred_word, t_word).get(n, 0.0) for n in range(1, n_word_order + 1)])
        t_char_total = np.array([_totals(t_char).get(n, 0.0) for n in range(1, n_char_order + 1)])
        t_word_total = np.array([_totals(t_word).get(n, 0.0) for n in range(1, n_word_order + 1)])
        f_char = _fscore_from_totals(m_char, t_char_total, pred_char_total, beta)
        f_word = _fscore_from_totals(m_word, t_word_total, pred_word_total, beta)
        score = float((f_char.sum() + f_word.sum()) / n_order)
        if score > best[0]:
            best = (score, (m_char, m_word, t_char_total, t_word_total))
    score, (m_char, m_word, t_char_total, t_word_total) = best
    return score, m_char, m_word, t_char_total, t_word_total


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int,
    n_word_order: int,
    beta: float,
    lowercase: bool,
    whitespace: bool,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, List[float]]:
    """Accumulate corpus totals; returns the six per-order count vectors
    plus sentence-level scores (reference ``chrf.py:373-480``)."""
    if isinstance(preds, str):
        preds = [preds]
    target = [[t] if isinstance(t, str) else t for t in target]
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")

    tot_p_char = np.zeros(n_char_order)
    tot_p_word = np.zeros(n_word_order)
    tot_t_char = np.zeros(n_char_order)
    tot_t_word = np.zeros(n_word_order)
    tot_m_char = np.zeros(n_char_order)
    tot_m_word = np.zeros(n_word_order)
    sentence_scores: List[float] = []
    for pred, targets in zip(preds, target):
        p_char, p_word = _sentence_counts(pred, n_char_order, n_word_order, lowercase, whitespace)
        tot_p_char += np.array([_totals(p_char).get(n, 0.0) for n in range(1, n_char_order + 1)])
        tot_p_word += np.array([_totals(p_word).get(n, 0.0) for n in range(1, n_word_order + 1)])
        score, m_char, m_word, t_char_total, t_word_total = _sentence_chrf(
            p_char, p_word, targets, n_char_order, n_word_order, beta, lowercase, whitespace
        )
        sentence_scores.append(score)
        tot_m_char += m_char
        tot_m_word += m_word
        tot_t_char += t_char_total
        tot_t_word += t_word_total
    return tot_p_char, tot_p_word, tot_t_char, tot_t_word, tot_m_char, tot_m_word, sentence_scores


def _chrf_score_compute(
    tot_p_char: np.ndarray,
    tot_p_word: np.ndarray,
    tot_t_char: np.ndarray,
    tot_t_word: np.ndarray,
    tot_m_char: np.ndarray,
    tot_m_word: np.ndarray,
    beta: float,
) -> Array:
    """Corpus chrF from totals (reference ``chrf.py:483-520``)."""
    f_char = _fscore_from_totals(np.asarray(tot_m_char), np.asarray(tot_t_char), np.asarray(tot_p_char), beta)
    f_word = _fscore_from_totals(np.asarray(tot_m_word), np.asarray(tot_t_word), np.asarray(tot_p_word), beta)
    n_order = len(f_char) + len(f_word)
    return jnp.asarray((f_char.sum() + f_word.sum()) / n_order, dtype=jnp.float32)


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """chrF/chrF++ score (reference ``chrf.py:523-637``)."""
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")

    *totals, sentence_scores = _chrf_score_update(
        preds, target, n_char_order, n_word_order, beta, lowercase, whitespace
    )
    score = _chrf_score_compute(*totals, beta)
    if return_sentence_level_score:
        return score, jnp.asarray(sentence_scores, dtype=jnp.float32)
    return score
