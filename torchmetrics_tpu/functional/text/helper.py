# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Text helpers: edit distance and n-gram counting (reference
``src/torchmetrics/functional/text/helper.py``).

String processing is inherently host-side scalar work; these helpers stay in
Python/numpy and feed scalar counts into device-resident metric states.
"""
from __future__ import annotations

from collections import Counter
from typing import List, Sequence, Tuple, Union

import numpy as np


def _edit_distance(prediction_tokens: Sequence, reference_tokens: Sequence, substitution_cost: int = 1) -> int:
    """Levenshtein distance between token sequences (reference ``helper.py:34-51``),
    vectorized row-wise in numpy (the DP recurrence stays, the inner loop goes)."""
    m, n = len(prediction_tokens), len(reference_tokens)
    if m == 0:
        return n
    if n == 0:
        return m
    ref = np.array([hash(t) for t in reference_tokens])
    prev = np.arange(n + 1)
    idx = np.arange(n + 1)
    for i, p_tok in enumerate(prediction_tokens, start=1):
        sub = prev[:-1] + np.where(ref == hash(p_tok), 0, substitution_cost)
        delete = prev[1:] + 1
        best = np.minimum(sub, delete)
        # fold the sequential insertion recurrence cur[j] = min(best[j], cur[j-1]+1)
        # via e[j] = cur[j] - j  =>  e[j] = min(best[j] - j, e[j-1]), a prefix min
        e = np.minimum.accumulate(np.concatenate(([i], best - idx[1:])))
        prev = e + idx
    return int(prev[n])


def _batch_edit_distance(
    pred_seqs: Sequence[Sequence], target_seqs: Sequence[Sequence], substitution_cost: int = 1
) -> np.ndarray:
    """Edit distance for every (pred, target) pair at once.

    Tokens are interned to consecutive integer ids (exact equality — no hash
    collisions), then the whole batch runs through the native C++ DP kernel
    (``native/edit_distance.cpp``, OpenMP over pairs). Falls back to the
    per-pair numpy recurrence when no compiler is available.
    """
    from torchmetrics_tpu.native import get_edit_library

    if len(pred_seqs) != len(target_seqs):
        raise ValueError(
            f"Expected `pred_seqs` and `target_seqs` to have same length, got {len(pred_seqs)} and {len(target_seqs)}"
        )
    lib = get_edit_library()
    if lib is None:
        return np.array(
            [_edit_distance(p, t, substitution_cost) for p, t in zip(pred_seqs, target_seqs)],
            dtype=np.int64,
        )

    vocab: dict = {}

    def intern(seq):
        return [vocab.setdefault(tok, len(vocab)) for tok in seq]

    pred_ids = [intern(s) for s in pred_seqs]
    tgt_ids = [intern(s) for s in target_seqs]
    pred_flat = np.array([i for s in pred_ids for i in s], dtype=np.uint64)
    tgt_flat = np.array([i for s in tgt_ids for i in s], dtype=np.uint64)
    pred_off = np.concatenate(([0], np.cumsum([len(s) for s in pred_ids]))).astype(np.int64)
    tgt_off = np.concatenate(([0], np.cumsum([len(s) for s in tgt_ids]))).astype(np.int64)
    out = np.empty(len(pred_ids), dtype=np.int64)
    lib.batch_edit_distance(
        pred_flat.ctypes.data, pred_off.ctypes.data,
        tgt_flat.ctypes.data, tgt_off.ctypes.data,
        len(pred_ids), substitution_cost, out.ctypes.data,
    )
    return out


def _count_ngram(ngram_input_list: Sequence[str], n_gram: int) -> Counter:
    """All n-grams up to ``n_gram`` (reference ``bleu.py:25-41``)."""
    ngram_counter: Counter = Counter()
    for i in range(1, n_gram + 1):
        for j in range(len(ngram_input_list) - i + 1):
            ngram_counter[tuple(ngram_input_list[j : i + j])] += 1
    return ngram_counter


def _normalize_inputs(
    preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]
) -> Tuple[List[str], List[str]]:
    """Promote single strings to lists and validate pairing.

    Deliberate divergence: the reference's WER/CER/MER/WIL/WIP silently
    ``zip``-truncate mismatched preds/target lists to the shorter one; here a
    length mismatch raises, since truncation silently discards data. Tested in
    ``tests/unittests/bases/test_collections.py``
    (``test_text_error_rates_reject_mismatched_lengths``).
    """
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError(
            f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
        )
    return list(preds), list(target)
