# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""ROUGE score (reference ``src/torchmetrics/functional/text/rouge.py``)."""
from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1,
    "rouge2": 2,
    "rouge3": 3,
    "rouge4": 4,
    "rouge5": 5,
    "rouge6": 6,
    "rouge7": 7,
    "rouge8": 8,
    "rouge9": 9,
    "rougeL": "L",
    "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")


def _split_sentence(x: str) -> Sequence[str]:
    """Sentence split for rougeLsum (reference ``rouge.py:62-71``); uses nltk
    punkt when available, a punctuation-regex fallback otherwise."""
    try:
        import nltk

        try:
            return nltk.sent_tokenize(x)
        except LookupError:
            pass
    except ImportError:
        pass
    re_split = re.split(r"(?<=[.!?])\s+", x.strip())
    return [s for s in re_split if s]


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, Array]:
    """precision/recall/fmeasure triple (reference ``rouge.py:74-92``)."""
    precision = hits_or_lcs / pred_len if pred_len > 0 else 0.0
    recall = hits_or_lcs / target_len if target_len > 0 else 0.0
    if precision == recall == 0.0:
        return {"precision": jnp.asarray(0.0), "recall": jnp.asarray(0.0), "fmeasure": jnp.asarray(0.0)}
    fmeasure = 2 * precision * recall / (precision + recall)
    return {
        "precision": jnp.asarray(precision, jnp.float32),
        "recall": jnp.asarray(recall, jnp.float32),
        "fmeasure": jnp.asarray(fmeasure, jnp.float32),
    }


def _lcs(pred_tokens: Sequence[str], target_tokens: Sequence[str], return_full_table: bool = False):
    """Longest common subsequence DP (reference ``rouge.py:95-115``), with the
    row recurrence vectorized in numpy."""
    m, n = len(pred_tokens), len(target_tokens)
    table = np.zeros((n + 1, m + 1), dtype=np.int64)
    if m and n:
        pred_arr = np.array([hash(t) for t in pred_tokens])
        for i in range(1, n + 1):
            match = pred_arr == hash(target_tokens[i - 1])
            prev = table[i - 1]
            row = np.where(match, prev[:-1] + 1, 0)
            # running max fold: table[i][j] = max(row[j], table[i-1][j], table[i][j-1])
            cur = np.maximum(row, prev[1:])
            table[i, 1:] = np.maximum.accumulate(cur)
    if return_full_table:
        return table
    return int(table[-1, -1])


def _backtracked_lcs(lcs_table, pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> Sequence[int]:
    """Indices of target tokens on the LCS path (reference ``rouge.py:118-141``)."""
    i = len(pred_tokens)
    j = len(target_tokens)
    backtracked: List[int] = []
    while i > 0 and j > 0:
        if pred_tokens[i - 1] == target_tokens[j - 1]:
            backtracked.insert(0, j - 1)
            i -= 1
            j -= 1
        elif lcs_table[j][i - 1] > lcs_table[j - 1][i]:
            i -= 1
        else:
            j -= 1
    return backtracked


def _union_lcs(pred_tokens_list: Sequence[Sequence[str]], target_tokens: Sequence[str]) -> Sequence[str]:
    """Union of per-sentence LCS indices (reference ``rouge.py:144-163``)."""

    def lcs_ind(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> Sequence[int]:
        lcs_table = _lcs(pred_tokens, target_tokens, return_full_table=True)
        return _backtracked_lcs(lcs_table, pred_tokens, target_tokens)

    lcs_union: set = set()
    for pred_tokens in pred_tokens_list:
        lcs_union = lcs_union.union(lcs_ind(pred_tokens, target_tokens))
    return [target_tokens[i] for i in sorted(lcs_union)]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Sequence[str]:
    """rouge-score-style normalization + tokenization (reference ``rouge.py:166-199``)."""
    if normalizer is not None:
        text = normalizer(text)
    else:
        text = re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if tokenizer is not None else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and len(x) > 0)]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, Array]:
    """ROUGE-N (reference ``rouge.py:202-225``)."""

    def _create_ngrams(tokens: Sequence[str], n: int) -> Counter:
        return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))

    pred_ngrams, target_ngrams = _create_ngrams(pred, n_gram), _create_ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return {"precision": jnp.asarray(0.0), "recall": jnp.asarray(0.0), "fmeasure": jnp.asarray(0.0)}
    hits = sum(min(pred_ngrams[w], target_ngrams[w]) for w in set(pred_ngrams) & set(target_ngrams))
    return _compute_metrics(hits, max(pred_len, 1), max(target_len, 1))


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, Array]:
    """ROUGE-L (reference ``rouge.py:228-241``)."""
    if 0 in (len(pred), len(target)):
        return {"precision": jnp.asarray(0.0), "recall": jnp.asarray(0.0), "fmeasure": jnp.asarray(0.0)}
    lcs = _lcs(pred, target)
    return _compute_metrics(lcs, len(pred), len(target))


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, Array]:
    """ROUGE-Lsum over sentence splits (reference ``rouge.py:244-284``)."""
    if 0 in (len(pred), len(target)):
        return {"precision": jnp.asarray(0.0), "recall": jnp.asarray(0.0), "fmeasure": jnp.asarray(0.0)}

    def _get_token_counts(sentences: Sequence[Sequence[str]]) -> Counter:
        ngrams: Counter = Counter()
        for sentence in sentences:
            ngrams.update(sentence)
        return ngrams

    pred_tokens_count = _get_token_counts(pred)
    target_tokens_count = _get_token_counts(target)
    hits = 0
    for tgt in target:
        lcs_words = _union_lcs(pred, tgt)
        for w in lcs_words:
            if pred_tokens_count[w] > 0 and target_tokens_count[w] > 0:
                hits += 1
                pred_tokens_count[w] -= 1
                target_tokens_count[w] -= 1
    return _compute_metrics(hits, sum(len(s) for s in pred), sum(len(s) for s in target))


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, Array]]]:
    """Per-pair ROUGE with best/avg multi-reference accumulation (reference
    ``rouge.py:287-390``)."""
    results: Dict[Union[int, str], List[Dict[str, Array]]] = {rouge_key: [] for rouge_key in rouge_keys_values}

    for pred_raw, target_raw in zip(preds, target):
        result_inner: Dict[Union[int, str], Dict[str, Array]] = {}
        result_avg: Dict[Union[int, str], List[Dict[str, Array]]] = {rouge_key: [] for rouge_key in rouge_keys_values}
        list_results = []
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        pred_lsum = None
        if "Lsum" in rouge_keys_values:
            pred_lsum = [
                _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer) for s in _split_sentence(pred_raw)
            ]

        for target_raw_inner in target_raw:
            tgt = _normalize_and_tokenize_text(target_raw_inner, stemmer, normalizer, tokenizer)
            if "Lsum" in rouge_keys_values:
                target_lsum = [
                    _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                    for s in _split_sentence(target_raw_inner)
                ]
            for rouge_key in rouge_keys_values:
                if isinstance(rouge_key, int):
                    score = _rouge_n_score(pred, tgt, rouge_key)
                elif rouge_key == "L":
                    score = _rouge_l_score(pred, tgt)
                else:  # Lsum
                    score = _rouge_lsum_score(pred_lsum, target_lsum)
                result_inner[rouge_key] = score
                result_avg[rouge_key].append(score)
            list_results.append(result_inner.copy())

        if accumulate == "best":
            key_curr = rouge_keys_values[0]
            all_fmeasure = np.array([float(v[key_curr]["fmeasure"]) for v in list_results])
            highest_idx = int(np.argmax(all_fmeasure))
            for rouge_key in rouge_keys_values:
                results[rouge_key].append(list_results[highest_idx][rouge_key])
        else:  # avg
            for rouge_key, metrics in result_avg.items():
                avg = {
                    t: jnp.mean(jnp.stack([m[t] for m in metrics])) for t in ("fmeasure", "precision", "recall")
                }
                results[rouge_key].append(avg)
    return results


def _rouge_score_compute(sentence_results: Dict[str, List[Array]]) -> Dict[str, Array]:
    """Mean over samples (reference ``rouge.py:393-408``)."""
    results: Dict[str, Array] = {}
    for rouge_key, scores in sentence_results.items():
        results[rouge_key] = jnp.mean(jnp.stack(scores)) if scores else jnp.asarray(0.0)
    return results


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE score (reference ``rouge.py:411-515``)."""
    if use_stemmer:
        try:
            import nltk

            stemmer = nltk.stem.porter.PorterStemmer()
        except ImportError as err:
            raise ModuleNotFoundError("Stemmer requires the nltk package: pip install nltk") from err
    else:
        stemmer = None

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate, stemmer, normalizer, tokenizer
    )
    output: Dict[str, List[Array]] = {}
    for rouge_key, metrics in sentence_results.items():
        for metric in metrics:
            for tp, value in metric.items():
                output.setdefault(f"rouge{rouge_key}_{tp}", []).append(value)
    return {name: jnp.mean(jnp.stack(vals)) for name, vals in output.items()}
