# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""COCO RLE mask utilities over the native codec.

Python API mirroring the pycocotools ``mask`` module the reference calls
(``detection/mean_ap.py:824-857``, SURVEY §2.6): ``encode``/``decode``/
``area``/``iou`` on dicts ``{"size": [h, w], "counts": np.uint32 runs}``.
Runs through the C++ codec when available, else vectorized numpy.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from torchmetrics_tpu.native import get_rle_library

RLE = Dict[str, object]


def _encode_numpy(flat: np.ndarray) -> np.ndarray:
    """Run lengths of a flat binary array, zeros first (vectorized numpy)."""
    flat = flat.astype(bool)
    change = np.nonzero(np.diff(flat))[0] + 1
    boundaries = np.concatenate([[0], change, [flat.size]])
    runs = np.diff(boundaries)
    if flat.size and flat[0]:
        runs = np.concatenate([[0], runs])
    return runs.astype(np.uint32)


def encode(mask: np.ndarray) -> RLE:
    """Encode an ``(H, W)`` binary mask (column-major runs, COCO convention)."""
    mask = np.asarray(mask)
    if mask.ndim != 2:
        raise ValueError(f"Expected a single (H, W) mask, got shape {mask.shape}")
    h, w = mask.shape
    flat = np.asfortranarray(mask.astype(np.uint8)).flatten(order="F")
    lib = get_rle_library()
    if lib is not None:
        buf = np.zeros(flat.size + 1, np.uint32)
        n = lib.rle_encode(
            flat.ctypes.data_as(ctypes.c_void_p), ctypes.c_uint64(flat.size), buf.ctypes.data_as(ctypes.c_void_p)
        )
        counts = buf[:n].copy()
    else:
        counts = _encode_numpy(flat)
    return {"size": [h, w], "counts": counts}


def decode(rle: RLE) -> np.ndarray:
    """Decode an RLE back into an ``(H, W)`` uint8 mask."""
    h, w = rle["size"]
    counts = np.asarray(rle["counts"], np.uint32)
    size = int(h) * int(w)
    lib = get_rle_library()
    if lib is not None:
        out = np.zeros(size, np.uint8)
        lib.rle_decode(
            counts.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint64(counts.size),
            out.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint64(size),
        )
    else:
        out = np.repeat(np.arange(counts.size) % 2, counts).astype(np.uint8)
        out = np.pad(out, (0, size - out.size)) if out.size < size else out[:size]
    return out.reshape((h, w), order="F")


def area(rles: Union[RLE, Sequence[RLE]]) -> np.ndarray:
    """Foreground areas of one or many RLEs."""
    single = isinstance(rles, dict)
    rle_list: List[RLE] = [rles] if single else list(rles)
    lib = get_rle_library()
    out = np.zeros(len(rle_list), np.float64)
    for i, r in enumerate(rle_list):
        counts = np.asarray(r["counts"], np.uint32)
        if lib is not None:
            out[i] = lib.rle_area(counts.ctypes.data_as(ctypes.c_void_p), ctypes.c_uint64(counts.size))
        else:
            out[i] = counts[1::2].sum()
    return out[0] if single else out


def to_bbox(rles: Union[RLE, Sequence[RLE]]) -> np.ndarray:
    """Tight ``[x, y, w, h]`` bounding box(es) of RLE mask(s) — the
    pycocotools ``rleToBbox`` rule: a foreground run spanning a column
    boundary covers the full mask height."""
    single = isinstance(rles, dict)
    out = []
    for r in [rles] if single else rles:
        h, _w = (int(v) for v in r["size"])
        cnts = np.asarray(r["counts"], np.int64)
        ends = np.cumsum(cnts)
        starts = ends - cnts
        s, e = starts[1::2], ends[1::2] - 1  # inclusive bounds of 1-runs
        if s.size == 0 or h == 0:
            out.append([0.0, 0.0, 0.0, 0.0])
            continue
        xs, xe = s // h, e // h
        spans = xe > xs
        ys = np.where(spans, 0, s % h)
        ye = np.where(spans, h - 1, e % h)
        x0, x1 = xs.min(), xe.max()
        y0, y1 = ys.min(), ye.max()
        out.append([float(x0), float(y0), float(x1 - x0 + 1), float(y1 - y0 + 1)])
    return np.asarray(out[0] if single else out, np.float64)


def iou(dt: Sequence[RLE], gt: Sequence[RLE], iscrowd: Optional[Sequence[int]] = None) -> np.ndarray:
    """Crowd-aware IoU matrix ``(len(dt), len(gt))`` between RLE sets."""
    dt, gt = list(dt), list(gt)
    n_dt, n_gt = len(dt), len(gt)
    crowd = np.asarray(iscrowd if iscrowd is not None else np.zeros(n_gt), np.uint8)
    if crowd.size != n_gt:
        raise ValueError(f"iscrowd must have one entry per gt, got {crowd.size} for {n_gt}")
    out = np.zeros((n_dt, n_gt), np.float64)
    if n_dt == 0 or n_gt == 0:
        return out
    lib = get_rle_library()
    if lib is not None:
        dt_runs = np.concatenate([np.asarray(r["counts"], np.uint32) for r in dt])
        dt_lengths = np.asarray([len(r["counts"]) for r in dt], np.uint64)
        dt_offsets = np.concatenate([[0], np.cumsum(dt_lengths)[:-1]]).astype(np.uint64)
        gt_runs = np.concatenate([np.asarray(r["counts"], np.uint32) for r in gt])
        gt_lengths = np.asarray([len(r["counts"]) for r in gt], np.uint64)
        gt_offsets = np.concatenate([[0], np.cumsum(gt_lengths)[:-1]]).astype(np.uint64)
        lib.rle_iou_matrix(
            dt_runs.ctypes.data_as(ctypes.c_void_p),
            dt_offsets.ctypes.data_as(ctypes.c_void_p),
            dt_lengths.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint64(n_dt),
            gt_runs.ctypes.data_as(ctypes.c_void_p),
            gt_offsets.ctypes.data_as(ctypes.c_void_p),
            gt_lengths.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint64(n_gt),
            crowd.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out
    # numpy fallback: decode and compare densely
    dt_masks = np.stack([decode(r).ravel() for r in dt]).astype(bool)
    gt_masks = np.stack([decode(r).ravel() for r in gt]).astype(bool)
    inter = dt_masks.astype(np.float64) @ gt_masks.T.astype(np.float64)
    area_d = dt_masks.sum(1)[:, None].astype(np.float64)
    area_g = gt_masks.sum(1)[None, :].astype(np.float64)
    union = np.where(crowd[None, :].astype(bool), area_d, area_d + area_g - inter)
    return np.where(union > 0, inter / np.maximum(union, 1), 0.0)


def rle_from_string(s: Union[str, bytes]) -> np.ndarray:
    """Decode COCO's compressed RLE ``counts`` string (the pycocotools
    ``rleFrString`` varint + delta coding) into plain run lengths."""
    if isinstance(s, bytes):
        s = s.decode("ascii")
    counts: List[int] = []
    i = 0
    while i < len(s):
        x = 0
        k = 0
        more = True
        while more:
            c = ord(s[i]) - 48
            x |= (c & 0x1F) << (5 * k)
            more = bool(c & 0x20)
            i += 1
            k += 1
            if not more and (c & 0x10):
                x |= -1 << (5 * k)
        if len(counts) > 2:
            x += counts[-2]
        counts.append(x)
    return np.asarray(counts, np.uint32)


def rle_to_string(counts: np.ndarray) -> str:
    """Encode run lengths into COCO's compressed ``counts`` string
    (pycocotools ``rleToString``)."""
    counts = np.asarray(counts, np.int64)
    out = []
    for i, x in enumerate(counts):
        x = int(x)
        if i > 2:
            x -= int(counts[i - 2])
        more = True
        while more:
            c = x & 0x1F
            x >>= 5
            more = not (x == -1 if (c & 0x10) else x == 0)
            if more:
                c |= 0x20
            out.append(chr(c + 48))
    return "".join(out)


def from_polygons(polygons: Sequence[Sequence[float]], h: int, w: int) -> RLE:
    """Rasterize COCO polygon segmentation(s) into one RLE (the pycocotools
    ``frPyObjects`` + ``merge`` path): each polygon is a flat
    ``[x0, y0, x1, y1, ...]`` list; multiple polygons union into one mask.
    Requires the native codec (the rasterization lives in C++)."""
    if not (isinstance(h, int) and isinstance(w, int) and h > 0 and w > 0):
        raise ValueError(f"Polygon rasterization needs positive integer image dims, got h={h}, w={w}")
    lib = get_rle_library()
    if lib is None:
        raise RuntimeError(
            "Polygon rasterization requires the native RLE codec (g++ unavailable?);"
            " convert polygons to RLE offline instead."
        )
    rles = []
    for poly in polygons:
        xy = np.asarray(poly, np.float64).reshape(-1)
        if xy.size < 6:
            continue  # degenerate polygon (< 3 vertices)
        buf = np.zeros(h * w + 2, np.uint32)
        n = lib.rle_from_polygon(
            xy.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_uint64(xy.size // 2),
            ctypes.c_uint64(h),
            ctypes.c_uint64(w),
            buf.ctypes.data_as(ctypes.c_void_p),
        )
        rles.append({"size": [h, w], "counts": buf[:n].copy()})
    if not rles:
        return {"size": [h, w], "counts": np.asarray([h * w], np.uint32)}
    if len(rles) == 1:
        return rles[0]
    return merge_union(rles)


def merge_union(rles: Sequence[RLE]) -> RLE:
    """Union of several same-size RLEs at the run level (pycocotools
    ``merge`` semantics) — no dense masks are materialized."""
    h, w = rles[0]["size"]
    size = int(h) * int(w)
    starts_list, ends_list = [], []
    for r in rles:
        if list(r["size"]) != [h, w]:
            raise ValueError("All RLEs must share the same size for merging")
        cum = np.concatenate([[0], np.cumsum(np.asarray(r["counts"], np.int64))])
        starts_list.append(cum[1:-1:2] if cum.size > 2 else cum[1:0])
        ends_list.append(cum[2::2])
    starts = np.concatenate([s for s in starts_list if s.size] or [np.zeros(0, np.int64)])
    ends = np.concatenate([e for e in ends_list if e.size] or [np.zeros(0, np.int64)])
    if starts.size == 0:
        return {"size": [h, w], "counts": np.asarray([size], np.uint32)}
    order = np.argsort(starts, kind="stable")
    starts, ends = starts[order], ends[order]
    # sweep-merge overlapping [start, end) intervals
    merged_s, merged_e = [int(starts[0])], [int(ends[0])]
    for s, e in zip(starts[1:], ends[1:]):
        if s <= merged_e[-1]:
            merged_e[-1] = max(merged_e[-1], int(e))
        else:
            merged_s.append(int(s))
            merged_e.append(int(e))
    counts = []
    pos = 0
    for s, e in zip(merged_s, merged_e):
        counts.append(s - pos)  # zeros run (may be 0 only for the first)
        counts.append(e - s)
        pos = e
    counts.append(size - pos)
    if counts[-1] == 0:
        counts.pop()
    return {"size": [h, w], "counts": np.asarray(counts, np.uint32)}
