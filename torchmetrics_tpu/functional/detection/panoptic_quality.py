# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Panoptic quality (reference ``functional/detection/panoptic_quality.py`` +
``_panoptic_quality_common.py``).

Design note: segment discovery is inherently dynamic-shape (the number of
``(category_id, instance_id)`` segments per image is data-dependent), so the
per-batch update runs on host with **vectorized** ``np.unique``/bincount —
no per-pixel Python loops — and produces fixed-size per-category
``iou_sum/tp/fp/fn`` states (reference ``_panoptic_quality_common.py:312-444``)
that accumulate on device and sync with ``"sum"`` collectives like any other
metric. The pixel-heavy work is one sort over the flattened image.
"""
from __future__ import annotations

from typing import Collection, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _parse_categories(things: Collection[int], stuffs: Collection[int]) -> Tuple[Set[int], Set[int]]:
    """Validate and normalize category id sets (reference ``:65-93``)."""
    things_parsed = set(things)
    stuffs_parsed = set(stuffs)
    if not all(isinstance(t, (int, np.integer)) for t in things_parsed):
        raise TypeError(f"Expected argument `things` to contain `int` categories, but got {things}")
    if not all(isinstance(s, (int, np.integer)) for s in stuffs_parsed):
        raise TypeError(f"Expected argument `stuffs` to contain `int` categories, but got {stuffs}")
    if things_parsed & stuffs_parsed:
        raise ValueError(
            f"Expected arguments `things` and `stuffs` to have distinct keys, but got {things} and {stuffs}"
        )
    if not (things_parsed | stuffs_parsed):
        raise ValueError("At least one of `things` and `stuffs` must be non-empty.")
    return things_parsed, stuffs_parsed


def _validate_inputs(preds, target) -> None:
    """Shape validation (reference ``:96-121``)."""
    preds, target = np.asarray(preds), np.asarray(target)
    if preds.shape != target.shape:
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same shape, but got {preds.shape} and {target.shape}"
        )
    if preds.ndim < 3:
        raise ValueError(
            "Expected argument `preds` to have at least one spatial dimension (B, *spatial_dims, 2),"
            f" got {preds.shape}"
        )
    if preds.shape[-1] != 2:
        raise ValueError(
            "Expected argument `preds` to have exactly 2 channels in the last dimension (category, instance),"
            f" got {preds.shape} instead"
        )


def _get_void_color(things: Set[int], stuffs: Set[int]) -> Tuple[int, int]:
    """An unused (category, instance) pair (reference ``:124-136``)."""
    unused_category_id = 1 + max([0, *things, *stuffs])
    return unused_category_id, 0


def _get_category_id_to_continuous_id(things: Set[int], stuffs: Set[int]) -> dict:
    """Things first, then stuffs, numerically sorted (reference ``:139-157``)."""
    thing_id_to_continuous_id = {t: i for i, t in enumerate(sorted(things))}
    stuff_id_to_continuous_id = {s: len(things) + i for i, s in enumerate(sorted(stuffs))}
    return {**thing_id_to_continuous_id, **stuff_id_to_continuous_id}


def _preprocess_inputs(
    things: Set[int],
    stuffs: Set[int],
    inputs,
    void_color: Tuple[int, int],
    allow_unknown_category: bool,
) -> np.ndarray:
    """Flatten spatial dims, zero stuff instance ids, map unknowns to void
    (reference ``:175-211``)."""
    out = np.array(inputs, copy=True)
    out = out.reshape(out.shape[0], -1, 2)
    cats = out[:, :, 0]
    mask_stuffs = np.isin(cats, list(stuffs))
    mask_things = np.isin(cats, list(things))
    out[:, :, 1] = np.where(mask_stuffs, 0, out[:, :, 1])
    known = mask_things | mask_stuffs
    if not allow_unknown_category and not known.all():
        raise ValueError(f"Unknown categories found: {out[~known]}")
    out[:, :, 0] = np.where(known, out[:, :, 0], void_color[0])
    out[:, :, 1] = np.where(known, out[:, :, 1], void_color[1])
    return out


def _panoptic_quality_update_sample(
    preds: np.ndarray,  # (P, 2)
    target: np.ndarray,  # (P, 2)
    cat_id_to_continuous_id: dict,
    void_color: Tuple[int, int],
    stuffs_modified_metric: Optional[Set[int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized per-sample segment matching (reference ``:312-394``).

    Segments are keyed by packing ``(category, instance)`` into one int64 via
    the sample's own compact color tables; all areas come from a single
    ``np.unique`` over the joint (pred, target) color pairs.
    """
    stuffs_modified_metric = stuffs_modified_metric or set()
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories, dtype=np.float64)
    true_positives = np.zeros(num_categories, dtype=np.int64)
    false_positives = np.zeros(num_categories, dtype=np.int64)
    false_negatives = np.zeros(num_categories, dtype=np.int64)

    # compact per-sample color tables: colors -> ids
    pred_colors, pred_inv = np.unique(preds, axis=0, return_inverse=True)
    target_colors, target_inv = np.unique(target, axis=0, return_inverse=True)
    pred_inv, target_inv = pred_inv.ravel(), target_inv.ravel()
    n_pc, n_tc = len(pred_colors), len(target_colors)
    pred_areas = np.bincount(pred_inv, minlength=n_pc)
    target_areas = np.bincount(target_inv, minlength=n_tc)
    # joint (pred_color, target_color) intersection areas
    joint = pred_inv.astype(np.int64) * n_tc + target_inv
    pair_keys, pair_areas = np.unique(joint, return_counts=True)
    pair_p = pair_keys // n_tc
    pair_t = pair_keys % n_tc

    def _is_void(colors: np.ndarray) -> np.ndarray:
        return (colors[:, 0] == void_color[0]) & (colors[:, 1] == void_color[1])

    pred_is_void = _is_void(pred_colors)
    target_is_void = _is_void(target_colors)

    # void overlap per segment (for union correction and FN/FP filtering)
    pred_void_area = np.zeros(n_pc, dtype=np.int64)
    void_mask_t = target_is_void[pair_t]
    np.add.at(pred_void_area, pair_p[void_mask_t], pair_areas[void_mask_t])
    target_void_area = np.zeros(n_tc, dtype=np.int64)
    void_mask_p = pred_is_void[pair_p]
    np.add.at(target_void_area, pair_t[void_mask_p], pair_areas[void_mask_p])

    # candidate matches: same category, target not void
    same_cat = pred_colors[pair_p, 0] == target_colors[pair_t, 0]
    cand = same_cat & ~target_is_void[pair_t] & ~pred_is_void[pair_p]
    cp, ct, ca = pair_p[cand], pair_t[cand], pair_areas[cand]
    union = pred_areas[cp] - pred_void_area[cp] + target_areas[ct] - target_void_area[ct] - ca
    iou = ca / union

    cat_of_pair = target_colors[ct, 0]
    cont_ids = np.array([cat_id_to_continuous_id[int(c)] for c in cat_of_pair], dtype=np.int64) if len(ct) else np.zeros(0, np.int64)
    modified = (
        np.isin(cat_of_pair, list(stuffs_modified_metric)) if len(ct) else np.zeros(0, bool)
    )

    matched = ~modified & (iou > 0.5)
    np.add.at(iou_sum, cont_ids[matched], iou[matched])
    np.add.at(true_positives, cont_ids[matched], 1)
    mod_hit = modified & (iou > 0)
    np.add.at(iou_sum, cont_ids[mod_hit], iou[mod_hit])

    pred_segment_matched = np.zeros(n_pc, dtype=bool)
    pred_segment_matched[cp[matched]] = True
    target_segment_matched = np.zeros(n_tc, dtype=bool)
    target_segment_matched[ct[matched]] = True

    # false negatives: unmatched target segments not mostly void in the pred
    fn_mask = ~target_segment_matched & ~target_is_void & (target_void_area / target_areas <= 0.5)
    for idx in np.nonzero(fn_mask)[0]:
        cat = int(target_colors[idx, 0])
        if cat not in stuffs_modified_metric:
            false_negatives[cat_id_to_continuous_id[cat]] += 1
    # false positives: unmatched pred segments not mostly void in the target
    fp_mask = ~pred_segment_matched & ~pred_is_void & (pred_void_area / pred_areas <= 0.5)
    for idx in np.nonzero(fp_mask)[0]:
        cat = int(pred_colors[idx, 0])
        if cat not in stuffs_modified_metric:
            false_positives[cat_id_to_continuous_id[cat]] += 1
    # modified metric: tp counts the number of target segments per stuff class
    for idx in range(n_tc):
        cat = int(target_colors[idx, 0])
        if cat in stuffs_modified_metric and not target_is_void[idx]:
            true_positives[cat_id_to_continuous_id[cat]] += 1

    return iou_sum, true_positives, false_positives, false_negatives


def _panoptic_quality_update(
    preds: np.ndarray,
    target: np.ndarray,
    cat_id_to_continuous_id: dict,
    void_color: Tuple[int, int],
    modified_metric_stuffs: Optional[Set[int]] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Batch update: samples are matched independently (reference ``:397-444``)."""
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories, dtype=np.float64)
    tp = np.zeros(num_categories, dtype=np.int64)
    fp = np.zeros(num_categories, dtype=np.int64)
    fn = np.zeros(num_categories, dtype=np.int64)
    for p, t in zip(preds, target):
        r = _panoptic_quality_update_sample(p, t, cat_id_to_continuous_id, void_color, modified_metric_stuffs)
        iou_sum += r[0]
        tp += r[1]
        fp += r[2]
        fn += r[3]
    return jnp.asarray(iou_sum), jnp.asarray(tp), jnp.asarray(fp), jnp.asarray(fn)


def _panoptic_quality_compute(
    iou_sum: Array, true_positives: Array, false_positives: Array, false_negatives: Array
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Per-class and averaged PQ/SQ/RQ (reference ``:447-475``)."""
    sq = jnp.where(true_positives > 0, iou_sum / jnp.maximum(true_positives, 1), 0.0)
    denominator = true_positives + 0.5 * false_positives + 0.5 * false_negatives
    rq = jnp.where(denominator > 0, true_positives / jnp.maximum(denominator, 1e-12), 0.0)
    pq = sq * rq
    seen = denominator > 0
    n_seen = jnp.maximum(seen.sum(), 1)
    pq_avg = jnp.where(seen, pq, 0.0).sum() / n_seen
    sq_avg = jnp.where(seen, sq, 0.0).sum() / n_seen
    rq_avg = jnp.where(seen, rq, 0.0).sum() / n_seen
    return pq, sq, rq, pq_avg, sq_avg, rq_avg


def panoptic_quality(
    preds,
    target,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
    return_sq_and_rq: bool = False,
    return_per_class: bool = False,
) -> Array:
    """Panoptic quality over ``(B, *spatial, 2)`` color maps (reference
    ``functional/detection/panoptic_quality.py:22-118``)."""
    things_p, stuffs_p = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things_p, stuffs_p)
    cat_map = _get_category_id_to_continuous_id(things_p, stuffs_p)
    preds_f = _preprocess_inputs(things_p, stuffs_p, np.asarray(preds), void_color, allow_unknown_preds_category)
    target_f = _preprocess_inputs(things_p, stuffs_p, np.asarray(target), void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(preds_f, target_f, cat_map, void_color)
    pq, sq, rq, pq_avg, sq_avg, rq_avg = _panoptic_quality_compute(iou_sum, tp, fp, fn)
    if return_per_class:
        if return_sq_and_rq:
            return jnp.stack([pq, sq, rq], axis=-1)
        return pq[None, :]
    if return_sq_and_rq:
        return jnp.stack([pq_avg, sq_avg, rq_avg])
    return pq_avg


def modified_panoptic_quality(
    preds,
    target,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
    return_sq_and_rq: bool = False,
    return_per_class: bool = False,
) -> Array:
    """Modified PQ: stuff classes use IoU>0 matching with per-segment tp
    counting (reference ``functional/detection/modified_panoptic_quality.py``)."""
    things_p, stuffs_p = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things_p, stuffs_p)
    cat_map = _get_category_id_to_continuous_id(things_p, stuffs_p)
    preds_f = _preprocess_inputs(things_p, stuffs_p, np.asarray(preds), void_color, allow_unknown_preds_category)
    target_f = _preprocess_inputs(things_p, stuffs_p, np.asarray(target), void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(preds_f, target_f, cat_map, void_color, modified_metric_stuffs=stuffs_p)
    pq, sq, rq, pq_avg, sq_avg, rq_avg = _panoptic_quality_compute(iou_sum, tp, fp, fn)
    if return_per_class:
        if return_sq_and_rq:
            return jnp.stack([pq, sq, rq], axis=-1)
        return pq[None, :]
    if return_sq_and_rq:
        return jnp.stack([pq_avg, sq_avg, rq_avg])
    return pq_avg
