# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""IoU-family functional metrics (reference ``functional/detection/{iou,giou,diou,ciou}.py``).

One shared pipeline parameterized by the pairwise kernel — the reference
repeats the identical update/compute pair in four files; here the kernels
live in :mod:`helpers` and the public functions share the machinery.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.detection.helpers import (
    box_iou,
    complete_box_iou,
    distance_box_iou,
    generalized_box_iou,
)

Array = jax.Array


def _iou_family_update(
    kernel: Callable[[Array, Array], Array],
    preds: Array,
    target: Array,
    iou_threshold: Optional[float],
    replacement_val: float = 0,
) -> Array:
    """Pairwise matrix with sub-threshold entries replaced (reference
    ``functional/detection/iou.py:24-39``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if preds.ndim != 2 or preds.shape[-1] != 4:
        raise ValueError(f"Expected preds to be of shape (N, 4) but got {tuple(preds.shape)}")
    if target.ndim != 2 or target.shape[-1] != 4:
        raise ValueError(f"Expected target to be of shape (N, 4) but got {tuple(target.shape)}")
    mat = kernel(preds, target)
    if iou_threshold is not None:
        mat = jnp.where(mat < iou_threshold, replacement_val, mat)
    return mat


def _iou_family_compute(mat: Array, aggregate: bool = True) -> Array:
    """Mean of the diagonal, or the raw matrix (reference ``iou.py:41-44``)."""
    if not aggregate:
        return mat
    return jnp.diagonal(mat).mean() if mat.size > 0 else jnp.asarray(0.0)


def _make_public(kernel: Callable[[Array, Array], Array], name: str) -> Callable:
    def fn(
        preds: Array,
        target: Array,
        iou_threshold: Optional[float] = None,
        replacement_val: float = 0,
        aggregate: bool = True,
    ) -> Array:
        mat = _iou_family_update(kernel, preds, target, iou_threshold, replacement_val)
        return _iou_family_compute(mat, aggregate)

    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = (
        f"Compute {name.replace('_', ' ')} between two sets of ``xyxy`` boxes.\n\n"
        "With ``aggregate=True`` (default) returns the mean of the matched\n"
        "(diagonal) pairs; otherwise the full pairwise matrix. ``iou_threshold``\n"
        f"replaces sub-threshold entries with ``replacement_val`` (reference\n"
        f"``functional/detection/{name.split('_')[0] if name != 'intersection_over_union' else 'iou'}.py``)."
    )
    return fn


intersection_over_union = _make_public(box_iou, "intersection_over_union")
generalized_intersection_over_union = _make_public(generalized_box_iou, "generalized_intersection_over_union")
distance_intersection_over_union = _make_public(distance_box_iou, "distance_intersection_over_union")
complete_intersection_over_union = _make_public(complete_box_iou, "complete_intersection_over_union")
