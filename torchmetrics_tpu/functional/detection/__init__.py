# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Detection functional metrics (reference ``src/torchmetrics/functional/detection/__init__.py``)."""
from torchmetrics_tpu.functional.detection.iou import (
    complete_intersection_over_union,
    distance_intersection_over_union,
    generalized_intersection_over_union,
    intersection_over_union,
)
from torchmetrics_tpu.functional.detection.map import coco_mean_average_precision
from torchmetrics_tpu.functional.detection.panoptic_quality import modified_panoptic_quality, panoptic_quality

__all__ = [
    "coco_mean_average_precision",
    "complete_intersection_over_union",
    "distance_intersection_over_union",
    "generalized_intersection_over_union",
    "intersection_over_union",
    "modified_panoptic_quality",
    "panoptic_quality",
]
