# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Box geometry kernels + detection input validation.

TPU-native replacements for the torchvision ops the reference calls
(``box_convert``/``box_area``/``box_iou``, reference
``functional/detection/iou.py:33``, ``detection/mean_ap.py:824-857``) and the
shared input validator (reference ``detection/helpers.py:19-101``). All box
kernels are pure ``jax.numpy`` — batched, static-shape, vmap/jit-safe.
"""
from __future__ import annotations

from typing import Dict, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_ALLOWED_BOX_FORMATS = ("xyxy", "xywh", "cxcywh")


def box_convert(boxes: Array, in_fmt: str, out_fmt: str) -> Array:
    """Convert boxes between ``xyxy``/``xywh``/``cxcywh`` formats.

    Capability of torchvision ``box_convert`` (used by reference
    ``detection/iou.py:200``, ``mean_ap.py:403``), expressed as pure jnp.
    """
    if in_fmt not in _ALLOWED_BOX_FORMATS or out_fmt not in _ALLOWED_BOX_FORMATS:
        raise ValueError(f"Unsupported box format conversion {in_fmt} -> {out_fmt}")
    boxes = jnp.asarray(boxes)
    if in_fmt == out_fmt:
        return boxes
    # normalize to xyxy
    if in_fmt == "xywh":
        x, y, w, h = jnp.split(boxes, 4, axis=-1)
        xyxy = jnp.concatenate([x, y, x + w, y + h], axis=-1)
    elif in_fmt == "cxcywh":
        cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
        xyxy = jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    else:
        xyxy = boxes
    if out_fmt == "xyxy":
        return xyxy
    x1, y1, x2, y2 = jnp.split(xyxy, 4, axis=-1)
    if out_fmt == "xywh":
        return jnp.concatenate([x1, y1, x2 - x1, y2 - y1], axis=-1)
    return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], axis=-1)


def box_area(boxes: Array) -> Array:
    """Area of ``xyxy`` boxes (torchvision ``box_area`` capability)."""
    boxes = jnp.asarray(boxes)
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _pairwise_intersection(preds: Array, target: Array) -> Array:
    """Intersection areas for every (pred, target) pair of ``xyxy`` boxes."""
    lt = jnp.maximum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.minimum(preds[..., :, None, 2:], target[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    return wh[..., 0] * wh[..., 1]


def box_iou(preds: Array, target: Array, iscrowd: Union[Array, None] = None) -> Array:
    """Pairwise IoU matrix ``(N, M)`` between ``xyxy`` boxes.

    ``iscrowd`` (shape ``(M,)`` bool) switches a column to the COCO crowd
    convention: IoU = intersection / pred-area (the gt is a region the
    detection may lie inside, pycocotools ``maskUtils.iou`` semantics used by
    reference ``mean_ap.py:534-546``).
    """
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)
    inter = _pairwise_intersection(preds, target)
    area_p = box_area(preds)[..., :, None]
    area_t = box_area(target)[..., None, :]
    union = area_p + area_t - inter
    if iscrowd is not None:
        union = jnp.where(jnp.asarray(iscrowd)[..., None, :], area_p * jnp.ones_like(union), union)
    return jnp.where(union > 0, inter / union, 0.0)


def generalized_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise GIoU matrix: IoU - (hull - union) / hull."""
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)
    inter = _pairwise_intersection(preds, target)
    area_p = box_area(preds)[..., :, None]
    area_t = box_area(target)[..., None, :]
    union = area_p + area_t - inter
    iou = jnp.where(union > 0, inter / union, 0.0)
    lt = jnp.minimum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.maximum(preds[..., :, None, 2:], target[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    hull = wh[..., 0] * wh[..., 1]
    return iou - jnp.where(hull > 0, (hull - union) / hull, 0.0)


def distance_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise DIoU: IoU - center-distance² / hull-diagonal²."""
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)
    inter = _pairwise_intersection(preds, target)
    area_p = box_area(preds)[..., :, None]
    area_t = box_area(target)[..., None, :]
    union = area_p + area_t - inter
    iou = jnp.where(union > 0, inter / union, 0.0)
    lt = jnp.minimum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.maximum(preds[..., :, None, 2:], target[..., None, :, 2:])
    diag = jnp.sum((rb - lt) ** 2, axis=-1)
    cp = (preds[..., :, None, :2] + preds[..., :, None, 2:]) / 2
    ct = (target[..., None, :, :2] + target[..., None, :, 2:]) / 2
    dist = jnp.sum((cp - ct) ** 2, axis=-1)
    return iou - jnp.where(diag > 0, dist / diag, 0.0)


def complete_box_iou(preds: Array, target: Array, eps: float = 1e-7) -> Array:
    """Pairwise CIoU: DIoU - aspect-ratio consistency term."""
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)
    diou = distance_box_iou(preds, target)
    inter = _pairwise_intersection(preds, target)
    area_p = box_area(preds)[..., :, None]
    area_t = box_area(target)[..., None, :]
    union = area_p + area_t - inter
    iou = jnp.where(union > 0, inter / union, 0.0)
    wp = preds[..., 2] - preds[..., 0]
    hp = preds[..., 3] - preds[..., 1]
    wt = target[..., 2] - target[..., 0]
    ht = target[..., 3] - target[..., 1]
    v = (4 / (jnp.pi**2)) * (
        jnp.arctan(wt / (ht + eps))[..., None, :] - jnp.arctan(wp / (hp + eps))[..., :, None]
    ) ** 2
    alpha = v / (1 - iou + v + eps)
    return diou - alpha * v


def _fix_empty_arrays(boxes: np.ndarray) -> np.ndarray:
    """Give degenerate empty box arrays a ``(0, 4)`` shape (reference
    ``detection/helpers.py:104-108``)."""
    boxes = np.asarray(boxes)
    if boxes.size == 0:
        return boxes.reshape(0, 4) if boxes.ndim != 1 or boxes.shape[0] == 0 else boxes
    return boxes


def _input_validator(
    preds: Sequence[Dict[str, Array]],
    targets: Sequence[Dict[str, Array]],
    iou_type: Union[str, Tuple[str, ...]] = "bbox",
    ignore_score: bool = False,
) -> None:
    """Validate the list-of-dicts detection input format (reference
    ``detection/helpers.py:19-101``; error strings kept API-compatible)."""
    if isinstance(iou_type, str):
        iou_type = (iou_type,)
    name_map = {"bbox": "boxes", "segm": "masks"}
    if any(tp not in name_map for tp in iou_type):
        raise Exception(f"IOU type {iou_type} is not supported")
    item_val_name = [name_map[tp] for tp in iou_type]

    if not isinstance(preds, Sequence):
        raise ValueError(f"Expected argument `preds` to be of type Sequence, but got {preds}")
    if not isinstance(targets, Sequence):
        raise ValueError(f"Expected argument `target` to be of type Sequence, but got {targets}")
    if len(preds) != len(targets):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, but got {len(preds)} and {len(targets)}"
        )
    for k in [*item_val_name, "labels"] + ([] if ignore_score else ["scores"]):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in [*item_val_name, "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")
    for i, item in enumerate(targets):
        for ivn in item_val_name:
            if np.asarray(item[ivn]).shape[0] != np.asarray(item["labels"]).shape[0]:
                raise ValueError(
                    f"Input '{ivn}' and labels of sample {i} in targets have a"
                    f" different length (expected {np.asarray(item[ivn]).shape[0]} labels,"
                    f" got {np.asarray(item['labels']).shape[0]})"
                )
    if ignore_score:
        return
    for i, item in enumerate(preds):
        for ivn in item_val_name:
            if not (
                np.asarray(item[ivn]).shape[0]
                == np.asarray(item["labels"]).shape[0]
                == np.asarray(item["scores"]).shape[0]
            ):
                raise ValueError(
                    f"Input '{ivn}', labels and scores of sample {i} in predictions have a"
                    f" different length (expected {np.asarray(item[ivn]).shape[0]} labels and scores,"
                    f" got {np.asarray(item['labels']).shape[0]} labels"
                    f" and {np.asarray(item['scores']).shape[0]} scores)"
                )


def _validate_iou_type_arg(iou_type: Union[str, Tuple[str, ...]] = "bbox") -> Tuple[str, ...]:
    """Validate the ``iou_type`` argument (reference ``detection/helpers.py:111-122``)."""
    allowed_iou_types = ("segm", "bbox")
    if isinstance(iou_type, str):
        iou_type = (iou_type,)
    if any(tp not in allowed_iou_types for tp in iou_type):
        raise ValueError(
            f"Expected argument `iou_type` to be one of {allowed_iou_types} or a tuple of, but got {iou_type}"
        )
    return iou_type
