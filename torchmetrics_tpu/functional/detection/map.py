# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Pure-JAX COCO mean-average-precision evaluator.

TPU-first re-design of COCO evaluation (reference blueprint:
``detection/_mean_ap.py:522-860`` pure-torch path; rule source of truth:
pycocotools ``COCOeval`` as delegated to by ``detection/mean_ap.py:534-546``):

- **Packing**: variable-size per-image detections/ground-truths are padded to
  dense ``(n_images, D, ...)`` / ``(n_images, G, ...)`` buffers with validity
  masks — static shapes, the XLA-native representation of ragged data.
- **Matching** (the O(images·D·G·T·A) hot loop): one ``lax.scan`` over
  score-sorted detections, vectorized over all IoU thresholds and area ranges
  at once and ``vmap``-ed over images. Per-category matching falls out of a
  label-equality mask on the IoU matrix — no per-class Python loop. Implements
  the full pycocotools rules: greedy best-IoU matching in score order,
  crowd ground truths matchable many times with the
  intersection-over-det-area IoU, ignored ground truths only matchable when no
  regular match exists, unmatched detections outside the area range ignored.
- **Accumulation** (tiny FLOPs): per (class, area, max-det) score-merge,
  cumulative TP/FP, precision envelope, and 101-point recall interpolation on
  host numpy — exactly the layout pycocotools uses, so results match to
  float precision.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from torchmetrics_tpu.functional.detection.helpers import box_area, box_convert

Array = jax.Array

# COCO defaults (pycocotools Params; reference ``mean_ap.py:410-431``)
DEFAULT_IOU_THRESHOLDS = tuple(np.linspace(0.5, 0.95, 10).tolist())
DEFAULT_REC_THRESHOLDS = tuple(np.linspace(0.0, 1.0, 101).tolist())
DEFAULT_MAX_DETECTIONS = (1, 10, 100)
DEFAULT_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def _round_up(n: int, mult: int = 8) -> int:
    """Round a pad dimension up to a multiple to limit jit recompiles."""
    return max(mult, ((n + mult - 1) // mult) * mult)


def _pack_ragged(
    items: Sequence[np.ndarray], pad_to: int, width: Optional[int] = None, dtype=np.float32
) -> Tuple[np.ndarray, np.ndarray]:
    """Stack variable-length per-image arrays into a padded dense buffer + mask."""
    n = len(items)
    shape = (n, pad_to) if width is None else (n, pad_to, width)
    out = np.zeros(shape, dtype=dtype)
    valid = np.zeros((n, pad_to), dtype=bool)
    for i, item in enumerate(items):
        item = np.asarray(item, dtype=dtype)
        k = min(item.shape[0], pad_to)
        if k:
            out[i, :k] = item[:k]
            valid[i, :k] = True
    return out, valid


def _crowd_box_iou(det: Array, gt: Array, crowd: Array) -> Array:
    """Padded pairwise IoU with COCO crowd columns (union = det area)."""
    lt = jnp.maximum(det[:, None, :2], gt[None, :, :2])
    rb = jnp.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_d = box_area(det)[:, None]
    area_g = box_area(gt)[None, :]
    union = jnp.where(crowd[None, :], area_d * jnp.ones_like(inter), area_d + area_g - inter)
    return jnp.where(union > 0, inter / union, 0.0)


def _match_one_image(
    iou: Array,  # (D, G) pairwise IoU (crowd-aware), any iou_type
    det_area: Array,  # (D,)
    det_labels: Array,  # (D,)
    det_valid: Array,  # (D,)
    gt_labels: Array,  # (G,)
    gt_valid: Array,  # (G,)
    gt_crowd: Array,  # (G,)
    gt_area: Array,  # (G,)
    iou_thrs: Array,  # (T,)
    area_rngs: Array,  # (A, 2)
) -> Tuple[Array, Array, Array]:
    """Greedy COCO matching for one image, all thresholds/areas at once.

    IoU-type agnostic: the pairwise IoU matrix and per-detection areas come
    precomputed (boxes on device, masks via the native RLE codec). Returns
    ``det_matched (A,T,D)``, ``det_ignored (A,T,D)``, ``gt_ignored (A,G)``
    (pycocotools ``evaluateImg`` semantics).
    """
    num_t = iou_thrs.shape[0]
    num_a = area_rngs.shape[0]
    num_g = gt_labels.shape[0]

    pair_ok = det_valid[:, None] & gt_valid[None, :] & (det_labels[:, None] == gt_labels[None, :])

    # per-area ignore: crowd or area outside range (pycocotools gt['_ignore'])
    area_out = (gt_area[None, :] < area_rngs[:, 0:1]) | (gt_area[None, :] > area_rngs[:, 1:2])  # (A, G)
    gt_ig = (gt_crowd[None, :] | area_out) & gt_valid[None, :]

    # matching bar: iou must reach min(t, 1-1e-10) (pycocotools evaluateImg)
    thr = jnp.minimum(iou_thrs, 1 - 1e-10)[None, :]  # (1, T) broadcast over (A, T)
    gt_ig_full = jnp.broadcast_to(gt_ig[:, None, :], (num_a, num_t, num_g))

    def _last_argmax(vals: Array) -> Array:
        # pycocotools' match loop updates on `iou >= best`, so among equal
        # IoUs the LAST ground truth in iteration order wins — first-argmax
        # silently diverges on exact ties (symmetric/grid boxes)
        return num_g - 1 - jnp.argmax(vals[..., ::-1], axis=-1)

    def step(gt_matched: Array, inputs: Tuple[Array, Array]) -> Tuple[Array, Array]:
        iou_d, ok_d = inputs  # (G,), (G,)
        # stage 1: regular (non-ignored, unmatched) ground truths
        cand1 = ok_d[None, None, :] & (~gt_ig[:, None, :]) & (~gt_matched)  # (A, T, G)
        vals1 = jnp.where(cand1, iou_d[None, None, :], -1.0)
        best1 = _last_argmax(vals1)  # (A, T)
        ok1 = jnp.max(vals1, axis=-1) >= thr
        # stage 2: ignored ground truths — crowds matchable repeatedly
        cand2 = ok_d[None, None, :] & gt_ig[:, None, :] & (gt_crowd[None, None, :] | ~gt_matched)
        vals2 = jnp.where(cand2, iou_d[None, None, :], -1.0)
        best2 = _last_argmax(vals2)
        ok2 = jnp.max(vals2, axis=-1) >= thr

        matched = ok1 | ok2  # (A, T)
        m = jnp.where(ok1, best1, best2)  # (A, T)
        hit = jax.nn.one_hot(m, num_g, dtype=bool) & matched[..., None]  # (A, T, G)
        gt_matched = gt_matched | hit
        ignored = matched & jnp.take_along_axis(gt_ig_full, m[..., None], axis=-1)[..., 0]
        return gt_matched, (matched, ignored)

    init = jnp.zeros((num_a, num_t, num_g), dtype=bool)
    _, (det_matched, det_ig) = lax.scan(step, init, (iou, pair_ok))
    det_matched = jnp.moveaxis(det_matched, 0, -1)  # (A, T, D)
    det_ig = jnp.moveaxis(det_ig, 0, -1)

    # unmatched detections outside the area range are ignored too
    det_out = (det_area[None, :] < area_rngs[:, 0:1]) | (det_area[None, :] > area_rngs[:, 1:2])  # (A, D)
    det_ig = det_ig | (~det_matched & det_out[:, None, :])
    return det_matched, det_ig, gt_ig


def _pack_bool_bits(x: Array) -> Array:
    """Pack a (..., L) bool array into (..., ceil(L/8)) uint8, little-endian
    bit order (``np.unpackbits(..., bitorder='little')`` inverts it).

    The match/ignore tensors are the only large device→host transfer of the
    evaluation; shipping bits instead of bool bytes cuts it 8×."""
    length = x.shape[-1]
    pad = (-length) % 8
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    x = x.reshape(*x.shape[:-1], -1, 8)
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.int32)
    return (x.astype(jnp.int32) * weights).sum(-1, dtype=jnp.int32).astype(jnp.uint8)


@jax.jit
def _match_images_packed(*args):
    det_matched, det_ignored, gt_ignored = jax.vmap(
        _match_one_image, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None)
    )(*args)
    return _pack_bool_bits(det_matched), _pack_bool_bits(det_ignored), _pack_bool_bits(gt_ignored)


def _match_images(
    iou, det_area, det_labels, det_valid, gt_labels, gt_valid, gt_crowd, gt_area, iou_thrs, area_rngs
):
    """Vectorized per-image matching; results cross the wire bit-packed and
    in one batched fetch."""
    packed = jax.device_get(
        _match_images_packed(
            iou, det_area, det_labels, det_valid, gt_labels, gt_valid, gt_crowd, gt_area, iou_thrs, area_rngs
        )
    )
    num_d = det_labels.shape[1]
    num_g = gt_labels.shape[1]
    out = []
    for arr, length in zip(packed, (num_d, num_d, num_g)):
        bits = np.unpackbits(arr, axis=-1, bitorder="little")
        out.append(bits[..., :length].astype(bool))
    return out


@jax.jit
def _bbox_iou_and_area(det_boxes: Array, gt_boxes: Array, gt_crowd: Array) -> Tuple[Array, Array]:
    """Batched (N, D, G) box IoU with crowd columns + (N, D) det areas."""
    iou = jax.vmap(_crowd_box_iou)(det_boxes, gt_boxes, gt_crowd)
    det_area = jax.vmap(box_area)(det_boxes)
    return iou, det_area


class COCOEvaluationResult(dict):
    """Result dict allowing attribute access (reference ``_mean_ap.py:74-92``)."""

    def __getattr__(self, key: str) -> Any:
        if key in self:
            return self[key]
        raise AttributeError(f"No such attribute: {key}")


# traverse like a plain dict under jax.tree_util (dict subclasses are
# otherwise opaque leaves, which breaks generic pytree post-processing)
jax.tree_util.register_pytree_node(
    COCOEvaluationResult,
    lambda d: (tuple(d[k] for k in sorted(d)), tuple(sorted(d))),
    lambda keys, vals: COCOEvaluationResult(zip(keys, vals)),
)


def coco_mean_average_precision(
    preds: Sequence[Dict[str, Any]],
    target: Sequence[Dict[str, Any]],
    box_format: str = "xyxy",
    iou_thresholds: Optional[Sequence[float]] = None,
    rec_thresholds: Optional[Sequence[float]] = None,
    max_detection_thresholds: Optional[Sequence[int]] = None,
    class_metrics: bool = False,
    extended_summary: bool = False,
    average: str = "macro",
    iou_type: str = "bbox",
) -> Dict[str, Any]:
    """Full COCO-style evaluation over a dataset of per-image dicts.

    Matches pycocotools ``COCOeval`` output (reference ``mean_ap.py:520-647``).
    ``preds[i]``: ``scores``/``labels`` plus ``boxes`` (``iou_type="bbox"``) or
    ``masks`` (``iou_type="segm"``: ``(n, H, W)`` binary arrays or RLE dicts);
    ``target[i]``: same geometry key, ``labels``, optional ``iscrowd``/``area``.
    Mask IoU/areas run through the native C++ RLE codec
    (:mod:`torchmetrics_tpu.functional.detection.mask_utils`).
    """
    if iou_type not in ("bbox", "segm"):
        raise ValueError(f"Expected argument `iou_type` to be one of ('bbox', 'segm') but got {iou_type}")
    iou_thrs = np.asarray(iou_thresholds if iou_thresholds is not None else DEFAULT_IOU_THRESHOLDS, np.float64)
    rec_thrs = np.asarray(rec_thresholds if rec_thresholds is not None else DEFAULT_REC_THRESHOLDS, np.float64)
    max_dets = sorted(max_detection_thresholds if max_detection_thresholds is not None else DEFAULT_MAX_DETECTIONS)
    area_rngs = np.asarray(list(DEFAULT_AREA_RANGES.values()), np.float64)
    n_imgs = len(preds)
    maxdet_last = max_dets[-1]

    if iou_type == "segm":
        from torchmetrics_tpu.functional.detection import mask_utils

        def _to_rles(items):
            masks = items.get("masks", [])
            if isinstance(masks, dict):
                masks = [masks]
            rles = []
            for m in masks:
                rles.append(m if isinstance(m, dict) else mask_utils.encode(np.asarray(m)))
            return rles

    det_boxes_l, det_scores_l, det_labels_l, det_rles_l, det_marea_l = [], [], [], [], []
    gt_boxes_l, gt_labels_l, gt_crowd_l, gt_area_l, gt_rles_l = [], [], [], [], []
    for p, t in zip(preds, target):
        scores = np.asarray(p["scores"], np.float64).reshape(-1)
        labels = np.asarray(p["labels"]).reshape(-1)
        order = np.argsort(-scores, kind="mergesort")[:maxdet_last]
        scores, labels = scores[order], labels[order]
        if iou_type == "bbox":
            boxes = np.asarray(p["boxes"], np.float64).reshape(-1, 4)[order]
            if box_format != "xyxy":
                boxes = np.asarray(box_convert(boxes, box_format, "xyxy")) if boxes.size else boxes
            det_boxes_l.append(boxes)
        else:
            rles = _to_rles(p)
            rles = [rles[i] for i in order]
            det_rles_l.append(rles)
            det_marea_l.append(np.asarray(mask_utils.area(rles)).reshape(-1) if rles else np.zeros(0))
        det_scores_l.append(scores)
        det_labels_l.append(labels)

        glabels = np.asarray(t["labels"]).reshape(-1)
        crowd = np.asarray(t.get("iscrowd", np.zeros(len(glabels)))).reshape(-1).astype(bool)
        area = t.get("area")
        if iou_type == "bbox":
            gboxes = np.asarray(t["boxes"], np.float64).reshape(-1, 4)
            if box_format != "xyxy":
                gboxes = np.asarray(box_convert(gboxes, box_format, "xyxy")) if gboxes.size else gboxes
            gt_boxes_l.append(gboxes)
            default_area = (gboxes[:, 2] - gboxes[:, 0]) * (gboxes[:, 3] - gboxes[:, 1])
        else:
            grles = _to_rles(t)
            gt_rles_l.append(grles)
            default_area = np.asarray(mask_utils.area(grles)).reshape(-1) if grles else np.zeros(0)
        area = (
            np.asarray(area, np.float64).reshape(-1)
            if area is not None and np.asarray(area).size
            else default_area
        )
        gt_labels_l.append(glabels)
        gt_crowd_l.append(crowd)
        gt_area_l.append(area)

    if average == "micro":
        # micro averaging pools every class into one (reference ``mean_ap.py:490-497``)
        det_labels_l = [np.zeros_like(x) for x in det_labels_l]
        gt_labels_l = [np.zeros_like(x) for x in gt_labels_l]

    all_labels = np.concatenate([np.concatenate(det_labels_l) if det_labels_l else np.zeros(0)]
                                + [np.concatenate(gt_labels_l) if gt_labels_l else np.zeros(0)])
    classes = np.unique(all_labels.astype(np.int64)) if all_labels.size else np.zeros(0, np.int64)
    num_t, num_r, num_k, num_a, num_m = len(iou_thrs), len(rec_thrs), len(classes), len(area_rngs), len(max_dets)

    precision = -np.ones((num_t, num_r, num_k, num_a, num_m))
    recall = -np.ones((num_t, num_k, num_a, num_m))
    scores_tbl = -np.ones((num_t, num_r, num_k, num_a, num_m))

    if n_imgs and num_k:
        pad_d = _round_up(max(1, max(len(s) for s in det_scores_l)))
        pad_g = _round_up(max(1, max(len(x) for x in gt_labels_l)))
        det_scores, det_valid = _pack_ragged(det_scores_l, pad_d)
        det_labels, _ = _pack_ragged(det_labels_l, pad_d, dtype=np.int64)
        gt_labels, gt_valid = _pack_ragged(gt_labels_l, pad_g, dtype=np.int64)
        gt_crowd, _ = _pack_ragged(gt_crowd_l, pad_g, dtype=bool)
        gt_area, _ = _pack_ragged(gt_area_l, pad_g)
        # pad labels with a sentinel no real class uses so padded rows never match
        det_labels = np.where(det_valid, det_labels, -1)
        gt_labels_pad = np.where(gt_valid, gt_labels, -2)

        if iou_type == "bbox":
            det_boxes, _ = _pack_ragged(det_boxes_l, pad_d, 4)
            gt_boxes, _ = _pack_ragged(gt_boxes_l, pad_g, 4)
            iou_all, det_area = _bbox_iou_and_area(
                jnp.asarray(det_boxes), jnp.asarray(gt_boxes), jnp.asarray(gt_crowd)
            )
        else:
            # per-image crowd-aware mask IoU via the native RLE codec (host)
            iou_np = np.zeros((n_imgs, pad_d, pad_g), np.float32)
            from torchmetrics_tpu.functional.detection import mask_utils

            for i in range(n_imgs):
                d_rles, g_rles = det_rles_l[i], gt_rles_l[i]
                if d_rles and g_rles:
                    iou_np[i, : len(d_rles), : len(g_rles)] = mask_utils.iou(
                        d_rles, g_rles, iscrowd=gt_crowd_l[i].astype(np.uint8)
                    )
            iou_all = jnp.asarray(iou_np)
            det_area_np, _ = _pack_ragged(det_marea_l, pad_d)
            det_area = jnp.asarray(det_area_np)

        det_matched, det_ignored, gt_ignored = (
            np.asarray(x)
            for x in _match_images(
                iou_all,
                det_area,
                jnp.asarray(det_labels),
                jnp.asarray(det_valid),
                jnp.asarray(gt_labels_pad),
                jnp.asarray(gt_valid),
                jnp.asarray(gt_crowd),
                jnp.asarray(gt_area),
                jnp.asarray(iou_thrs, jnp.float32),
                jnp.asarray(area_rngs, jnp.float32),
            )
        )  # (N,A,T,D), (N,A,T,D), (N,A,G)

        eps = np.spacing(np.float64(1))
        # (A, T, N·D) flattened match/ignore views shared by every class
        dtm_flat = det_matched.transpose(1, 2, 0, 3).reshape(num_a, num_t, -1)
        dtig_flat = det_ignored.transpose(1, 2, 0, 3).reshape(num_a, num_t, -1)
        gtig_flat = gt_ignored.transpose(1, 0, 2).reshape(num_a, -1)
        # group det/gt indices by class ONCE per image (stable sort keeps the
        # per-image score order within each class group) instead of scanning
        # every image again for every class
        def _group_by_class(labels, valid):
            sels = []
            for i in range(labels.shape[0]):
                pos = np.searchsorted(classes, labels[i])
                pos = np.clip(pos, 0, num_k - 1)
                key = np.where(valid[i] & (classes[pos] == labels[i]), pos, num_k)
                order = np.argsort(key, kind="stable")
                counts = np.bincount(key, minlength=num_k + 1)
                offs = np.concatenate(([0], np.cumsum(counts[:num_k])))
                sels.append((order, offs))
            return sels

        det_groups = _group_by_class(det_labels, det_valid)
        gt_groups = _group_by_class(gt_labels, gt_valid)
        for ki, k in enumerate(classes):
            det_sel = [order[offs[ki] : offs[ki + 1]] for order, offs in det_groups]
            gt_sel = [order[offs[ki] : offs[ki + 1]] for order, offs in gt_groups]
            if not any(len(s) for s in det_sel) and not any(len(s) for s in gt_sel):
                continue
            # hoist per-(maxdet) selections out of the area loop: scores and
            # sort order are area-independent
            per_mdet = []
            for mdet in max_dets:
                sel = [s[:mdet] for s in det_sel]
                flat = np.concatenate([i * det_valid.shape[1] + sel[i] for i in range(n_imgs)]) if n_imgs else np.zeros(0, np.int64)
                dt_scores = det_scores.reshape(-1)[flat]
                order = np.argsort(-dt_scores, kind="mergesort")
                per_mdet.append((flat[order], dt_scores[order]))
            gt_flat = np.concatenate([i * gt_valid.shape[1] + gt_sel[i] for i in range(n_imgs)]) if n_imgs else np.zeros(0, np.int64)
            for ai in range(num_a):
                npig = int((~gtig_flat[ai, gt_flat]).sum())
                if npig == 0:
                    continue
                for mi, mdet in enumerate(max_dets):
                    flat_sorted, dt_scores_sorted = per_mdet[mi]
                    dtm = dtm_flat[ai][:, flat_sorted]
                    dt_ig = dtig_flat[ai][:, flat_sorted]
                    tps = dtm & ~dt_ig
                    fps = ~dtm & ~dt_ig
                    tp_sum = np.cumsum(tps, axis=1).astype(np.float64)
                    fp_sum = np.cumsum(fps, axis=1).astype(np.float64)
                    nd = tp_sum.shape[1]
                    # all thresholds at once: the per-T python loop was the
                    # host-side hot spot at val2017 scale (K·A·M·T ~ 10k
                    # small-vector iterations)
                    rc = tp_sum / npig  # (T, nd)
                    pr = tp_sum / (fp_sum + tp_sum + eps)
                    recall[:, ki, ai, mi] = rc[:, -1] if nd else 0
                    # precision envelope: non-increasing from the right
                    pr = np.maximum.accumulate(pr[:, ::-1], axis=1)[:, ::-1]
                    precision[:, :, ki, ai, mi] = 0.0
                    scores_tbl[:, :, ki, ai, mi] = 0.0
                    for ti in range(num_t):
                        inds = np.searchsorted(rc[ti], rec_thrs, side="left")
                        valid_inds = inds < nd
                        precision[ti, valid_inds, ki, ai, mi] = pr[ti][inds[valid_inds]]
                        scores_tbl[ti, valid_inds, ki, ai, mi] = dt_scores_sorted[inds[valid_inds]]

    def _summarize(ap: bool, iou_thr: Optional[float] = None, area: str = "all", mdet: int = maxdet_last) -> float:
        ai = list(DEFAULT_AREA_RANGES).index(area)
        mi = max_dets.index(mdet)
        if ap:
            s = precision[:, :, :, ai, mi]
            if iou_thr is not None:
                s = s[np.where(np.isclose(iou_thrs, iou_thr))[0]]
        else:
            s = recall[:, :, ai, mi]
            if iou_thr is not None:
                s = s[np.where(np.isclose(iou_thrs, iou_thr))[0]]
        s = s[s > -1]
        return float(np.mean(s)) if s.size else -1.0

    res: Dict[str, Any] = COCOEvaluationResult()
    res["map"] = jnp.asarray(_summarize(True), jnp.float32)
    res["map_50"] = jnp.asarray(_summarize(True, 0.5) if np.any(np.isclose(iou_thrs, 0.5)) else -1.0, jnp.float32)
    res["map_75"] = jnp.asarray(_summarize(True, 0.75) if np.any(np.isclose(iou_thrs, 0.75)) else -1.0, jnp.float32)
    res["map_small"] = jnp.asarray(_summarize(True, area="small"), jnp.float32)
    res["map_medium"] = jnp.asarray(_summarize(True, area="medium"), jnp.float32)
    res["map_large"] = jnp.asarray(_summarize(True, area="large"), jnp.float32)
    for mdet in max_dets:
        res[f"mar_{mdet}"] = jnp.asarray(_summarize(False, mdet=mdet), jnp.float32)
    res["mar_small"] = jnp.asarray(_summarize(False, area="small"), jnp.float32)
    res["mar_medium"] = jnp.asarray(_summarize(False, area="medium"), jnp.float32)
    res["mar_large"] = jnp.asarray(_summarize(False, area="large"), jnp.float32)

    if class_metrics and num_k:
        map_pc, mar_pc = [], []
        for ki in range(num_k):
            s = precision[:, :, ki, 0, num_m - 1]
            s = s[s > -1]
            map_pc.append(float(np.mean(s)) if s.size else -1.0)
            r = recall[:, ki, 0, num_m - 1]
            r = r[r > -1]
            mar_pc.append(float(np.mean(r)) if r.size else -1.0)
        res["map_per_class"] = jnp.asarray(map_pc, jnp.float32)
        res[f"mar_{maxdet_last}_per_class"] = jnp.asarray(mar_pc, jnp.float32)
    else:
        res["map_per_class"] = jnp.asarray(-1.0, jnp.float32)
        res[f"mar_{maxdet_last}_per_class"] = jnp.asarray(-1.0, jnp.float32)
    res["classes"] = jnp.asarray(classes, jnp.int32)

    if extended_summary:
        res["precision"] = jnp.asarray(precision, jnp.float32)
        res["recall"] = jnp.asarray(recall, jnp.float32)
        res["scores"] = jnp.asarray(scores_tbl, jnp.float32)
    return res
