# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Pure-JAX COCO mean-average-precision evaluator.

TPU-first re-design of COCO evaluation (reference blueprint:
``detection/_mean_ap.py:522-860`` pure-torch path; rule source of truth:
pycocotools ``COCOeval`` as delegated to by ``detection/mean_ap.py:534-546``):

- **Packing**: variable-size per-image detections/ground-truths are padded to
  dense ``(n_images, D, ...)`` / ``(n_images, G, ...)`` buffers with validity
  masks — static shapes, the XLA-native representation of ragged data.
- **Matching** (the O(images·D·G·T·A) hot loop): one ``lax.scan`` over
  score-sorted detections, vectorized over all IoU thresholds and area ranges
  at once and ``vmap``-ed over images. Per-category matching falls out of a
  label-equality mask on the IoU matrix — no per-class Python loop. Implements
  the full pycocotools rules: greedy best-IoU matching in score order,
  crowd ground truths matchable many times with the
  intersection-over-det-area IoU, ignored ground truths only matchable when no
  regular match exists, unmatched detections outside the area range ignored.
- **Accumulation**: per (class, area, max-det) score-merge, cumulative
  TP/FP, precision envelope, and 101-point recall interpolation as ONE
  static-shape device program (``_accumulate_device``): a single stable
  lexsort by (class, -score) makes classes contiguous segments, cumulative
  sums become segmented prefix sums, the precision envelope is a segmented
  reverse cumulative max (``lax.associative_scan``), and the 101-point
  table is built by scattering each position's recall-threshold span start
  and forward-filling along the grid. Matching and accumulation compile
  into one program, so the only device→host transfer is the final
  ``(T, R, K, A, M)`` tables — the host accumulate (and its CPU
  sensitivity, VERDICT r3 weak #1/#6) is gone.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from torchmetrics_tpu.functional.detection.helpers import box_area, box_convert

Array = jax.Array

# COCO defaults (pycocotools Params; reference ``mean_ap.py:410-431``)
DEFAULT_IOU_THRESHOLDS = tuple(np.linspace(0.5, 0.95, 10).tolist())
DEFAULT_REC_THRESHOLDS = tuple(np.linspace(0.0, 1.0, 101).tolist())
DEFAULT_MAX_DETECTIONS = (1, 10, 100)
DEFAULT_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def _round_up(n: int, mult: int = 8) -> int:
    """Round a pad dimension up to a multiple to limit jit recompiles."""
    return max(mult, ((n + mult - 1) // mult) * mult)


def _pack_ragged(
    items: Sequence[np.ndarray], pad_to: int, width: Optional[int] = None, dtype=np.float32
) -> Tuple[np.ndarray, np.ndarray]:
    """Stack variable-length per-image arrays into a padded dense buffer + mask."""
    n = len(items)
    shape = (n, pad_to) if width is None else (n, pad_to, width)
    out = np.zeros(shape, dtype=dtype)
    valid = np.zeros((n, pad_to), dtype=bool)
    for i, item in enumerate(items):
        item = np.asarray(item, dtype=dtype)
        k = min(item.shape[0], pad_to)
        if k:
            out[i, :k] = item[:k]
            valid[i, :k] = True
    return out, valid


def _crowd_box_iou(det: Array, gt: Array, crowd: Array) -> Array:
    """Padded pairwise IoU with COCO crowd columns (union = det area)."""
    lt = jnp.maximum(det[:, None, :2], gt[None, :, :2])
    rb = jnp.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_d = box_area(det)[:, None]
    area_g = box_area(gt)[None, :]
    union = jnp.where(crowd[None, :], area_d * jnp.ones_like(inter), area_d + area_g - inter)
    return jnp.where(union > 0, inter / union, 0.0)


def _match_one_image(
    iou: Array,  # (D, G) pairwise IoU (crowd-aware), any iou_type
    det_area: Array,  # (D,)
    det_labels: Array,  # (D,)
    det_valid: Array,  # (D,)
    gt_labels: Array,  # (G,)
    gt_valid: Array,  # (G,)
    gt_crowd: Array,  # (G,)
    gt_area: Array,  # (G,)
    iou_thrs: Array,  # (T,)
    area_rngs: Array,  # (A, 2)
) -> Tuple[Array, Array, Array]:
    """Greedy COCO matching for one image, all thresholds/areas at once.

    IoU-type agnostic: the pairwise IoU matrix and per-detection areas come
    precomputed (boxes on device, masks via the native RLE codec). Returns
    ``det_matched (A,T,D)``, ``det_ignored (A,T,D)``, ``gt_ignored (A,G)``
    (pycocotools ``evaluateImg`` semantics).
    """
    num_t = iou_thrs.shape[0]
    num_a = area_rngs.shape[0]
    num_g = gt_labels.shape[0]

    pair_ok = det_valid[:, None] & gt_valid[None, :] & (det_labels[:, None] == gt_labels[None, :])

    # per-area ignore: crowd or area outside range (pycocotools gt['_ignore'])
    area_out = (gt_area[None, :] < area_rngs[:, 0:1]) | (gt_area[None, :] > area_rngs[:, 1:2])  # (A, G)
    gt_ig = (gt_crowd[None, :] | area_out) & gt_valid[None, :]

    # matching bar: iou must reach min(t, 1-1e-10) (pycocotools evaluateImg)
    thr = jnp.minimum(iou_thrs, 1 - 1e-10)[None, :]  # (1, T) broadcast over (A, T)
    gt_ig_full = jnp.broadcast_to(gt_ig[:, None, :], (num_a, num_t, num_g))

    def _last_argmax(vals: Array) -> Array:
        # pycocotools' match loop updates on `iou >= best`, so among equal
        # IoUs the LAST ground truth in iteration order wins — first-argmax
        # silently diverges on exact ties (symmetric/grid boxes)
        return num_g - 1 - jnp.argmax(vals[..., ::-1], axis=-1)

    def step(gt_matched: Array, inputs: Tuple[Array, Array]) -> Tuple[Array, Array]:
        iou_d, ok_d = inputs  # (G,), (G,)
        # stage 1: regular (non-ignored, unmatched) ground truths
        cand1 = ok_d[None, None, :] & (~gt_ig[:, None, :]) & (~gt_matched)  # (A, T, G)
        vals1 = jnp.where(cand1, iou_d[None, None, :], -1.0)
        best1 = _last_argmax(vals1)  # (A, T)
        ok1 = jnp.max(vals1, axis=-1) >= thr
        # stage 2: ignored ground truths — crowds matchable repeatedly
        cand2 = ok_d[None, None, :] & gt_ig[:, None, :] & (gt_crowd[None, None, :] | ~gt_matched)
        vals2 = jnp.where(cand2, iou_d[None, None, :], -1.0)
        best2 = _last_argmax(vals2)
        ok2 = jnp.max(vals2, axis=-1) >= thr

        matched = ok1 | ok2  # (A, T)
        m = jnp.where(ok1, best1, best2)  # (A, T)
        hit = jax.nn.one_hot(m, num_g, dtype=bool) & matched[..., None]  # (A, T, G)
        gt_matched = gt_matched | hit
        ignored = matched & jnp.take_along_axis(gt_ig_full, m[..., None], axis=-1)[..., 0]
        return gt_matched, (matched, ignored)

    init = jnp.zeros((num_a, num_t, num_g), dtype=bool)
    _, (det_matched, det_ig) = lax.scan(step, init, (iou, pair_ok))
    det_matched = jnp.moveaxis(det_matched, 0, -1)  # (A, T, D)
    det_ig = jnp.moveaxis(det_ig, 0, -1)

    # unmatched detections outside the area range are ignored too
    det_out = (det_area[None, :] < area_rngs[:, 0:1]) | (det_area[None, :] > area_rngs[:, 1:2])  # (A, D)
    det_ig = det_ig | (~det_matched & det_out[:, None, :])
    return det_matched, det_ig, gt_ig


@jax.jit
def _bbox_iou_and_area(det_boxes: Array, gt_boxes: Array, gt_crowd: Array) -> Tuple[Array, Array]:
    """Batched (N, D, G) box IoU with crowd columns + (N, D) det areas."""
    iou = jax.vmap(_crowd_box_iou)(det_boxes, gt_boxes, gt_crowd)
    det_area = jax.vmap(box_area)(det_boxes)
    return iou, det_area


def _mean_valid(s: Array) -> Array:
    """pycocotools summarize: mean over cells > -1, or -1 if none."""
    valid = s > -1
    n = valid.sum()
    return jnp.where(n > 0, jnp.where(valid, s, 0.0).sum() / jnp.maximum(n, 1), -1.0)


@partial(jax.jit, static_argnames=("num_k", "max_dets", "t50", "t75", "return_tables"))
def _match_and_accumulate(
    iou, det_area, det_labels, det_valid, gt_labels_pad, gt_valid, gt_crowd, gt_area,
    iou_thrs, area_rngs, det_scores, classes, rec_thrs, rec_dsign, *, num_k: int,
    max_dets: Tuple[int, ...], t50: Tuple[int, ...] = (), t75: Tuple[int, ...] = (),
    return_tables: bool = False,
):
    """Matching + accumulation + summarization as ONE compiled program.

    Only ~a dozen scalars plus the per-class vectors leave the device — the
    ``(T,R,K,A,M)`` tables (several MB at val2017 scale; the dominant cost
    over a remote-TPU link) are returned only for ``extended_summary``."""
    det_matched, det_ignored, gt_ignored = jax.vmap(
        _match_one_image, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None, None)
    )(iou, det_area, det_labels, det_valid, gt_labels_pad, gt_valid, gt_crowd, gt_area, iou_thrs, area_rngs)

    def to_class_idx(labels, valid):
        flat = labels.reshape(-1)
        pos = jnp.clip(jnp.searchsorted(classes, flat), 0, num_k - 1)
        return jnp.where(valid.reshape(-1) & (classes[pos] == flat), pos, num_k).astype(jnp.int32).reshape(labels.shape)

    det_class = to_class_idx(det_labels, det_valid)
    gt_class = to_class_idx(gt_labels_pad, gt_valid)
    # rank within (image, class) in the per-image score order — the
    # pycocotools per-image-class [:maxdet] cut as a static-shape mask
    num_d = det_labels.shape[1]
    same = (det_labels[:, :, None] == det_labels[:, None, :]) & det_valid[:, :, None] & det_valid[:, None, :]
    tri = jnp.tril(jnp.ones((num_d, num_d), bool), -1)
    det_rank = (same & tri[None]).sum(-1).astype(jnp.int32)
    precision, recall, scores, npig = _accumulate_device(
        det_matched, det_ignored, gt_ignored, det_scores, det_class, det_rank, gt_class,
        rec_thrs, num_k, max_dets, rec_dsign,
    )

    # ---- pycocotools summarize, on device (area 0 = "all", last maxdet)
    out = {
        "map": _mean_valid(precision[:, :, :, 0, -1]),
        "map_50": _mean_valid(precision[list(t50), :, :, 0, -1]) if t50 else jnp.asarray(-1.0),
        "map_75": _mean_valid(precision[list(t75), :, :, 0, -1]) if t75 else jnp.asarray(-1.0),
        "map_small": _mean_valid(precision[:, :, :, 1, -1]),
        "map_medium": _mean_valid(precision[:, :, :, 2, -1]),
        "map_large": _mean_valid(precision[:, :, :, 3, -1]),
        "mar_small": _mean_valid(recall[:, :, 1, -1]),
        "mar_medium": _mean_valid(recall[:, :, 2, -1]),
        "mar_large": _mean_valid(recall[:, :, 3, -1]),
        "mar_per_mdet": jnp.stack([_mean_valid(recall[:, :, 0, mi]) for mi in range(len(max_dets))]),
        "map_per_class": jax.vmap(lambda k: _mean_valid(precision[:, :, k, 0, -1]))(jnp.arange(num_k)),
        "mar_per_class": jax.vmap(lambda k: _mean_valid(recall[:, k, 0, -1]))(jnp.arange(num_k)),
    }
    if return_tables:
        out["precision"], out["recall"], out["scores"] = precision, recall, scores
    return out


def _segmented_scan(values: Array, is_boundary: Array, combine, reverse: bool = False) -> Array:
    """Segmented inclusive scan along the last axis.

    ``is_boundary[i]`` marks the FIRST element of a segment in scan
    direction (for ``reverse=True`` pass segment-END flags). The classic
    associative segmented-scan operator: a flagged element resets the
    carry, so segments never leak into each other.
    """
    def op(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, combine(va, vb)), fa | fb

    out, _ = lax.associative_scan(op, (values, is_boundary), reverse=reverse, axis=values.ndim - 1)
    return out


def _rec_grid_dsigns(rec_thrs: np.ndarray) -> Optional[np.ndarray]:
    """Exact-comparison data for a uniform recall grid, or None.

    pycocotools compares FLOAT64 ``rc = tp/npig`` against ``linspace(0,1,R)``
    with ``searchsorted(..., 'left')``; a float32 device comparison flips
    slots whenever ``tp/npig`` lands within f32 noise of a grid point (e.g.
    7/20 vs 0.35 — f64 says <, f32 says ==). For the uniform grid
    ``r_j ≈ j/M`` (M = R-1) the f64 comparison reduces to INTEGERS:
    ``|j·npig − M·tp| ≥ 1`` decides outright (the relative fp errors are
    ~1e-16, far below the 1/(M·npig) rational gap), and at exact rational
    equality ``f64(tp/npig) == f64(j/M)`` — rounding depends only on the
    real value — so the tie resolves to the host-computable comparison
    ``r_j <= f64(j)/f64(M)``. Returns that ``(R,) int32`` tie-sign array,
    or None when the grid is not uniform (callers fall back to f32).
    """
    r = np.asarray(rec_thrs, np.float64)
    m = len(r) - 1
    if m < 1 or abs(r[0]) > 0 or abs(r[-1] - 1.0) > 0:
        return None
    if np.max(np.abs(r - np.arange(len(r)) / m)) > 1e-12:
        return None
    return (r <= np.arange(len(r), dtype=np.float64) / np.float64(m)).astype(np.int32)


def _accumulate_device(
    det_matched: Array,  # (N, A, T, D) bool
    det_ignored: Array,  # (N, A, T, D) bool
    gt_ignored: Array,  # (N, A, G) bool
    det_scores: Array,  # (N, D) f32
    det_class: Array,  # (N, D) int32 in [0, K] (K = invalid/padded)
    det_rank: Array,  # (N, D) int32: rank within (image, class), score order
    gt_class: Array,  # (N, G) int32 in [0, K]
    rec_thrs: Array,  # (R,) f32
    num_k: int,
    max_dets: Tuple[int, ...],
    rec_dsign: Optional[Array] = None,  # (R,) int32 from _rec_grid_dsigns
) -> Tuple[Array, Array, Array, Array]:
    """pycocotools ``accumulate`` as one static-shape device program.

    Returns ``precision (T,R,K,A,M)``, ``recall (T,K,A,M)``,
    ``scores (T,R,K,A,M)``, ``npig (A,K)`` — classes with ``npig == 0`` are
    already masked to ``-1`` like pycocotools leaves them uninitialized.
    """
    n_imgs, num_a, num_t, num_d = det_matched.shape
    num_r = rec_thrs.shape[0]
    num_m = len(max_dets)
    # pycocotools' f64 eps: as an f32 constant it is absorbed whenever
    # tp+fp >= 1 (matching the reference value post-cast) yet still guards
    # the tp+fp == 0 division; the f32 eps would bias precision low ~1e-7
    eps = jnp.float32(np.spacing(np.float64(1)))
    grid_m = num_r - 1

    # ---- one stable sort: class ascending, score descending, position-stable
    flat_class = det_class.reshape(-1)
    flat_scores = det_scores.reshape(-1)
    order = jnp.lexsort((-flat_scores, flat_class))
    cls_s = flat_class[order]  # (ND,) non-decreasing
    score_s = flat_scores[order]
    rank_s = det_rank.reshape(-1)[order]
    seg_start = jnp.concatenate([jnp.ones(1, bool), cls_s[1:] != cls_s[:-1]])
    seg_end = jnp.concatenate([cls_s[1:] != cls_s[:-1], jnp.ones(1, bool)])

    # (A, T, ND) match/ignore views in sorted order
    dtm_s = det_matched.transpose(1, 2, 0, 3).reshape(num_a, num_t, -1)[:, :, order]
    dtig_s = det_ignored.transpose(1, 2, 0, 3).reshape(num_a, num_t, -1)[:, :, order]
    real = cls_s < num_k  # padded/invalid dets carry class K

    # ---- npig per (area, class): non-ignored gt count (exact int32)
    gt_oh = jax.nn.one_hot(gt_class.reshape(-1), num_k, dtype=jnp.int32)  # (NG, K)
    npig = jnp.einsum("ag,gk->ak", (~gt_ignored).transpose(1, 0, 2).reshape(num_a, -1).astype(jnp.int32), gt_oh)

    mdets = jnp.asarray(max_dets, jnp.int32)  # (M,)
    keep = real[None, :] & (rank_s[None, :] < mdets[:, None])  # (M, ND)

    def count_thrs_leq(tp_int: Array, npig_int: Array) -> Array:
        """``#{j: rec_thrs[j] <= tp/npig}`` with pycocotools' f64 semantics.

        Uniform grid: exact integer arithmetic + the precomputed deviation
        signs. Custom grid: f32 searchsorted (boundary slots may differ from
        an f64 reference by one where rc collides with a threshold).
        """
        if rec_dsign is None:
            rc = tp_int.astype(jnp.float32) / jnp.maximum(npig_int, 1).astype(jnp.float32)
            return jnp.searchsorted(rec_thrs, rc, side="right").astype(jnp.int32)
        npig_safe = jnp.maximum(npig_int, 1)
        prod = grid_m * tp_int
        q = prod // npig_safe
        rem = prod - q * npig_safe
        cnt_strict = jnp.minimum(jnp.where(rem > 0, q + 1, q), num_r)
        eq_extra = jnp.where((rem == 0) & (q <= grid_m), rec_dsign[jnp.clip(q, 0, grid_m)], 0)
        return cnt_strict + eq_extra

    def per_atm(dtm_row: Array, dtig_row: Array, keep_row: Array, npig_row: Array):
        """One (area, threshold, maxdet) combination over the sorted axis."""
        tp = (dtm_row & ~dtig_row & keep_row).astype(jnp.int32)
        fp = (~dtm_row & ~dtig_row & keep_row).astype(jnp.int32)
        tp_cum = _segmented_scan(tp, seg_start, jnp.add)
        fp_cum = _segmented_scan(fp, seg_start, jnp.add)
        npig_here = npig_row[jnp.clip(cls_s, 0, num_k - 1)]
        tp_f, fp_f = tp_cum.astype(jnp.float32), fp_cum.astype(jnp.float32)
        pr = tp_f / (tp_f + fp_f + eps)
        pr_env = _segmented_scan(pr, seg_end, jnp.maximum, reverse=True)

        # span of recall-threshold slots served by each position: [cnt_prev, cnt)
        cnt = count_thrs_leq(tp_cum, npig_here)
        cnt_prev = jnp.where(seg_start, 0, jnp.concatenate([jnp.zeros(1, jnp.int32), cnt[:-1]]))
        nonempty = (cnt > cnt_prev) & real
        k_idx = jnp.where(nonempty, cls_s, num_k)
        j_idx = jnp.where(nonempty, cnt_prev, num_r)

        # scatter span starts + per-class terminators, then forward-fill
        tbl = jnp.zeros((num_k + 1, num_r + 1, 2), jnp.float32)
        wrote = jnp.zeros((num_k + 1, num_r + 1), bool)
        vals = jnp.stack([pr_env, score_s], -1)
        tbl = tbl.at[k_idx, j_idx].set(vals, mode="drop")
        wrote = wrote.at[k_idx, j_idx].set(True, mode="drop")
        # terminator at each class's final slot count: 0.0 fills the tail.
        # clamp: a class with gts but NO dets has an empty segment, and
        # segment_max's identity is INT32_MIN — pycocotools gives recall 0
        # ('rc[-1] if nd else 0') and unclamped it would both corrupt the
        # mar_* means and overflow the integer grid comparison
        end_tp = jnp.maximum(
            jax.ops.segment_max(jnp.where(real, tp_cum, 0), jnp.clip(cls_s, 0, num_k), num_segments=num_k + 1)[:num_k],
            0,
        )
        rc_end = jnp.where(npig_row > 0, end_tp.astype(jnp.float32) / jnp.maximum(npig_row, 1).astype(jnp.float32), 0.0)
        cnt_end = count_thrs_leq(end_tp, npig_row)
        tbl = tbl.at[jnp.arange(num_k), cnt_end].set(0.0, mode="drop")
        wrote = wrote.at[jnp.arange(num_k), cnt_end].set(True, mode="drop")

        def fill(a, b):
            (va, wa), (vb, wb) = a, b
            return jnp.where(wb, vb, va), wa | wb

        filled, _ = lax.associative_scan(fill, (tbl, wrote[..., None]), axis=1)
        filled = filled[:num_k, :num_r]  # (K, R, 2)
        ok = npig_row > 0
        precision_row = jnp.where(ok[:, None], filled[..., 0], -1.0)
        scores_row = jnp.where(ok[:, None], filled[..., 1], -1.0)
        recall_row = jnp.where(ok, rc_end, -1.0)
        return precision_row, recall_row, scores_row

    # vmap over M (keep), then T, then A
    per_t = jax.vmap(per_atm, in_axes=(None, None, 0, None))  # over M
    per_at = jax.vmap(per_t, in_axes=(0, 0, None, None))  # over T
    per_all = jax.vmap(per_at, in_axes=(0, 0, None, 0))  # over A
    precision, recall, scores = per_all(dtm_s, dtig_s, keep, npig)  # (A,T,M,K,R) / (A,T,M,K)
    precision = precision.transpose(1, 4, 3, 0, 2)  # (T,R,K,A,M)
    scores = scores.transpose(1, 4, 3, 0, 2)
    recall = recall.transpose(1, 3, 0, 2)  # (T,K,A,M)
    return precision, recall, scores, npig


class COCOEvaluationResult(dict):
    """Result dict allowing attribute access (reference ``_mean_ap.py:74-92``)."""

    def __getattr__(self, key: str) -> Any:
        if key in self:
            return self[key]
        raise AttributeError(f"No such attribute: {key}")


# traverse like a plain dict under jax.tree_util (dict subclasses are
# otherwise opaque leaves, which breaks generic pytree post-processing)
jax.tree_util.register_pytree_node(
    COCOEvaluationResult,
    lambda d: (tuple(d[k] for k in sorted(d)), tuple(sorted(d))),
    lambda keys, vals: COCOEvaluationResult(zip(keys, vals)),
)


def coco_mean_average_precision(
    preds: Sequence[Dict[str, Any]],
    target: Sequence[Dict[str, Any]],
    box_format: str = "xyxy",
    iou_thresholds: Optional[Sequence[float]] = None,
    rec_thresholds: Optional[Sequence[float]] = None,
    max_detection_thresholds: Optional[Sequence[int]] = None,
    class_metrics: bool = False,
    extended_summary: bool = False,
    average: str = "macro",
    iou_type: str = "bbox",
) -> Dict[str, Any]:
    """Full COCO-style evaluation over a dataset of per-image dicts.

    Matches pycocotools ``COCOeval`` output (reference ``mean_ap.py:520-647``).
    ``preds[i]``: ``scores``/``labels`` plus ``boxes`` (``iou_type="bbox"``) or
    ``masks`` (``iou_type="segm"``: ``(n, H, W)`` binary arrays or RLE dicts);
    ``target[i]``: same geometry key, ``labels``, optional ``iscrowd``/``area``.
    Mask IoU/areas run through the native C++ RLE codec
    (:mod:`torchmetrics_tpu.functional.detection.mask_utils`).

    .. note::
        With the default (uniform 101-point) ``rec_thresholds`` grid the
        recall→threshold-slot assignment reproduces pycocotools' float64
        comparison EXACTLY via integer arithmetic. A **custom non-uniform**
        grid falls back to an f32 ``searchsorted`` on device: when a recall
        value ``tp/npig`` collides with a threshold within f32 noise the slot
        can differ by one from an f64 reference. Exact semantics require a
        uniform grid spanning exactly ``[0, 1]`` (``np.linspace(0, 1, R)``
        for any resolution ``R``); any other grid takes the f32 fallback.
    """
    if iou_type not in ("bbox", "segm"):
        raise ValueError(f"Expected argument `iou_type` to be one of ('bbox', 'segm') but got {iou_type}")
    iou_thrs = np.asarray(iou_thresholds if iou_thresholds is not None else DEFAULT_IOU_THRESHOLDS, np.float64)
    rec_thrs = np.asarray(rec_thresholds if rec_thresholds is not None else DEFAULT_REC_THRESHOLDS, np.float64)
    max_dets = sorted(max_detection_thresholds if max_detection_thresholds is not None else DEFAULT_MAX_DETECTIONS)
    area_rngs = np.asarray(list(DEFAULT_AREA_RANGES.values()), np.float64)
    n_imgs = len(preds)
    maxdet_last = max_dets[-1]

    if iou_type == "segm":
        from torchmetrics_tpu.functional.detection import mask_utils

        def _to_rles(items):
            masks = items.get("masks", [])
            if isinstance(masks, dict):
                masks = [masks]
            rles = []
            for m in masks:
                rles.append(m if isinstance(m, dict) else mask_utils.encode(np.asarray(m)))
            return rles

    det_boxes_l, det_scores_l, det_labels_l, det_rles_l, det_marea_l = [], [], [], [], []
    gt_boxes_l, gt_labels_l, gt_crowd_l, gt_area_l, gt_rles_l = [], [], [], [], []
    for p, t in zip(preds, target):
        scores = np.asarray(p["scores"], np.float64).reshape(-1)
        labels = np.asarray(p["labels"]).reshape(-1)
        order = np.argsort(-scores, kind="mergesort")[:maxdet_last]
        scores, labels = scores[order], labels[order]
        if iou_type == "bbox":
            boxes = np.asarray(p["boxes"], np.float64).reshape(-1, 4)[order]
            if box_format != "xyxy":
                boxes = np.asarray(box_convert(boxes, box_format, "xyxy")) if boxes.size else boxes
            det_boxes_l.append(boxes)
        else:
            rles = _to_rles(p)
            rles = [rles[i] for i in order]
            det_rles_l.append(rles)
            det_marea_l.append(np.asarray(mask_utils.area(rles)).reshape(-1) if rles else np.zeros(0))
        det_scores_l.append(scores)
        det_labels_l.append(labels)

        glabels = np.asarray(t["labels"]).reshape(-1)
        crowd = np.asarray(t.get("iscrowd", np.zeros(len(glabels)))).reshape(-1).astype(bool)
        area = t.get("area")
        if iou_type == "bbox":
            gboxes = np.asarray(t["boxes"], np.float64).reshape(-1, 4)
            if box_format != "xyxy":
                gboxes = np.asarray(box_convert(gboxes, box_format, "xyxy")) if gboxes.size else gboxes
            gt_boxes_l.append(gboxes)
            default_area = (gboxes[:, 2] - gboxes[:, 0]) * (gboxes[:, 3] - gboxes[:, 1])
        else:
            grles = _to_rles(t)
            gt_rles_l.append(grles)
            default_area = np.asarray(mask_utils.area(grles)).reshape(-1) if grles else np.zeros(0)
        area = (
            np.asarray(area, np.float64).reshape(-1)
            if area is not None and np.asarray(area).size
            else default_area
        )
        gt_labels_l.append(glabels)
        gt_crowd_l.append(crowd)
        gt_area_l.append(area)

    if average == "micro":
        # micro averaging pools every class into one (reference ``mean_ap.py:490-497``)
        det_labels_l = [np.zeros_like(x) for x in det_labels_l]
        gt_labels_l = [np.zeros_like(x) for x in gt_labels_l]

    all_labels = np.concatenate([np.concatenate(det_labels_l) if det_labels_l else np.zeros(0)]
                                + [np.concatenate(gt_labels_l) if gt_labels_l else np.zeros(0)])
    classes = np.unique(all_labels.astype(np.int64)) if all_labels.size else np.zeros(0, np.int64)
    num_t, num_r, num_k, num_a, num_m = len(iou_thrs), len(rec_thrs), len(classes), len(area_rngs), len(max_dets)

    if n_imgs and num_k:
        pad_d = _round_up(max(1, max(len(s) for s in det_scores_l)))
        pad_g = _round_up(max(1, max(len(x) for x in gt_labels_l)))
        det_scores, det_valid = _pack_ragged(det_scores_l, pad_d)
        det_labels, _ = _pack_ragged(det_labels_l, pad_d, dtype=np.int64)
        gt_labels, gt_valid = _pack_ragged(gt_labels_l, pad_g, dtype=np.int64)
        gt_crowd, _ = _pack_ragged(gt_crowd_l, pad_g, dtype=bool)
        gt_area, _ = _pack_ragged(gt_area_l, pad_g)
        # pad labels with a sentinel no real class uses so padded rows never match
        det_labels = np.where(det_valid, det_labels, -1)
        gt_labels_pad = np.where(gt_valid, gt_labels, -2)

        if iou_type == "bbox":
            det_boxes, _ = _pack_ragged(det_boxes_l, pad_d, 4)
            gt_boxes, _ = _pack_ragged(gt_boxes_l, pad_g, 4)
            iou_all, det_area = _bbox_iou_and_area(
                jnp.asarray(det_boxes), jnp.asarray(gt_boxes), jnp.asarray(gt_crowd)
            )
        else:
            # per-image crowd-aware mask IoU via the native RLE codec (host)
            iou_np = np.zeros((n_imgs, pad_d, pad_g), np.float32)
            from torchmetrics_tpu.functional.detection import mask_utils

            for i in range(n_imgs):
                d_rles, g_rles = det_rles_l[i], gt_rles_l[i]
                if d_rles and g_rles:
                    iou_np[i, : len(d_rles), : len(g_rles)] = mask_utils.iou(
                        d_rles, g_rles, iscrowd=gt_crowd_l[i].astype(np.uint8)
                    )
            iou_all = jnp.asarray(iou_np)
            det_area_np, _ = _pack_ragged(det_marea_l, pad_d)
            det_area = jnp.asarray(det_area_np)

        summ = _match_and_accumulate(
            iou_all,
            det_area,
            jnp.asarray(det_labels),
            jnp.asarray(det_valid),
            jnp.asarray(gt_labels_pad),
            jnp.asarray(gt_valid),
            jnp.asarray(gt_crowd),
            jnp.asarray(gt_area),
            jnp.asarray(iou_thrs, jnp.float32),
            jnp.asarray(area_rngs, jnp.float32),
            jnp.asarray(det_scores),
            jnp.asarray(classes),
            jnp.asarray(rec_thrs, jnp.float32),
            (lambda d: None if d is None else jnp.asarray(d))(_rec_grid_dsigns(rec_thrs)),
            num_k=num_k,
            max_dets=tuple(max_dets),
            t50=tuple(int(i) for i in np.where(np.isclose(iou_thrs, 0.5))[0]),
            t75=tuple(int(i) for i in np.where(np.isclose(iou_thrs, 0.75))[0]),
            return_tables=extended_summary,
        )
    else:
        neg1 = jnp.asarray(-1.0, jnp.float32)
        summ = {key: neg1 for key in (
            "map", "map_50", "map_75", "map_small", "map_medium", "map_large",
            "mar_small", "mar_medium", "mar_large",
        )}
        summ["mar_per_mdet"] = jnp.full((num_m,), -1.0, jnp.float32)
        summ["map_per_class"] = jnp.full((max(num_k, 1),), -1.0, jnp.float32)
        summ["mar_per_class"] = jnp.full((max(num_k, 1),), -1.0, jnp.float32)
        if extended_summary:
            summ["precision"] = jnp.full((num_t, num_r, num_k, num_a, num_m), -1.0, jnp.float32)
            summ["recall"] = jnp.full((num_t, num_k, num_a, num_m), -1.0, jnp.float32)
            summ["scores"] = jnp.full((num_t, num_r, num_k, num_a, num_m), -1.0, jnp.float32)

    res: Dict[str, Any] = COCOEvaluationResult()
    for key in ("map", "map_50", "map_75", "map_small", "map_medium", "map_large"):
        res[key] = summ[key].astype(jnp.float32)
    for mi, mdet in enumerate(max_dets):
        res[f"mar_{mdet}"] = summ["mar_per_mdet"][mi].astype(jnp.float32)
    for key in ("mar_small", "mar_medium", "mar_large"):
        res[key] = summ[key].astype(jnp.float32)

    if class_metrics and num_k:
        res["map_per_class"] = summ["map_per_class"].astype(jnp.float32)
        res[f"mar_{maxdet_last}_per_class"] = summ["mar_per_class"].astype(jnp.float32)
    else:
        res["map_per_class"] = jnp.asarray(-1.0, jnp.float32)
        res[f"mar_{maxdet_last}_per_class"] = jnp.asarray(-1.0, jnp.float32)
    res["classes"] = jnp.asarray(classes, jnp.int32)

    if extended_summary:
        res["precision"] = jnp.asarray(summ["precision"], jnp.float32)
        res["recall"] = jnp.asarray(summ["recall"], jnp.float32)
        res["scores"] = jnp.asarray(summ["scores"], jnp.float32)
    return res
