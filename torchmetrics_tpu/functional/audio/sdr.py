# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""SDR family (reference ``functional/audio/sdr.py``).

The distortion-filter solve is expressed as an FFT autocorrelation plus a
dense symmetric-Toeplitz system solved with ``jnp.linalg.solve`` — batched
linear algebra that XLA maps onto the MXU, replacing the reference's optional
``fast_bss_eval`` conjugate-gradient path (``sdr.py:162-184``).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _symmetric_toeplitz(vector: Array) -> Array:
    """Symmetric Toeplitz matrix from its first row (reference ``sdr.py:28-53``)."""
    v_len = vector.shape[-1]
    idx = jnp.abs(jnp.arange(v_len)[:, None] - jnp.arange(v_len)[None, :])
    return vector[..., idx]


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int):
    """FFT-based auto/cross correlation (reference ``sdr.py:56-87``)."""
    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :corr_len]
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return r_0, b


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR via the optimal distortion filter (reference ``sdr.py:90-238``).

    ``use_cg_iter`` is accepted for API parity but the dense batched solve is
    used always — on TPU the direct solve IS the fast path.
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _check_same_shape(preds, target)
    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    preds = preds.astype(dtype)
    target = target.astype(dtype)

    if zero_mean:
        preds = preds - preds.mean(axis=-1, keepdims=True)
        target = target - target.mean(axis=-1, keepdims=True)

    target = target / jnp.clip(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-6)
    preds = preds / jnp.clip(jnp.linalg.norm(preds, axis=-1, keepdims=True), 1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)
    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)

    r = _symmetric_toeplitz(r_0)
    sol = jnp.linalg.solve(r, b[..., None])[..., 0]
    coh = jnp.einsum("...l,...l->...", b, sol)
    ratio = coh / (1 - coh)
    return (10.0 * jnp.log10(ratio)).astype(jnp.float32 if dtype == jnp.float32 else dtype)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR (reference ``sdr.py:201-238``)."""
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)


def source_aggregated_signal_distortion_ratio(
    preds: Array,
    target: Array,
    scale_invariant: bool = True,
    zero_mean: bool = False,
) -> Array:
    """SA-SDR over all speakers at once (reference ``sdr.py:241-303``)."""
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim < 2:
        raise RuntimeError(f"The preds and target should have the shape (..., spk, time), but {preds.shape} found")
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    if scale_invariant:
        alpha = (jnp.sum(preds * target, axis=(-1, -2), keepdims=True) + eps) / (
            jnp.sum(target**2, axis=(-1, -2), keepdims=True) + eps
        )
        target = alpha * target
    distortion = target - preds
    val = (jnp.sum(target**2, axis=(-1, -2)) + eps) / (jnp.sum(distortion**2, axis=(-1, -2)) + eps)
    return 10 * jnp.log10(val)
