# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""DNSMOS (reference ``functional/audio/dnsmos.py:22-280``).

Full pipeline implemented natively except the ONNX model inference itself:
the 120-band mel-spectrogram (librosa's Slaney-mel conventions) is computed
in numpy/scipy here, so only ``onnxruntime`` plus the two published DNSMOS
model files are required — the reference additionally needs ``librosa`` and
``requests``. There is no network egress in this environment, so the models
must be placed locally (see :data:`DNSMOS_DIR`); the reference downloads them
from the microsoft/DNS-Challenge repository on first use.
"""
from __future__ import annotations

import os
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.imports import _ONNXRUNTIME_AVAILABLE

Array = jax.Array

SAMPLING_RATE = 16000
INPUT_LENGTH = 9.01


def _dnsmos_dir() -> str:
    """Model directory, read per call so TM_TPU_DNSMOS_DIR can be set late."""
    return os.environ.get("TM_TPU_DNSMOS_DIR", "~/.torchmetrics_tpu/DNSMOS")


# --------------------------------------------------------- native mel features


@lru_cache(maxsize=4)
def _mel_filterbank(sr: int = 16000, n_fft: int = 321, n_mels: int = 120) -> np.ndarray:
    """Slaney-mel triangular filterbank with Slaney normalization (librosa's
    defaults for ``melspectrogram``)."""

    def hz_to_mel(f):
        f = np.asarray(f, np.float64)
        # Slaney scale: linear below 1 kHz, log above
        mel = f / (200.0 / 3)
        log_region = f >= 1000.0
        mel = np.where(log_region, 15.0 + np.log(np.maximum(f, 1e-10) / 1000.0) / (np.log(6.4) / 27.0), mel)
        return mel

    def mel_to_hz(m):
        m = np.asarray(m, np.float64)
        f = m * (200.0 / 3)
        log_region = m >= 15.0
        return np.where(log_region, 1000.0 * np.exp((np.log(6.4) / 27.0) * (m - 15.0)), f)

    fmax = sr / 2
    mel_pts = mel_to_hz(np.linspace(hz_to_mel(0.0), hz_to_mel(fmax), n_mels + 2))
    fft_freqs = np.linspace(0, sr / 2, 1 + n_fft // 2)
    weights = np.zeros((n_mels, len(fft_freqs)))
    fdiff = np.diff(mel_pts)
    ramps = mel_pts[:, None] - fft_freqs[None, :]
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    # Slaney normalization: each filter integrates to ~1 over Hz
    enorm = 2.0 / (mel_pts[2 : n_mels + 2] - mel_pts[:n_mels])
    return weights * enorm[:, None]


def _audio_melspec(audio: np.ndarray, n_mels: int = 120, frame_size: int = 320, hop_length: int = 160) -> np.ndarray:
    """dB-scaled mel-spectrogram matching the reference's librosa call
    (``dnsmos.py:121-150``: ``n_fft=frame_size+1``, centered, Hann)."""
    n_fft = frame_size + 1
    shape = audio.shape
    audio = np.asarray(audio, np.float64).reshape(-1, shape[-1])
    pad = n_fft // 2
    audio = np.pad(audio, ((0, 0), (pad, pad)), mode="reflect")
    window = np.hanning(n_fft + 1)[:-1]  # periodic Hann (librosa fftbins=True)
    n_frames = 1 + (audio.shape[-1] - n_fft) // hop_length
    idx = np.arange(n_fft)[None, :] + hop_length * np.arange(n_frames)[:, None]
    frames = audio[:, idx] * window  # (B, T', n_fft)
    spec = np.abs(np.fft.rfft(frames, n=n_fft, axis=-1)) ** 2
    mel = spec @ _mel_filterbank(SAMPLING_RATE, n_fft, n_mels).T  # (B, T', n_mels)
    # librosa power_to_db(ref=np.max, top_db=80), then the DNSMOS (x+40)/40
    out = np.empty_like(mel)
    for b in range(mel.shape[0]):
        ref = max(mel[b].max(), 1e-10)
        db = 10.0 * np.log10(np.maximum(mel[b], 1e-10) / ref)
        db = np.maximum(db, db.max() - 80.0)
        out[b] = (db + 40.0) / 40.0
    return out.reshape(shape[:-1] + out.shape[1:])


def _polyfit_val(mos: np.ndarray, personalized: bool) -> np.ndarray:
    """Polynomial calibration of the raw model outputs (reference
    ``dnsmos.py:157-179``; published DNSMOS coefficients)."""
    if personalized:
        p_ovr = np.poly1d([-0.00533021, 0.005101, 1.18058466, -0.11236046])
        p_sig = np.poly1d([-0.01019296, 0.02751166, 1.19576786, -0.24348726])
        p_bak = np.poly1d([-0.04976499, 0.44276479, -0.1644611, 0.96883132])
    else:
        p_ovr = np.poly1d([-0.06766283, 1.11546468, 0.04602535])
        p_sig = np.poly1d([-0.08397278, 1.22083953, 0.0052439])
        p_bak = np.poly1d([-0.13166888, 1.60915514, -0.39604546])
    mos[..., 1] = p_sig(mos[..., 1])
    mos[..., 2] = p_bak(mos[..., 2])
    mos[..., 3] = p_ovr(mos[..., 3])
    return mos


@lru_cache(maxsize=4)
def _load_session(path: str, num_threads: Optional[int] = None):
    """Load an onnxruntime CPU session for a local model file."""
    import onnxruntime as ort

    path = os.path.expanduser(path)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"DNSMOS model file {path!r} not found. This environment has no network egress; download"
            " 'DNSMOS/model_v8.onnx', 'DNSMOS/sig_bak_ovr.onnx' and 'pDNSMOS/sig_bak_ovr.onnx' from the"
            " microsoft/DNS-Challenge repository and place them under"
            f" {_dnsmos_dir()} (override with TM_TPU_DNSMOS_DIR)."
        )
    opts = ort.SessionOptions()
    if num_threads is not None:
        opts.inter_op_num_threads = num_threads
        opts.intra_op_num_threads = num_threads
    return ort.InferenceSession(path, providers=["CPUExecutionProvider"], sess_options=opts)


def _dnsmos_host(preds: np.ndarray, fs: int, personalized: bool, num_threads: Optional[int]) -> np.ndarray:
    """Host pipeline (resample -> segments -> mel + ONNX -> calibration)."""
    audio = np.asarray(preds, np.float64)
    if fs != SAMPLING_RATE:
        from scipy.signal import resample_poly

        from math import gcd

        g = gcd(SAMPLING_RATE, fs)
        audio = resample_poly(audio, SAMPLING_RATE // g, fs // g, axis=-1)

    sess = _load_session(f"{_dnsmos_dir()}/{'p' if personalized else ''}DNSMOS/sig_bak_ovr.onnx", num_threads)
    p808_sess = _load_session(f"{_dnsmos_dir()}/DNSMOS/model_v8.onnx", num_threads)

    if audio.shape[-1] == 0:
        raise ValueError("DNSMOS requires non-empty audio input.")
    len_samples = int(INPUT_LENGTH * SAMPLING_RATE)
    while audio.shape[-1] < len_samples:
        audio = np.concatenate([audio, audio], axis=-1)
    num_hops = int(np.floor(audio.shape[-1] / SAMPLING_RATE) - INPUT_LENGTH) + 1

    moss = []
    for idx in range(num_hops):
        seg = audio[..., idx * SAMPLING_RATE : int((idx + INPUT_LENGTH) * SAMPLING_RATE)]
        if seg.shape[-1] < len_samples:
            continue
        shape = seg.shape
        seg2 = seg.reshape(-1, shape[-1]).astype(np.float32)
        mel_features = _audio_melspec(seg2[..., :-160]).astype(np.float32)
        p808_mos = p808_sess.run(None, {"input_1": mel_features})[0].reshape(seg2.shape[0], 1)
        raw = sess.run(None, {"input_1": seg2})[0]  # (B, 3): sig, bak, ovr
        mos = np.concatenate([p808_mos, raw], axis=-1)  # (B, 4)
        mos = _polyfit_val(mos, personalized)
        moss.append(mos.reshape(shape[:-1] + (4,)))
    return np.mean(np.stack(moss), axis=0).astype(np.float32)


def deep_noise_suppression_mean_opinion_score(
    preds: Array, fs: int, personalized: bool = False, device: Optional[str] = None, num_threads: Optional[int] = None
) -> Array:
    """DNSMOS ``[p808_mos, mos_sig, mos_bak, mos_ovr]`` per sample (reference
    ``dnsmos.py:182-280``). The host pipeline runs behind ``jax.pure_callback``
    so the metric stays jit/``shard_map`` traceable like PESQ/STOI."""
    if not _ONNXRUNTIME_AVAILABLE:
        raise ModuleNotFoundError(
            "DNSMOS metric requires that onnxruntime is installed."
            " Install as `pip install onnxruntime` (the mel features are computed natively; librosa is not needed)."
        )
    preds = jnp.asarray(preds)
    if preds.shape[-1] == 0:
        raise ValueError("DNSMOS requires non-empty audio input.")
    out_spec = jax.ShapeDtypeStruct((*preds.shape[:-1], 4), jnp.float32)
    return jax.pure_callback(
        lambda p: _dnsmos_host(np.asarray(p), fs, personalized, num_threads), out_spec, preds
    )
