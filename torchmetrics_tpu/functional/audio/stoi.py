# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Short-time objective intelligibility, implemented natively.

The reference wraps the ``pystoi`` package per-sample on CPU
(``functional/audio/stoi.py:25-96``); here the published algorithm (Taal et
al., 2011 — and the extended variant of Jensen & Taal, 2016) is implemented
directly, following pystoi's exact conventions (nearest-bin third-octave
edges, strict framing, 1e-5 score for too-short signals). The whole pipeline
is host numpy — silent-frame removal makes the shapes data-dependent — and is
exposed through ``jax.pure_callback`` so the metric stays jit/``shard_map``
traceable exactly like the host-callback design it replaces. ``pystoi`` is
not needed; when it is installed the parity test compares against it.

Pipeline: resample to 10 kHz → drop frames more than 40 dB below the loudest
clean frame → 512-point STFT (256 window / 128 hop, Hann) → 15 third-octave
bands from 150 Hz → 384 ms segments (N=30 frames) → per-band clipped
correlation (STOI) or spectrogram-normalized correlation (ESTOI), averaged.
"""
from __future__ import annotations

from functools import lru_cache
from math import gcd
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

FS = 10000  # the algorithm's internal rate
N_FRAME = 256
NFFT = 512
HOP = 128
NUM_BANDS = 15
MIN_FREQ = 150.0
N_SEG = 30  # frames per analysis segment (384 ms)
BETA = -15.0  # lower SDR clip bound
DYN_RANGE = 40.0


@lru_cache(maxsize=8)
def _third_octave_band_matrix() -> np.ndarray:
    """(15, NFFT//2+1) band matrix with pystoi's nearest-bin edge rounding."""
    freqs = np.linspace(0, FS, NFFT + 1)[: NFFT // 2 + 1]
    cfs = MIN_FREQ * 2.0 ** (np.arange(NUM_BANDS) / 3.0)
    lo = cfs * 2 ** (-1 / 6)
    hi = cfs * 2 ** (1 / 6)
    obm = np.zeros((NUM_BANDS, len(freqs)))
    for k in range(NUM_BANDS):
        lo_idx = int(np.argmin(np.abs(freqs - lo[k])))
        hi_idx = int(np.argmin(np.abs(freqs - hi[k])))
        obm[k, lo_idx:hi_idx] = 1.0
    return obm


def _frame(x: np.ndarray) -> np.ndarray:
    """(time,) -> (n_frames, N_FRAME), pystoi's strict ``range(0, len-256, 128)``."""
    starts = np.arange(0, x.shape[-1] - N_FRAME, HOP)
    if len(starts) == 0:
        return np.zeros((0, N_FRAME))
    return x[starts[:, None] + np.arange(N_FRAME)[None, :]]


def _remove_silent_frames(clean: np.ndarray, degraded: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Drop frames > 40 dB below the loudest clean frame, overlap-add the
    survivors back into waveforms (data-dependent output length)."""
    window = np.hanning(N_FRAME + 2)[1:-1]
    frames_c = _frame(clean) * window
    frames_d = _frame(degraded) * window
    if frames_c.shape[0] == 0:
        return np.zeros(0), np.zeros(0)
    energies = 20 * np.log10(np.linalg.norm(frames_c, axis=-1) + 1e-20)
    mask = energies > energies.max() - DYN_RANGE
    frames_c = frames_c[mask]
    frames_d = frames_d[mask]
    n_kept = frames_c.shape[0]
    out_len = (n_kept - 1) * HOP + N_FRAME if n_kept else 0
    out_c = np.zeros(out_len)
    out_d = np.zeros(out_len)
    for i in range(n_kept):  # overlap-add (50% Hann gives unity gain)
        out_c[i * HOP : i * HOP + N_FRAME] += frames_c[i]
        out_d[i * HOP : i * HOP + N_FRAME] += frames_d[i]
    return out_c, out_d


def _band_envelopes(x: np.ndarray) -> np.ndarray:
    """Third-octave band magnitudes per frame: (n_frames, 15)."""
    frames = _frame(x)
    window = np.hanning(N_FRAME + 2)[1:-1]
    spec = np.fft.rfft(frames * window, NFFT, axis=-1)
    power = np.abs(spec) ** 2
    return np.sqrt(power @ _third_octave_band_matrix().T)


def _segments(bands: np.ndarray) -> np.ndarray:
    """(n_frames, 15) -> (n_segments, 15, N_SEG) sliding windows."""
    windows = np.lib.stride_tricks.sliding_window_view(bands, (N_SEG, NUM_BANDS))[:, 0]
    return windows.transpose(0, 2, 1)


def _stoi_correlation(x_seg: np.ndarray, y_seg: np.ndarray) -> float:
    """Classic STOI: per-band normalize + clip + correlate."""
    eps = np.finfo(np.float64).eps
    alpha = np.sqrt((x_seg**2).sum(-1, keepdims=True) / ((y_seg**2).sum(-1, keepdims=True) + eps))
    y_prime = np.minimum(y_seg * alpha, x_seg * (1 + 10 ** (-BETA / 20)))
    x_c = x_seg - x_seg.mean(-1, keepdims=True)
    y_c = y_prime - y_prime.mean(-1, keepdims=True)
    corr = (x_c * y_c).sum(-1) / (
        np.linalg.norm(x_c, axis=-1) * np.linalg.norm(y_c, axis=-1) + eps
    )
    return float(corr.mean())


def _estoi_correlation(x_seg: np.ndarray, y_seg: np.ndarray) -> float:
    """Extended STOI: row+column normalization, mean inner product."""
    eps = np.finfo(np.float64).eps

    def normalize(seg: np.ndarray) -> np.ndarray:
        seg = seg - seg.mean(-1, keepdims=True)
        seg = seg / (np.linalg.norm(seg, axis=-1, keepdims=True) + eps)
        seg = seg - seg.mean(-2, keepdims=True)
        return seg / (np.linalg.norm(seg, axis=-2, keepdims=True) + eps)

    x_n = normalize(x_seg)
    y_n = normalize(y_seg)
    return float((x_n * y_n).sum(-2).mean())


def _resample_to_10k(x: np.ndarray, fs: int) -> np.ndarray:
    if fs == FS:
        return x
    from scipy.signal import resample_poly

    g = gcd(FS, fs)
    return resample_poly(x, FS // g, fs // g, axis=-1)


def _stoi_single(clean: np.ndarray, degraded: np.ndarray, fs: int, extended: bool) -> float:
    clean = _resample_to_10k(np.asarray(clean, np.float64), fs)
    degraded = _resample_to_10k(np.asarray(degraded, np.float64), fs)
    clean, degraded = _remove_silent_frames(clean, degraded)
    x_bands = _band_envelopes(clean)  # (frames, 15)
    y_bands = _band_envelopes(degraded)
    if x_bands.shape[0] < N_SEG:
        # pystoi convention: warn and return a floor score instead of raising
        rank_zero_warn(
            "Not enough non-silent frames for a STOI measurement (need ≥ 30 frames, ~384 ms of"
            f" speech; got {x_bands.shape[0]}). Returning 1e-5.",
            UserWarning,
        )
        return 1e-5
    x_seg = _segments(x_bands)
    y_seg = _segments(y_bands)
    return _estoi_correlation(x_seg, y_seg) if extended else _stoi_correlation(x_seg, y_seg)


def short_time_objective_intelligibility(
    preds: Array, target: Array, fs: int, extended: bool = False, keep_same_device: bool = False
) -> Array:
    """STOI/ESTOI of degraded ``preds`` against clean ``target`` (reference
    ``functional/audio/stoi.py:25-96``, native — no ``pystoi`` needed).

    Runs on host behind ``jax.pure_callback`` (silent-frame removal is
    data-dependent), so the call remains jit/``shard_map`` traceable.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds.shape} and {target.shape}"
        )
    shape = preds.shape

    def host_fn(preds_np, target_np):
        p2 = np.asarray(preds_np, np.float64).reshape(-1, shape[-1])
        t2 = np.asarray(target_np, np.float64).reshape(-1, shape[-1])
        scores = [_stoi_single(t, p, fs, extended) for p, t in zip(p2, t2)]
        return np.asarray(scores, np.float32).reshape(shape[:-1])

    out_spec = jax.ShapeDtypeStruct(shape[:-1], jnp.float32)
    return jax.pure_callback(host_fn, out_spec, preds, target)
