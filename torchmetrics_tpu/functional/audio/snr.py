# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""SNR family (reference ``functional/audio/snr.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR = 10 log10(||target||² / ||target - preds||²) (reference ``snr.py:22-61``)."""
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR = SI-SDR with zero-mean (reference ``snr.py:64-87``)."""
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)


def complex_scale_invariant_signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """C-SI-SNR over complex STFT inputs (reference ``snr.py:90-131``).

    Accepts complex arrays ``(..., frequency, time)`` or real arrays
    ``(..., frequency, time, 2)``.
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if jnp.iscomplexobj(preds):
        preds = jnp.stack([preds.real, preds.imag], axis=-1)
    if jnp.iscomplexobj(target):
        target = jnp.stack([target.real, target.imag], axis=-1)
    if preds.ndim < 3 or preds.shape[-1] != 2 or target.ndim < 3 or target.shape[-1] != 2:
        raise RuntimeError(
            "Predictions and targets are expected to have the shape (..., frequency, time, 2),"
            " but got {} and {}.".format(preds.shape, target.shape)
        )
    preds = preds.reshape(*preds.shape[:-3], -1)
    target = target.reshape(*target.shape[:-3], -1)
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=zero_mean)
