# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Speech-to-reverberation modulation energy ratio, implemented natively.

The reference (``functional/audio/srmr.py:177-305``) translates the SRMR
toolbox into torch but still requires the ``gammatone`` package for ERB
filter coefficients and ``torchaudio`` for IIR filtering. Here both are
native: the Slaney ERB gammatone filter design (Apple TR #35 / Glasberg &
Moore parameters — the same published formulas ``gammatone.filters``
implements) runs in numpy at setup, and the biquad cascades run as a single
``lax.scan`` over time, vectorized across batch × cochlear × modulation
channels — so SRMR needs no optional dependencies at all.

Pipeline (Falk et al., 2010): ERB gammatone filterbank → Hilbert envelope →
8-band modulation filterbank (Q=2) → windowed modulation energy (256 ms / 64
ms hop, Hamming) → ratio of low (bands 1-4) to high (bands 5..K*) modulation
energy, with K* chosen from the 90%-energy ERB bandwidth.
"""
from __future__ import annotations

from functools import lru_cache
from math import ceil, pi
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_EAR_Q = 9.26449  # Glasberg and Moore parameters
_MIN_BW = 24.7


def _erb_space(low_freq: float, high_freq: float, n: int) -> np.ndarray:
    """ERB-spaced centre frequencies, descending (Slaney ERBSpace)."""
    c = _EAR_Q * _MIN_BW
    return -c + np.exp(
        np.arange(1, n + 1) * (-np.log(high_freq + c) + np.log(low_freq + c)) / n
    ) * (high_freq + c)


@lru_cache(maxsize=100)
def _calc_erbs(low_freq: float, fs: int, n_filters: int) -> np.ndarray:
    """Equivalent rectangular bandwidths of the filterbank channels
    (reference ``srmr.py:38-47``)."""
    cfs = _erb_space(low_freq, fs / 2, n_filters)
    return (cfs / _EAR_Q) + _MIN_BW


@lru_cache(maxsize=100)
def _make_erb_filters(fs: int, num_freqs: int, cutoff: float) -> np.ndarray:
    """Slaney gammatone filter coefficients ``(N, 10)``:
    ``A0, A11, A12, A13, A14, A2, B0, B1, B2, gain`` (the published design
    ``gammatone.filters.make_erb_filters`` evaluates)."""
    cf = _erb_space(cutoff, fs / 2, num_freqs)
    t = 1.0 / fs
    erb = ((cf / _EAR_Q) ** 1 + _MIN_BW**1) ** 1
    b = 1.019 * 2 * np.pi * erb

    arg = 2 * cf * np.pi * t
    vec = np.exp(2j * arg)

    a0 = t
    a2 = 0.0
    b0 = 1.0
    b1 = -2 * np.cos(arg) / np.exp(b * t)
    b2 = np.exp(-2 * b * t)

    rt_pos = np.sqrt(3 + 2**1.5)
    rt_neg = np.sqrt(3 - 2**1.5)

    common = -t * np.exp(-(b * t))
    k11 = np.cos(arg) + rt_pos * np.sin(arg)
    k12 = np.cos(arg) - rt_pos * np.sin(arg)
    k13 = np.cos(arg) + rt_neg * np.sin(arg)
    k14 = np.cos(arg) - rt_neg * np.sin(arg)
    a11 = common * k11
    a12 = common * k12
    a13 = common * k13
    a14 = common * k14

    gain_arg = np.exp(1j * arg - b * t)
    gain = np.abs(
        (vec - gain_arg * k11)
        * (vec - gain_arg * k12)
        * (vec - gain_arg * k13)
        * (vec - gain_arg * k14)
        * (t / (-np.exp(-2 * b * t) - vec + (1 + vec) * np.exp(-b * t))) ** 4
    )

    n = len(cf)
    coefs = np.zeros((n, 10))
    coefs[:, 0] = a0
    coefs[:, 1] = a11
    coefs[:, 2] = a12
    coefs[:, 3] = a13
    coefs[:, 4] = a14
    coefs[:, 5] = a2
    coefs[:, 6] = b0
    coefs[:, 7] = b1
    coefs[:, 8] = b2
    coefs[:, 9] = gain
    return coefs


def _biquad(x: Array, b: Array, a: Array) -> Array:
    """IIR biquad along the last axis (transposed direct form II) as one
    ``lax.scan`` over time; ``b``/``a`` shape ``(..., 3)`` broadcasting over
    the leading axes of ``x``."""
    b = b / a[..., 0:1]
    a = a / a[..., 0:1]
    b0, b1, b2 = b[..., 0], b[..., 1], b[..., 2]
    a1, a2 = a[..., 1], a[..., 2]

    def step(carry, x_t):
        z1, z2 = carry
        y_t = b0 * x_t + z1
        z1_new = b1 * x_t - a1 * y_t + z2
        z2_new = b2 * x_t - a2 * y_t
        return (z1_new, z2_new), y_t

    x_t_first = jnp.moveaxis(x, -1, 0)  # (time, ...)
    zeros = jnp.zeros_like(x_t_first[0])
    _, y = jax.lax.scan(step, (zeros, zeros), x_t_first)
    return jnp.moveaxis(y, 0, -1)


def _erb_filterbank(wave: Array, coefs: np.ndarray) -> Array:
    """4-stage gammatone cascade (reference ``srmr.py:116-144``):
    ``wave (B, time)`` -> ``(B, N, time)``."""
    n = coefs.shape[0]
    x = jnp.broadcast_to(wave[:, None, :], (wave.shape[0], n, wave.shape[-1]))
    bs = jnp.asarray(coefs[:, 6:9], jnp.float32)  # B0 B1 B2 (the a-side here)
    gain = jnp.asarray(coefs[:, 9], jnp.float32)
    for idx in (1, 2, 3, 4):
        num = jnp.asarray(np.stack([coefs[:, 0], coefs[:, idx], coefs[:, 5]], axis=-1), jnp.float32)
        x = _biquad(x, num, bs)
    return x / gain[None, :, None]


def _hilbert_envelope(x: Array) -> Array:
    """|analytic signal| via FFT (reference ``srmr.py:91-113``)."""
    time = x.shape[-1]
    n = time if time % 16 == 0 else ceil(time / 16) * 16
    x_fft = jnp.fft.fft(x, n=n, axis=-1)
    h = np.zeros(n)
    if n % 2 == 0:
        h[0] = h[n // 2] = 1
        h[1 : n // 2] = 2
    else:
        h[0] = 1
        h[1 : (n + 1) // 2] = 2
    y = jnp.fft.ifft(x_fft * jnp.asarray(h), axis=-1)
    return jnp.abs(y[..., :time])


@lru_cache(maxsize=100)
def _modulation_filterbank_and_cutoffs(min_cf: float, max_cf: float, n: int, fs: float, q: int):
    """Second-order bandpass bank + 3 dB cutoffs (reference ``srmr.py:58-88``)."""
    spacing_factor = (max_cf / min_cf) ** (1.0 / (n - 1))
    cfs = min_cf * spacing_factor ** np.arange(n)
    w0 = 2 * pi * cfs / fs
    w0t = np.tan(w0 / 2)
    b0 = w0t / q
    b = np.stack([b0, np.zeros(n), -b0], axis=-1)
    a = np.stack([1 + b0 + w0t**2, 2 * w0t**2 - 2, 1 - b0 + w0t**2], axis=-1)
    lower = cfs - b0 * fs / (2 * pi)
    upper = cfs + b0 * fs / (2 * pi)
    return cfs, b, a, lower, upper


def _srmr_arg_validate(
    fs: int,
    n_cochlear_filters: int,
    low_freq: float,
    min_cf: float,
    max_cf: Optional[float],
    norm: bool,
    fast: bool,
) -> None:
    """Validate arguments (reference ``srmr.py:308-340``)."""
    if not (isinstance(fs, int) and fs > 0):
        raise ValueError(f"Expected argument `fs` to be a positive int, but got {fs}")
    if not (isinstance(n_cochlear_filters, int) and n_cochlear_filters > 0):
        raise ValueError(
            f"Expected argument `n_cochlear_filters` to be a positive int, but got {n_cochlear_filters}"
        )
    if not ((isinstance(low_freq, (float, int))) and low_freq > 0):
        raise ValueError(f"Expected argument `low_freq` to be a positive float, but got {low_freq}")
    if not ((isinstance(min_cf, (float, int))) and min_cf > 0):
        raise ValueError(f"Expected argument `min_cf` to be a positive float, but got {min_cf}")
    if max_cf is not None and not ((isinstance(max_cf, (float, int))) and max_cf > 0):
        raise ValueError(f"Expected argument `max_cf` to be a positive float, but got {max_cf}")
    if not isinstance(norm, bool):
        raise ValueError("Expected argument `norm` to be a bool value")
    if not isinstance(fast, bool):
        raise ValueError("Expected argument `fast` to be a bool value")


def speech_reverberation_modulation_energy_ratio(
    preds: Array,
    fs: int,
    n_cochlear_filters: int = 23,
    low_freq: float = 125,
    min_cf: float = 4,
    max_cf: Optional[float] = None,
    norm: bool = False,
    fast: bool = False,
) -> Array:
    """SRMR (reference ``srmr.py:177-305``; the ``fast`` gammatonegram path is
    not replicated — the exact filterbank runs fast enough on TPU)."""
    _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, fast)
    if fast:
        from torchmetrics_tpu.utilities.prints import rank_zero_warn

        rank_zero_warn(
            "`fast=True` is accepted for API parity but the exact ERB filterbank is used;"
            " values equal the fast=False result, not the reference's gammatonegram approximation.",
            UserWarning,
        )
    preds = jnp.asarray(preds)
    shape = preds.shape
    preds = preds.reshape(1, -1) if preds.ndim == 1 else preds.reshape(-1, shape[-1])
    num_batch, time = preds.shape
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        preds = preds.astype(jnp.float32) / jnp.iinfo(preds.dtype).max

    # normalize into [-1, 1] like the reference's lfilter precondition
    max_vals = jnp.abs(preds).max(axis=-1, keepdims=True)
    preds = preds / jnp.where(max_vals > 1, max_vals, 1.0)

    w_length_s, w_inc_s = 0.256, 0.064
    fcoefs = _make_erb_filters(fs, n_cochlear_filters, low_freq)
    gt_env = _hilbert_envelope(_erb_filterbank(preds, fcoefs))  # (B, N, time)
    mfs = float(fs)

    w_length = ceil(w_length_s * mfs)
    w_inc = ceil(w_inc_s * mfs)

    if max_cf is None:
        max_cf = 30 if norm else 128
    _, mf_b, mf_a, cutoffs_lower, _ = _modulation_filterbank_and_cutoffs(min_cf, max_cf, 8, mfs, 2)

    # modulation filterbank over envelopes: (B, N, 8, time)
    env8 = jnp.broadcast_to(gt_env[:, :, None, :], (*gt_env.shape[:2], 8, gt_env.shape[-1]))
    mod_out = _biquad(env8, jnp.asarray(mf_b, jnp.float32), jnp.asarray(mf_a, jnp.float32))

    num_frames = int(1 + (time - w_length) // w_inc) if time >= w_length else 1
    pad = max(ceil(time / w_inc) * w_inc - time, w_length - time)
    mod_out = jnp.pad(mod_out, ((0, 0), (0, 0), (0, 0), (0, pad)))
    # periodic Hamming window (torch.hamming_window default), matching the
    # reference's hamming_window(w_length + 1)[:-1]
    window = jnp.asarray(np.hamming(w_length + 2)[:w_length], jnp.float32)
    # windowed frame energy == strided correlation of mod_out² with window²:
    # Σ_j (frame[j]·w[j])² = Σ_j frame[j]²·w[j]² — no frames materialized
    b_, n_, m_, t_ = mod_out.shape
    sq = (mod_out ** 2).reshape(b_ * n_ * m_, 1, t_)
    kernel = (window ** 2).reshape(1, 1, w_length)
    energy = jax.lax.conv_general_dilated(
        sq, kernel, window_strides=(w_inc,), padding="VALID",
        dimension_numbers=("NCH", "OIH", "NCH"),
    ).reshape(b_, n_, m_, -1)[..., :num_frames]  # (B, N, 8, num_frames)

    if norm:
        peak = energy.mean(axis=1, keepdims=True).max(axis=2, keepdims=True).max(axis=3, keepdims=True)
        floor = peak * 10.0 ** (-30.0 / 10.0)
        energy = jnp.clip(energy, floor, peak)

    erbs = np.flipud(_calc_erbs(low_freq, fs, n_cochlear_filters))  # ascending

    avg_energy = energy.mean(axis=-1)  # (B, N, 8)
    total_energy = avg_energy.reshape(num_batch, -1).sum(axis=-1)
    ac_energy = avg_energy.sum(axis=2)  # (B, N)
    ac_perc = ac_energy * 100 / total_energy[:, None]
    ac_perc_cumsum = jnp.flip(ac_perc, -1).cumsum(-1)
    k90perc_idx = jnp.argmax((ac_perc_cumsum > 90).astype(jnp.int32), axis=-1)
    bw = jnp.asarray(erbs.copy())[k90perc_idx]  # (B,)

    cutoffs = jnp.asarray(cutoffs_lower)
    # K* per sample from the 90%-energy bandwidth vs modulation cutoffs
    # (reference _cal_srmr_score): count how many of cutoffs[4..7] are <= bw
    kstar = 4 + (cutoffs[4:8][None, :] <= bw[:, None]).sum(axis=-1)  # in 5..8
    if bool((np.asarray(kstar) < 5).any()):
        raise ValueError("Something wrong with the cutoffs compared to bw values.")
    low_e = avg_energy[:, :, :4].sum(axis=(1, 2))
    # high energy = sum over mod bands 4..kstar-1 (exclusive of kstar)
    band_idx = jnp.arange(8)
    high_mask = (band_idx[None, :] >= 4) & (band_idx[None, :] < kstar[:, None])
    high_e = (avg_energy.sum(axis=1) * high_mask).sum(axis=-1)
    score = low_e / high_e
    return score.reshape(*shape[:-1]) if len(shape) > 1 else score.reshape(())
