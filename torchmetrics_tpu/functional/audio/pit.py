# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Permutation invariant training (reference ``functional/audio/pit.py``).

TPU-first formulation: the pairwise metric matrix is built with two stacked
batched metric calls (vectorized over speaker pairs instead of the
reference's per-pair Python loop, ``pit.py:190-202``), and the exhaustive
permutation search is a static gather over the precomputed permutation table.
``scipy`` linear-sum-assignment remains available as a host path for large
speaker counts.
"""
from __future__ import annotations

from itertools import permutations
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_ps_dict: dict = {}


def _gen_permutations(spk_num: int) -> Array:
    """All speaker permutations, cached (reference ``pit.py:30-40``)."""
    if spk_num not in _ps_dict:
        _ps_dict[spk_num] = jnp.asarray(list(permutations(range(spk_num))), dtype=jnp.int32)
    return _ps_dict[spk_num]


def _find_best_perm_by_exhaustive_method(metric_mtx: Array, eval_func: str) -> Tuple[Array, Array]:
    """Best permutation by evaluating every permutation (reference ``pit.py:68-106``)."""
    batch_size, spk_num = metric_mtx.shape[:2]
    ps = _gen_permutations(spk_num)  # (perm_num, spk_num): ps[p, j] = pred index for target j
    # metric value of permutation p = mean_j metric_mtx[:, j, ps[p, j]]
    metric_of_ps = jnp.mean(metric_mtx[:, jnp.arange(spk_num)[None, :], ps], axis=-1)  # (B, perm_num)
    if eval_func == "max":
        best_indexes = jnp.argmax(metric_of_ps, axis=1)
        best_metric = jnp.max(metric_of_ps, axis=1)
    else:
        best_indexes = jnp.argmin(metric_of_ps, axis=1)
        best_metric = jnp.min(metric_of_ps, axis=1)
    best_perm = ps[best_indexes]
    return best_metric, best_perm


def _find_best_perm_by_linear_sum_assignment(metric_mtx: Array, eval_func: str) -> Tuple[Array, Array]:
    """Hungarian assignment on host (reference ``pit.py:43-65``)."""
    from scipy.optimize import linear_sum_assignment

    mmtx = np.asarray(metric_mtx)
    best_perm = jnp.asarray(
        np.array([linear_sum_assignment(pwm, eval_func == "max")[1] for pwm in mmtx]), jnp.int32
    )
    best_metric = jnp.mean(jnp.take_along_axis(metric_mtx, best_perm[:, :, None], axis=2)[..., 0], axis=-1)
    return best_metric, best_perm


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    mode: str = "speaker-wise",
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """PIT (reference ``pit.py:109-231``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if mode not in ["speaker-wise", "permutation-wise"]:
        raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    batch_size, spk_num = target.shape[0:2]

    if mode == "permutation-wise":
        perms = _gen_permutations(spk_num)  # (perm_num, spk_num)
        perm_num = perms.shape[0]
        ppreds = preds[:, perms.reshape(-1), ...].reshape(batch_size * perm_num, *preds.shape[1:])
        ptarget = jnp.repeat(target, perm_num, axis=0)
        metric_of_ps = metric_func(ppreds, ptarget, **kwargs)
        metric_of_ps = jnp.mean(metric_of_ps.reshape(batch_size, perm_num, -1), axis=-1)
        if eval_func == "max":
            best_indexes = jnp.argmax(metric_of_ps, axis=1)
            best_metric = jnp.max(metric_of_ps, axis=1)
        else:
            best_indexes = jnp.argmin(metric_of_ps, axis=1)
            best_metric = jnp.min(metric_of_ps, axis=1)
        return best_metric, perms[best_indexes]

    # speaker-wise: one batched metric call over all (target, pred) pairs
    rest = preds.shape[2:]
    preds_pairs = jnp.broadcast_to(preds[:, None, :, ...], (batch_size, spk_num, spk_num, *rest))
    target_pairs = jnp.broadcast_to(target[:, :, None, ...], (batch_size, spk_num, spk_num, *rest))
    metric_mtx = metric_func(
        preds_pairs.reshape(batch_size * spk_num * spk_num, *rest),
        target_pairs.reshape(batch_size * spk_num * spk_num, *rest),
        **kwargs,
    ).reshape(batch_size, spk_num, spk_num)

    try:
        import scipy.optimize  # noqa: F401

        has_scipy = True
    except ImportError:  # pragma: no cover
        has_scipy = False
    if spk_num < 3 or not has_scipy:
        return _find_best_perm_by_exhaustive_method(metric_mtx, eval_func)
    return _find_best_perm_by_linear_sum_assignment(metric_mtx, eval_func)


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Rearrange predictions by the best permutation (reference ``pit.py:234-252``)."""
    preds, perm = jnp.asarray(preds), jnp.asarray(perm)
    return jnp.take_along_axis(preds, perm.reshape(*perm.shape, *([1] * (preds.ndim - 2))), axis=1)
