# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Host-callback audio metrics: PESQ.

These wrap inherently host-native DSP/inference backends (the C ``pesq``
library and onnxruntime — reference ``functional/audio/{pesq,dnsmos}.py``)
behind a clean
``jax.pure_callback`` boundary so a jitted evaluation graph stays pure. Each
raises ``ModuleNotFoundError`` when its backend isn't installed, exactly like
the reference's import gates.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.imports import ModuleAvailableCache

Array = jax.Array

_PESQ_AVAILABLE = ModuleAvailableCache("pesq")


def _batch_callback(host_fn, preds: Array, target: Optional[Array], out_shape) -> Array:
    """Run a per-batch host function under ``jax.pure_callback``."""
    result_spec = jax.ShapeDtypeStruct(out_shape, jnp.float32)
    if target is None:
        return jax.pure_callback(host_fn, result_spec, preds)
    return jax.pure_callback(host_fn, result_spec, preds, target)


def perceptual_evaluation_speech_quality(
    preds: Array,
    target: Array,
    fs: int,
    mode: str,
    keep_same_device: bool = False,
    n_processes: int = 1,
) -> Array:
    """PESQ via the native ``pesq`` library on host (reference
    ``functional/audio/pesq.py:30-123``)."""
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that pesq is installed. Either install as `pip install torchmetrics[audio]`"
            " or `pip install pesq`."
        )
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    preds, target = jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)

    def host_fn(preds_np, target_np):
        import pesq as pesq_backend

        p = np.asarray(preds_np, np.float32).reshape(-1, preds_np.shape[-1])
        t = np.asarray(target_np, np.float32).reshape(-1, target_np.shape[-1])
        scores = [pesq_backend.pesq(fs, tt, pp, mode) for pp, tt in zip(p, t)]
        return np.asarray(scores, np.float32).reshape(preds_np.shape[:-1])

    return _batch_callback(host_fn, preds, target, preds.shape[:-1])
