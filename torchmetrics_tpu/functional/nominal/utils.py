# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Nominal-association helpers (reference ``src/torchmetrics/functional/nominal/utils.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _is_concrete
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[float]) -> None:
    """Validate NaN-handling args (reference ``:23-34``)."""
    if nan_strategy not in ("replace", "drop"):
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (float, int)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _compute_expected_freqs(confmat: Array) -> Array:
    """Outer product of margins / total (reference ``:37-40``)."""
    margin_sum_rows, margin_sum_cols = confmat.sum(axis=1), confmat.sum(axis=0)
    return jnp.einsum("r,c->rc", margin_sum_rows, margin_sum_cols) / confmat.sum()


def _compute_chi_squared(confmat: Array, bias_correction: bool) -> Array:
    """Chi-squared statistic with optional Yates correction (reference ``:43-57``)."""
    expected_freqs = _compute_expected_freqs(confmat)
    df = expected_freqs.size - sum(expected_freqs.shape) + expected_freqs.ndim - 1
    if df == 0:
        return jnp.asarray(0.0)
    if df == 1 and bias_correction:
        diff = expected_freqs - confmat
        direction = jnp.sign(diff)
        confmat = confmat + direction * jnp.minimum(0.5, jnp.abs(diff))
    return jnp.sum((confmat - expected_freqs) ** 2 / expected_freqs)


def _drop_empty_rows_and_cols(confmat: Array) -> Array:
    """Drop all-zero rows and columns (reference ``:60-77``). Host-side
    (concrete shapes) — used only at compute time."""
    confmat = confmat[confmat.sum(axis=1) != 0]
    return confmat[:, confmat.sum(axis=0) != 0]


def _compute_phi_squared_corrected(phi_squared: Array, num_rows: int, num_cols: int, confmat_sum: Array) -> Array:
    """Bias-corrected phi^2 (reference ``:80-90``)."""
    return jnp.maximum(jnp.asarray(0.0), phi_squared - ((num_rows - 1) * (num_cols - 1)) / (confmat_sum - 1))


def _compute_rows_and_cols_corrected(num_rows: int, num_cols: int, confmat_sum: Array) -> Tuple[Array, Array]:
    """Bias-corrected row/col counts (reference ``:93-96``)."""
    rows_corrected = num_rows - (num_rows - 1) ** 2 / (confmat_sum - 1)
    cols_corrected = num_cols - (num_cols - 1) ** 2 / (confmat_sum - 1)
    return rows_corrected, cols_corrected


def _compute_bias_corrected_values(
    phi_squared: Array, num_rows: int, num_cols: int, confmat_sum: Array
) -> Tuple[Array, Array, Array]:
    """All bias-corrected quantities (reference ``:99-104``)."""
    phi_squared_corrected = _compute_phi_squared_corrected(phi_squared, num_rows, num_cols, confmat_sum)
    rows_corrected, cols_corrected = _compute_rows_and_cols_corrected(num_rows, num_cols, confmat_sum)
    return phi_squared_corrected, rows_corrected, cols_corrected


def _handle_nan_in_data(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Tuple[Array, Array]:
    """Replace or drop NaNs (reference ``:107-140``)."""
    if nan_strategy == "replace":
        return jnp.nan_to_num(preds, nan=nan_replace_value), jnp.nan_to_num(target, nan=nan_replace_value)
    if jnp.issubdtype(preds.dtype, jnp.floating) or jnp.issubdtype(target.dtype, jnp.floating):
        rows_contain_nan = jnp.logical_or(jnp.isnan(preds.astype(jnp.float32)), jnp.isnan(target.astype(jnp.float32)))
        return preds[~rows_contain_nan], target[~rows_contain_nan]
    return preds, target


def _unable_to_use_bias_correction_warning(metric_name: str) -> None:
    """Warn about degenerate bias correction (reference ``:143-146``)."""
    rank_zero_warn(
        f"Unable to compute {metric_name} using bias correction. Please consider to set `bias_correction=False`."
    )


def _nominal_confmat(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Shared update: argmax 2D inputs, handle NaNs, bincount confusion matrix
    (the ``_<metric>_update`` body shared by every nominal metric).

    Labels must be ``0..num_classes-1`` — out-of-range values would be
    silently dropped by the bincount scatter, so they error loudly instead.
    """
    from torchmetrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update

    preds = jnp.argmax(preds, axis=1) if preds.ndim == 2 else preds
    target = jnp.argmax(target, axis=1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    if _is_concrete(preds) and _is_concrete(target):  # skip under jit/shard_map tracing
        max_label = int(jnp.maximum(jnp.max(preds), jnp.max(target)))  # metriclint: disable=ML002 -- guarded by _is_concrete: a tracer never reaches the coercion
        min_label = int(jnp.minimum(jnp.min(preds), jnp.min(target)))  # metriclint: disable=ML002 -- guarded by _is_concrete: a tracer never reaches the coercion
        if max_label >= num_classes or min_label < 0:
            raise ValueError(
                f"Detected label values in [{min_label}, {max_label}] but `num_classes`={num_classes}; nominal"
                " metrics expect labels in 0..num_classes-1. Relabel the data or pass a larger `num_classes`."
            )
    return _multiclass_confusion_matrix_update(preds.astype(jnp.int32), target.astype(jnp.int32), num_classes)


def _relabel_nominal(preds: Array, target: Array) -> Tuple[Array, Array, int]:
    """Map arbitrary categorical values onto ``0..K-1`` over the union of
    both variables' values (used by the top-level functionals, which derive
    ``num_classes`` from the data)."""
    vals = jnp.unique(jnp.concatenate([preds.reshape(-1), target.reshape(-1)]))
    return jnp.searchsorted(vals, preds), jnp.searchsorted(vals, target), int(vals.shape[0])
