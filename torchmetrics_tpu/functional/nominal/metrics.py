# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Nominal-association kernels: Cramer's V, Pearson's contingency coefficient,
Theil's U, Tschuprow's T, Fleiss kappa (reference
``src/torchmetrics/functional/nominal/{cramers,pearson,theils_u,tschuprows,fleiss_kappa}.py``)."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.nominal.utils import (
    _compute_bias_corrected_values,
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _handle_nan_in_data,
    _nominal_confmat,
    _nominal_input_validation,
    _relabel_nominal,
    _unable_to_use_bias_correction_warning,
)

def _prepare_nominal(preds, target, nan_strategy, nan_replace_value):
    """NaN-handle 1D label inputs, then remap the union of values onto
    ``0..K-1`` so arbitrary category ids never fall outside the confmat."""
    if preds.ndim == 2 or target.ndim == 2:
        num_classes = preds.shape[1] if preds.ndim == 2 else target.shape[1]
        preds = jnp.argmax(preds, axis=1) if preds.ndim == 2 else preds
        target = jnp.argmax(target, axis=1) if target.ndim == 2 else target
        return preds, target, num_classes
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    preds, target, num_classes = _relabel_nominal(preds, target)
    return preds, target, num_classes


Array = jax.Array


# ------------------------------------------------------------------ Cramer's V
def _cramers_v_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Confusion matrix for Cramer's V (reference ``cramers.py:33-58``)."""
    return _nominal_confmat(preds, target, num_classes, nan_strategy, nan_replace_value)


def _cramers_v_compute(confmat: Array, bias_correction: bool) -> Array:
    """Cramer's V from the confusion matrix (reference ``cramers.py:61-90``)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction)
    phi_squared = chi_squared / cm_sum
    num_rows, num_cols = confmat.shape
    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, num_rows, num_cols, cm_sum
        )
        if bool(jnp.minimum(rows_corrected, cols_corrected) == 1):  # metriclint: disable=ML002 -- data-dependent user warning: eager by design
            _unable_to_use_bias_correction_warning(metric_name="Cramer's V")
            return jnp.asarray(float("nan"))
        cramers_v_value = jnp.sqrt(phi_squared_corrected / jnp.minimum(rows_corrected - 1, cols_corrected - 1))
    else:
        cramers_v_value = jnp.sqrt(phi_squared / min(num_rows - 1, num_cols - 1))
    return jnp.clip(cramers_v_value, 0.0, 1.0)


def cramers_v(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Cramer's V statistic between two categorical variables (reference ``cramers.py:93-144``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _prepare_nominal(preds, target, nan_strategy, nan_replace_value)
    confmat = _cramers_v_update(preds, target, num_classes, nan_strategy, nan_replace_value)
    return _cramers_v_compute(confmat, bias_correction)


def cramers_v_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pairwise Cramer's V over matrix columns (reference ``cramers.py:147-189``)."""
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = jnp.ones((num_variables, num_variables), dtype=jnp.float32)
    for i in range(num_variables):
        for j in range(i + 1, num_variables):
            v = cramers_v(matrix[:, i], matrix[:, j], bias_correction, nan_strategy, nan_replace_value)
            out = out.at[i, j].set(v).at[j, i].set(v)
    return out


# ---------------------------------------------- Pearson contingency coefficient
def _pearsons_contingency_coefficient_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Confusion matrix (reference ``pearson.py:32-57``)."""
    return _nominal_confmat(preds, target, num_classes, nan_strategy, nan_replace_value)


def _pearsons_contingency_coefficient_compute(confmat: Array) -> Array:
    """Pearson = sqrt(phi^2 / (1 + phi^2)) (reference ``pearson.py:60-74``)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction=False)
    phi_squared = chi_squared / cm_sum
    value = jnp.sqrt(phi_squared / (1 + phi_squared))
    return jnp.clip(value, 0.0, 1.0)


def pearsons_contingency_coefficient(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pearson's contingency coefficient (reference ``pearson.py:77-131``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _prepare_nominal(preds, target, nan_strategy, nan_replace_value)
    confmat = _pearsons_contingency_coefficient_update(preds, target, num_classes, nan_strategy, nan_replace_value)
    return _pearsons_contingency_coefficient_compute(confmat)


def pearsons_contingency_coefficient_matrix(
    matrix: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pairwise Pearson contingency coefficients (reference ``pearson.py:134-174``)."""
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = jnp.ones((num_variables, num_variables), dtype=jnp.float32)
    for i in range(num_variables):
        for j in range(i + 1, num_variables):
            v = pearsons_contingency_coefficient(matrix[:, i], matrix[:, j], nan_strategy, nan_replace_value)
            out = out.at[i, j].set(v).at[j, i].set(v)
    return out


# ------------------------------------------------------------------- Theil's U
def _conditional_entropy_compute(confmat: Array) -> Array:
    """H(X|Y) from the confusion matrix (reference ``theils_u.py:24-44``)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    total_occurrences = confmat.sum()
    p_xy_m = confmat / total_occurrences
    p_y = confmat.sum(axis=1) / total_occurrences
    p_y_m = jnp.broadcast_to(p_y[:, None], p_xy_m.shape)
    terms = p_xy_m * jnp.log(jnp.where(p_xy_m > 0, p_y_m / jnp.where(p_xy_m > 0, p_xy_m, 1.0), 1.0))
    return jnp.where(p_xy_m > 0, terms, 0.0).sum()


def _theils_u_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Confusion matrix (reference ``theils_u.py:47-72``)."""
    return _nominal_confmat(preds, target, num_classes, nan_strategy, nan_replace_value)


def _theils_u_compute(confmat: Array) -> Array:
    """U = (H(X) - H(X|Y)) / H(X) (reference ``theils_u.py:75-96``)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    s_xy = _conditional_entropy_compute(confmat)
    total_occurrences = confmat.sum()
    p_x = confmat.sum(axis=0) / total_occurrences
    s_x = -jnp.sum(jnp.where(p_x > 0, p_x * jnp.log(jnp.where(p_x > 0, p_x, 1.0)), 0.0))
    # zero marginal entropy degenerates to 0.0; traced select keeps it jittable
    return jnp.where(s_x == 0, 0.0, (s_x - s_xy) / jnp.where(s_x == 0, 1.0, s_x))


def theils_u(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Theil's U statistic (uncertainty coefficient) (reference ``theils_u.py:99-141``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _prepare_nominal(preds, target, nan_strategy, nan_replace_value)
    confmat = _theils_u_update(preds, target, num_classes, nan_strategy, nan_replace_value)
    return _theils_u_compute(confmat)


def theils_u_matrix(
    matrix: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pairwise Theil's U (asymmetric) (reference ``theils_u.py:144-185``)."""
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = jnp.ones((num_variables, num_variables), dtype=jnp.float32)
    for i in range(num_variables):
        for j in range(num_variables):
            if i != j:
                out = out.at[i, j].set(theils_u(matrix[:, i], matrix[:, j], nan_strategy, nan_replace_value))
    return out


# ---------------------------------------------------------------- Tschuprow's T
def _tschuprows_t_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Confusion matrix (reference ``tschuprows.py:33-58``)."""
    return _nominal_confmat(preds, target, num_classes, nan_strategy, nan_replace_value)


def _tschuprows_t_compute(confmat: Array, bias_correction: bool) -> Array:
    """Tschuprow's T from the confusion matrix (reference ``tschuprows.py:61-92``)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction)
    phi_squared = chi_squared / cm_sum
    num_rows, num_cols = confmat.shape
    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, num_rows, num_cols, cm_sum
        )
        if bool(jnp.minimum(rows_corrected, cols_corrected) == 1):  # metriclint: disable=ML002 -- data-dependent user warning: eager by design
            _unable_to_use_bias_correction_warning(metric_name="Tschuprow's T")
            return jnp.asarray(float("nan"))
        value = jnp.sqrt(phi_squared_corrected / jnp.sqrt((rows_corrected - 1) * (cols_corrected - 1)))
    else:
        value = jnp.sqrt(phi_squared / jnp.sqrt(jnp.asarray((num_rows - 1) * (num_cols - 1), jnp.float32)))
    return jnp.clip(value, 0.0, 1.0)


def tschuprows_t(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Tschuprow's T statistic (reference ``tschuprows.py:95-146``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _prepare_nominal(preds, target, nan_strategy, nan_replace_value)
    confmat = _tschuprows_t_update(preds, target, num_classes, nan_strategy, nan_replace_value)
    return _tschuprows_t_compute(confmat, bias_correction)


def tschuprows_t_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pairwise Tschuprow's T (reference ``tschuprows.py:149-191``)."""
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = jnp.ones((num_variables, num_variables), dtype=jnp.float32)
    for i in range(num_variables):
        for j in range(i + 1, num_variables):
            v = tschuprows_t(matrix[:, i], matrix[:, j], bias_correction, nan_strategy, nan_replace_value)
            out = out.at[i, j].set(v).at[j, i].set(v)
    return out


# ---------------------------------------------------------------- Fleiss kappa
def _fleiss_kappa_update(ratings: Array, mode: str = "counts") -> Array:
    """Normalize ratings to a [n_samples, n_categories] counts matrix
    (reference ``fleiss_kappa.py:22-44``)."""
    if mode == "probs":
        if ratings.ndim != 3 or not jnp.issubdtype(ratings.dtype, jnp.floating):
            raise ValueError(
                "If argument ``mode`` is 'probs', ratings must have 3 dimensions with the format"
                " [n_samples, n_categories, n_raters] and be floating point."
            )
        n_categories = ratings.shape[1]
        rater_choice = jnp.argmax(ratings, axis=1)  # (n_samples, n_raters)
        one_hot = jax.nn.one_hot(rater_choice, n_categories, dtype=jnp.int32)  # (n_samples, n_raters, n_categories)
        return one_hot.sum(axis=1)
    if mode == "counts" and (ratings.ndim != 2 or jnp.issubdtype(ratings.dtype, jnp.floating)):
        raise ValueError(
            "If argument ``mode`` is `counts`, ratings must have 2 dimensions with the format"
            " [n_samples, n_categories] and be none floating point."
        )
    return ratings


def _fleiss_kappa_compute(counts: Array) -> Array:
    """Fleiss kappa from the counts matrix (reference ``fleiss_kappa.py:47-60``)."""
    total = counts.shape[0]
    num_raters = counts.sum(axis=1).max()
    p_i = counts.sum(axis=0) / (total * num_raters)
    p_j = ((counts**2).sum(axis=1) - num_raters) / (num_raters * (num_raters - 1))
    p_bar = p_j.mean()
    pe_bar = (p_i**2).sum()
    return (p_bar - pe_bar) / (1 - pe_bar + 1e-5)


def fleiss_kappa(ratings: Array, mode: str = "counts") -> Array:
    """Fleiss kappa inter-rater agreement (reference ``fleiss_kappa.py:63-103``)."""
    if mode not in ("counts", "probs"):
        raise ValueError("Argument ``mode`` must be one of ['counts', 'probs'].")
    ratings = jnp.asarray(ratings)
    counts = _fleiss_kappa_update(ratings, mode)
    return _fleiss_kappa_compute(counts)
