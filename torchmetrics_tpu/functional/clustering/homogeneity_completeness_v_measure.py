# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Homogeneity / completeness / V-measure (reference
``src/torchmetrics/functional/clustering/homogeneity_completeness_v_measure.py``)."""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.mutual_info_score import mutual_info_score
from torchmetrics_tpu.functional.clustering.utils import calculate_entropy, check_cluster_labels

Array = jax.Array


def _homogeneity_score_compute(preds: Array, target: Array) -> Tuple[Array, Array, Array, Array]:
    """homogeneity = MI / H(target) (reference ``:24-37``)."""
    check_cluster_labels(preds, target)
    if target.size == 0:
        zero = jnp.asarray(0.0)
        return zero, zero, zero, zero
    entropy_target = calculate_entropy(target)
    entropy_preds = calculate_entropy(preds)
    mutual_info = mutual_info_score(preds, target)
    homogeneity = jnp.where(entropy_target != 0, mutual_info / jnp.where(entropy_target != 0, entropy_target, 1.0), 1.0)
    return homogeneity, mutual_info, entropy_preds, entropy_target


def _completeness_score_compute(preds: Array, target: Array) -> Tuple[Array, Array]:
    """completeness = MI / H(preds) (reference ``:40-46``)."""
    homogeneity, mutual_info, entropy_preds, _ = _homogeneity_score_compute(preds, target)
    completeness = jnp.where(entropy_preds != 0, mutual_info / jnp.where(entropy_preds != 0, entropy_preds, 1.0), 1.0)
    return completeness, homogeneity


def homogeneity_score(preds: Array, target: Array) -> Array:
    """Homogeneity: each cluster contains only one class (reference ``:49-74``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    homogeneity, _, _, _ = _homogeneity_score_compute(preds, target)
    return homogeneity


def completeness_score(preds: Array, target: Array) -> Array:
    """Completeness: all members of a class are in one cluster (reference ``:77-102``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    completeness, _ = _completeness_score_compute(preds, target)
    return completeness


def v_measure_score(preds: Array, target: Array, beta: float = 1.0) -> Array:
    """Weighted harmonic mean of homogeneity and completeness (reference ``:105-135``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    completeness, homogeneity = _completeness_score_compute(preds, target)
    if bool(homogeneity + completeness == 0):
        return jnp.asarray(0.0)
    return (1 + beta) * homogeneity * completeness / (beta * homogeneity + completeness)
