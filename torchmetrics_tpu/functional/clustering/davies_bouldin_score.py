# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Davies-Bouldin score (reference ``src/torchmetrics/functional/clustering/davies_bouldin_score.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.utils import (
    _cluster_stats,
    _validate_intrinsic_cluster_data,
    _validate_intrinsic_labels_to_samples,
)

Array = jax.Array


def davies_bouldin_score(data: Array, labels: Array) -> Array:
    """Mean worst-case intra/inter cluster distance ratio (reference ``:22-66``)."""
    data, labels = jnp.asarray(data), jnp.asarray(labels)
    _validate_intrinsic_cluster_data(data, labels)
    inverse, counts, centroids = _cluster_stats(data, labels)
    num_labels = counts.shape[0]
    num_samples = data.shape[0]
    _validate_intrinsic_labels_to_samples(num_labels, num_samples)

    # per-cluster mean distance to centroid via one-hot segment mean
    dists = jnp.sqrt(((data - centroids[inverse]) ** 2).sum(axis=1))
    onehot = jax.nn.one_hot(inverse, num_labels, dtype=data.dtype)
    intra_dists = (onehot.T @ dists) / counts

    diff = centroids[:, None, :] - centroids[None, :, :]
    centroid_distances = jnp.sqrt((diff**2).sum(axis=-1))

    # degenerate clusterings (all-zero intra or inter distances) score 0; a
    # traced select instead of an early return keeps the whole kernel jittable
    degenerate = jnp.allclose(intra_dists, 0.0) | jnp.allclose(centroid_distances, 0.0)
    centroid_distances = jnp.where(centroid_distances == 0, jnp.inf, centroid_distances)
    combined_intra = intra_dists[None, :] + intra_dists[:, None]
    scores = (combined_intra / centroid_distances).max(axis=1)
    return jnp.where(degenerate, 0.0, scores.mean())
