# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Fowlkes-Mallows index (reference ``src/torchmetrics/functional/clustering/fowlkes_mallows_index.py``)."""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.utils import calculate_contingency_matrix, check_cluster_labels

Array = jax.Array


def _fowlkes_mallows_index_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Contingency matrix + sample count (reference ``fowlkes_mallows_index.py:22-37``)."""
    check_cluster_labels(preds, target)
    return calculate_contingency_matrix(preds, target), preds.shape[0]


def _fowlkes_mallows_index_compute(contingency: Array, n: int) -> Array:
    """FMI from the contingency matrix (reference ``:40-58``).

    Squared marginal sums overflow int32 past ~46k samples, so the terminal
    (non-jitted) reduction runs host-side in int64.
    """
    import numpy as np

    cont = np.asarray(contingency).astype(np.int64)
    tk = float((cont**2).sum() - n)
    if np.isclose(tk, 0):
        return jnp.asarray(0.0)
    pk = float((cont.sum(axis=0).astype(np.int64) ** 2).sum() - n)
    qk = float((cont.sum(axis=1).astype(np.int64) ** 2).sum() - n)
    return jnp.asarray(np.sqrt(tk / pk) * np.sqrt(tk / qk), dtype=jnp.float32)


def fowlkes_mallows_index(preds: Array, target: Array) -> Array:
    """Fowlkes-Mallows index between two clusterings (reference ``:61-84``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    contingency, n = _fowlkes_mallows_index_update(preds, target)
    return _fowlkes_mallows_index_compute(contingency, n)
