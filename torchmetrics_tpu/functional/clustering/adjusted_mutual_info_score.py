# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Adjusted mutual information (reference
``src/torchmetrics/functional/clustering/adjusted_mutual_info_score.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.mutual_info_score import (
    _mutual_info_score_compute,
    _mutual_info_score_update,
)
from torchmetrics_tpu.functional.clustering.utils import (
    _validate_average_method_arg,
    calculate_entropy,
    calculate_generalized_mean,
)

Array = jax.Array


def expected_mutual_info_score(contingency: Array, n_samples: int) -> Array:
    """Expected MI of two random clusterings with fixed marginals
    (reference ``:78-131``, sklearn's hypergeometric model).

    The reference's triple Python loop over (i, j, nij) becomes a dense
    masked grid evaluated per nij-chunk. This is terminal compute-time work
    on small (R, C) marginals, so it runs host-side in numpy float64 with the
    nij axis chunked to bound memory at ``R*C*chunk`` even when the largest
    cluster holds millions of samples.
    """
    import numpy as np
    from scipy.special import gammaln

    a = np.ravel(np.asarray(contingency).sum(axis=1)).astype(np.float64)
    b = np.ravel(np.asarray(contingency).sum(axis=0)).astype(np.float64)
    if a.shape[0] == 1 or b.shape[0] == 1:
        return jnp.asarray(0.0)

    n = float(n_samples)
    max_nij = int(min(a.max(), b.max()))
    log_a = np.log(a)[:, None, None]
    log_b = np.log(b)[None, :, None]
    gln_a = gammaln(a + 1)[:, None, None]
    gln_b = gammaln(b + 1)[None, :, None]
    gln_na = gammaln(n - a + 1)[:, None, None]
    gln_nb = gammaln(n - b + 1)[None, :, None]
    gln_n = gammaln(n + 1)
    aij = a[:, None, None]
    bij = b[None, :, None]

    emi = 0.0
    # bound temporaries to ~128 MB of float64 regardless of cluster counts
    chunk = max(1, (1 << 24) // (a.shape[0] * b.shape[0]))
    for lo in range(1, max_nij + 1, chunk):
        nij = np.arange(lo, min(lo + chunk, max_nij + 1), dtype=np.float64)[None, None, :]
        # valid hypergeometric support: max(1, a+b-n) <= nij <= min(a, b)
        start = np.maximum(1.0, aij + bij - n)
        end = np.minimum(aij, bij)
        valid = (nij >= start) & (nij <= end)
        nij_c = np.where(valid, nij, 1.0)  # clamp so lgamma args stay positive
        term1 = nij_c / n
        term2 = np.log(n) + np.log(nij_c) - log_a - log_b
        gln = (
            gln_a + gln_b + gln_na + gln_nb - (gammaln(nij_c + 1) + gln_n)
            - gammaln(aij - nij_c + 1)
            - gammaln(bij - nij_c + 1)
            - gammaln(n - aij - bij + nij_c + 1)
        )
        emi += float(np.where(valid, term1 * term2 * np.exp(gln), 0.0).sum())
    return jnp.asarray(emi, dtype=jnp.float32)


def adjusted_mutual_info_score(
    preds: Array, target: Array, average_method: str = "arithmetic"
) -> Array:
    """Adjusted mutual information (reference ``:24-75``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    _validate_average_method_arg(average_method)
    contingency = _mutual_info_score_update(preds, target)
    mutual_info = _mutual_info_score_compute(contingency)
    expected_mutual_info = expected_mutual_info_score(contingency, preds.size)
    normalizer = calculate_generalized_mean(
        jnp.stack([calculate_entropy(preds), calculate_entropy(target)]), average_method
    )
    denominator = normalizer - expected_mutual_info
    eps = jnp.finfo(jnp.float32).eps
    denominator = jnp.where(denominator < 0, jnp.minimum(denominator, -eps), jnp.maximum(denominator, eps))
    return (mutual_info - expected_mutual_info) / denominator
