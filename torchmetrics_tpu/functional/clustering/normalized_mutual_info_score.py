# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Normalized mutual information (reference
``src/torchmetrics/functional/clustering/normalized_mutual_info_score.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.mutual_info_score import mutual_info_score
from torchmetrics_tpu.functional.clustering.utils import (
    _validate_average_method_arg,
    calculate_entropy,
    calculate_generalized_mean,
    check_cluster_labels,
)

Array = jax.Array


def normalized_mutual_info_score(preds: Array, target: Array, average_method: str = "arithmetic") -> Array:
    """NMI = MI / gen_mean(H(preds), H(target)) (reference ``:24-66``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    check_cluster_labels(preds, target)
    _validate_average_method_arg(average_method)
    mutual_info = mutual_info_score(preds, target)
    # ~zero MI short-circuits to MI itself (normalizer may be 0 there); a
    # traced select instead of an early return keeps the kernel jittable
    degenerate = jnp.isclose(mutual_info, 0.0, atol=jnp.finfo(jnp.float32).eps)
    normalizer = calculate_generalized_mean(
        jnp.stack([calculate_entropy(preds), calculate_entropy(target)]), average_method
    )
    return jnp.where(degenerate, mutual_info, mutual_info / jnp.where(degenerate, 1.0, normalizer))
