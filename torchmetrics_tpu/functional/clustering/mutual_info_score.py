# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Mutual information score (reference ``src/torchmetrics/functional/clustering/mutual_info_score.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.utils import calculate_contingency_matrix, check_cluster_labels

Array = jax.Array


def _mutual_info_score_update(preds: Array, target: Array) -> Array:
    """Contingency matrix (reference ``mutual_info_score.py:24-38``)."""
    check_cluster_labels(preds, target)
    return calculate_contingency_matrix(preds, target)


def _mutual_info_score_compute(contingency: Array) -> Array:
    """MI from the contingency matrix (reference ``:41-64``).

    The reference gathers nonzero entries; here zero entries contribute 0 via
    masking — static shapes.
    """
    n = contingency.sum()
    u = contingency.sum(axis=1)
    v = contingency.sum(axis=0)
    if u.shape[0] == 1 or v.shape[0] == 1:
        return jnp.asarray(0.0)
    nz = contingency > 0
    log_outer = jnp.log(jnp.maximum(u, 1))[:, None] + jnp.log(jnp.maximum(v, 1))[None, :]
    terms = contingency / n * (jnp.log(n) + jnp.log(jnp.maximum(contingency, 1)) - log_outer)
    return jnp.where(nz, terms, 0.0).sum()


def mutual_info_score(preds: Array, target: Array) -> Array:
    """Mutual information between two clusterings (reference ``:67-93``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    contingency = _mutual_info_score_update(preds, target)
    return _mutual_info_score_compute(contingency)
