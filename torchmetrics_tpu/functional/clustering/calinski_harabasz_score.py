# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Calinski-Harabasz score (reference ``src/torchmetrics/functional/clustering/calinski_harabasz_score.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.utils import (
    _cluster_stats,
    _validate_intrinsic_cluster_data,
    _validate_intrinsic_labels_to_samples,
)

Array = jax.Array


def calinski_harabasz_score(data: Array, labels: Array) -> Array:
    """Between- vs within-cluster dispersion ratio (reference ``:22-62``).

    Per-cluster means/dispersions come from one-hot segment reductions rather
    than the reference's per-cluster boolean-index loop.
    """
    data, labels = jnp.asarray(data), jnp.asarray(labels)
    _validate_intrinsic_cluster_data(data, labels)
    inverse, counts, centroids = _cluster_stats(data, labels)
    num_labels = counts.shape[0]
    num_samples = data.shape[0]
    _validate_intrinsic_labels_to_samples(num_labels, num_samples)

    mean = data.mean(axis=0)
    between = (counts * ((centroids - mean[None, :]) ** 2).sum(axis=1)).sum()
    within = ((data - centroids[inverse]) ** 2).sum()
    # zero within-dispersion degenerates to 1.0; a traced select instead of an
    # early return keeps the kernel jittable
    safe_within = jnp.where(within == 0, 1.0, within)
    score = between * (num_samples - num_labels) / (safe_within * (num_labels - 1.0))
    return jnp.where(within == 0, 1.0, score)
