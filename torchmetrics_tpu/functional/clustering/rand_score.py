# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Rand score (reference ``src/torchmetrics/functional/clustering/rand_score.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.utils import (
    calculate_contingency_matrix,
    calculate_pair_cluster_confusion_matrix,
    check_cluster_labels,
)

Array = jax.Array


def _rand_score_update(preds: Array, target: Array) -> Array:
    """Contingency matrix (reference ``rand_score.py:22-36``)."""
    check_cluster_labels(preds, target)
    return calculate_contingency_matrix(preds, target)


def _rand_score_compute(contingency: Array) -> Array:
    """Rand score from the contingency matrix (reference ``:39-60``)."""
    import numpy as np

    pair_matrix = np.asarray(calculate_pair_cluster_confusion_matrix(contingency=contingency), dtype=np.float64)
    numerator = np.diagonal(pair_matrix).sum()
    denominator = pair_matrix.sum()
    if numerator == denominator or denominator == 0:
        # trivial clusterings are perfect matches (reference ``:52-56``)
        return jnp.asarray(1.0)
    return jnp.asarray(numerator / denominator, dtype=jnp.float32)


def rand_score(preds: Array, target: Array) -> Array:
    """Rand score between two clusterings (reference ``:63-89``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    contingency = _rand_score_update(preds, target)
    return _rand_score_compute(contingency)
