# Copyright The TorchMetrics-TPU contributors.
# Licensed under the Apache License, Version 2.0.
"""Adjusted Rand score (reference ``src/torchmetrics/functional/clustering/adjusted_rand_score.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.clustering.utils import (
    calculate_contingency_matrix,
    calculate_pair_cluster_confusion_matrix,
    check_cluster_labels,
)

Array = jax.Array


def _adjusted_rand_score_update(preds: Array, target: Array) -> Array:
    """Contingency matrix (reference ``adjusted_rand_score.py:22-36``)."""
    check_cluster_labels(preds, target)
    return calculate_contingency_matrix(preds, target)


def _adjusted_rand_score_compute(contingency: Array) -> Array:
    """ARI from the pair confusion matrix (reference ``:39-53``)."""
    import numpy as np

    pair_matrix = np.asarray(calculate_pair_cluster_confusion_matrix(contingency=contingency), dtype=np.float64)
    (tn, fp), (fn, tp) = pair_matrix[0], pair_matrix[1]
    if fn == 0 and fp == 0:
        return jnp.asarray(1.0)
    return jnp.asarray(
        2.0 * (tp * tn - fn * fp) / ((tp + fn) * (fn + tn) + (tp + fp) * (fp + tn)), dtype=jnp.float32
    )


def adjusted_rand_score(preds: Array, target: Array) -> Array:
    """Adjusted Rand score between two clusterings (reference ``:56-83``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    contingency = _adjusted_rand_score_update(preds, target)
    return _adjusted_rand_score_compute(contingency)
